"""TPC-DS connector: deterministic on-the-fly columnar data generation.

Reference: ``plugin/trino-tpcds`` — synthetic TPC-DS tables generated per
split (no storage). All 24 schema tables are present with spec row-count
scaling; column sets cover the keys, measures, and descriptive attributes
used by the TPC-DS query corpus (notably the BASELINE configs' Q64/Q95
families). Like the tpch connector, exact per-row values are our own
deterministic keyed-hash streams — the engine's oracle recomputes expected
results from the same generated data.

Referential structure honored:
  - fact foreign keys land in the matching dimension key ranges
  - returns are a deterministic ~10% subset of their sales table, sharing
    (item_sk, ticket/order number) so sales-returns joins behave like the
    spec's (Q64's ss/sr join, Q95's ws/wr order-number semijoin)
  - date_dim spans 1998-01-01..2003-12-31 with consistent d_year/d_moy/d_dom
"""

from __future__ import annotations

import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column, Dictionary
from trino_tpu.compiler import days_from_civil
from trino_tpu.connectors.api import ColumnSchema, Connector, Split, TableSchema

DEC = T.decimal(7, 2)

# === schemas (column subsets: keys + measures + hot descriptive attrs) =====

_SCHEMAS: dict[str, list[tuple[str, T.SqlType]]] = {
    "date_dim": [
        ("d_date_sk", T.BIGINT), ("d_date_id", T.VARCHAR), ("d_date", T.DATE),
        ("d_month_seq", T.BIGINT), ("d_week_seq", T.BIGINT),
        ("d_quarter_seq", T.BIGINT), ("d_year", T.BIGINT), ("d_dow", T.BIGINT),
        ("d_moy", T.BIGINT), ("d_dom", T.BIGINT), ("d_qoy", T.BIGINT),
        ("d_fy_year", T.BIGINT), ("d_day_name", T.VARCHAR),
        ("d_holiday", T.VARCHAR), ("d_weekend", T.VARCHAR),
    ],
    "time_dim": [
        ("t_time_sk", T.BIGINT), ("t_time_id", T.VARCHAR), ("t_time", T.BIGINT),
        ("t_hour", T.BIGINT), ("t_minute", T.BIGINT), ("t_second", T.BIGINT),
        ("t_am_pm", T.VARCHAR), ("t_shift", T.VARCHAR),
    ],
    "item": [
        ("i_item_sk", T.BIGINT), ("i_item_id", T.VARCHAR),
        ("i_item_desc", T.VARCHAR), ("i_current_price", DEC),
        ("i_wholesale_cost", DEC), ("i_brand_id", T.BIGINT),
        ("i_brand", T.VARCHAR), ("i_class_id", T.BIGINT),
        ("i_class", T.VARCHAR), ("i_category_id", T.BIGINT),
        ("i_category", T.VARCHAR), ("i_manufact_id", T.BIGINT),
        ("i_manufact", T.VARCHAR), ("i_size", T.VARCHAR),
        ("i_color", T.VARCHAR), ("i_units", T.VARCHAR),
        ("i_product_name", T.VARCHAR),
    ],
    "customer": [
        ("c_customer_sk", T.BIGINT), ("c_customer_id", T.VARCHAR),
        ("c_current_cdemo_sk", T.BIGINT), ("c_current_hdemo_sk", T.BIGINT),
        ("c_current_addr_sk", T.BIGINT), ("c_first_shipto_date_sk", T.BIGINT),
        ("c_first_sales_date_sk", T.BIGINT), ("c_first_name", T.VARCHAR),
        ("c_last_name", T.VARCHAR), ("c_birth_year", T.BIGINT),
        ("c_birth_country", T.VARCHAR), ("c_email_address", T.VARCHAR),
    ],
    "customer_address": [
        ("ca_address_sk", T.BIGINT), ("ca_address_id", T.VARCHAR),
        ("ca_street_number", T.VARCHAR), ("ca_street_name", T.VARCHAR),
        ("ca_city", T.VARCHAR), ("ca_county", T.VARCHAR),
        ("ca_state", T.VARCHAR), ("ca_zip", T.VARCHAR),
        ("ca_country", T.VARCHAR), ("ca_gmt_offset", DEC),
        ("ca_location_type", T.VARCHAR),
    ],
    "customer_demographics": [
        ("cd_demo_sk", T.BIGINT), ("cd_gender", T.VARCHAR),
        ("cd_marital_status", T.VARCHAR), ("cd_education_status", T.VARCHAR),
        ("cd_purchase_estimate", T.BIGINT), ("cd_credit_rating", T.VARCHAR),
        ("cd_dep_count", T.BIGINT),
    ],
    "household_demographics": [
        ("hd_demo_sk", T.BIGINT), ("hd_income_band_sk", T.BIGINT),
        ("hd_buy_potential", T.VARCHAR), ("hd_dep_count", T.BIGINT),
        ("hd_vehicle_count", T.BIGINT),
    ],
    "income_band": [
        ("ib_income_band_sk", T.BIGINT), ("ib_lower_bound", T.BIGINT),
        ("ib_upper_bound", T.BIGINT),
    ],
    "store": [
        ("s_store_sk", T.BIGINT), ("s_store_id", T.VARCHAR),
        ("s_store_name", T.VARCHAR), ("s_number_employees", T.BIGINT),
        ("s_floor_space", T.BIGINT), ("s_hours", T.VARCHAR),
        ("s_manager", T.VARCHAR), ("s_market_id", T.BIGINT),
        ("s_city", T.VARCHAR), ("s_county", T.VARCHAR),
        ("s_state", T.VARCHAR), ("s_zip", T.VARCHAR),
    ],
    "warehouse": [
        ("w_warehouse_sk", T.BIGINT), ("w_warehouse_id", T.VARCHAR),
        ("w_warehouse_name", T.VARCHAR), ("w_warehouse_sq_ft", T.BIGINT),
        ("w_city", T.VARCHAR), ("w_state", T.VARCHAR),
        ("w_country", T.VARCHAR),
    ],
    "ship_mode": [
        ("sm_ship_mode_sk", T.BIGINT), ("sm_ship_mode_id", T.VARCHAR),
        ("sm_type", T.VARCHAR), ("sm_code", T.VARCHAR),
        ("sm_carrier", T.VARCHAR),
    ],
    "reason": [
        ("r_reason_sk", T.BIGINT), ("r_reason_id", T.VARCHAR),
        ("r_reason_desc", T.VARCHAR),
    ],
    "promotion": [
        ("p_promo_sk", T.BIGINT), ("p_promo_id", T.VARCHAR),
        ("p_start_date_sk", T.BIGINT), ("p_end_date_sk", T.BIGINT),
        ("p_item_sk", T.BIGINT), ("p_cost", DEC),
        ("p_channel_dmail", T.VARCHAR), ("p_channel_email", T.VARCHAR),
        ("p_channel_tv", T.VARCHAR), ("p_promo_name", T.VARCHAR),
    ],
    "web_site": [
        ("web_site_sk", T.BIGINT), ("web_site_id", T.VARCHAR),
        ("web_name", T.VARCHAR), ("web_manager", T.VARCHAR),
        ("web_company_name", T.VARCHAR), ("web_state", T.VARCHAR),
    ],
    "web_page": [
        ("wp_web_page_sk", T.BIGINT), ("wp_web_page_id", T.VARCHAR),
        ("wp_url", T.VARCHAR), ("wp_type", T.VARCHAR),
        ("wp_char_count", T.BIGINT), ("wp_link_count", T.BIGINT),
    ],
    "call_center": [
        ("cc_call_center_sk", T.BIGINT), ("cc_call_center_id", T.VARCHAR),
        ("cc_name", T.VARCHAR), ("cc_class", T.VARCHAR),
        ("cc_employees", T.BIGINT), ("cc_manager", T.VARCHAR),
        ("cc_county", T.VARCHAR), ("cc_state", T.VARCHAR),
    ],
    "catalog_page": [
        ("cp_catalog_page_sk", T.BIGINT), ("cp_catalog_page_id", T.VARCHAR),
        ("cp_department", T.VARCHAR), ("cp_catalog_number", T.BIGINT),
        ("cp_catalog_page_number", T.BIGINT), ("cp_type", T.VARCHAR),
    ],
    "inventory": [
        ("inv_date_sk", T.BIGINT), ("inv_item_sk", T.BIGINT),
        ("inv_warehouse_sk", T.BIGINT), ("inv_quantity_on_hand", T.BIGINT),
    ],
    "store_sales": [
        ("ss_sold_date_sk", T.BIGINT), ("ss_sold_time_sk", T.BIGINT),
        ("ss_item_sk", T.BIGINT), ("ss_customer_sk", T.BIGINT),
        ("ss_cdemo_sk", T.BIGINT), ("ss_hdemo_sk", T.BIGINT),
        ("ss_addr_sk", T.BIGINT), ("ss_store_sk", T.BIGINT),
        ("ss_promo_sk", T.BIGINT), ("ss_ticket_number", T.BIGINT),
        ("ss_quantity", T.BIGINT), ("ss_wholesale_cost", DEC),
        ("ss_list_price", DEC), ("ss_sales_price", DEC),
        ("ss_ext_discount_amt", DEC), ("ss_ext_sales_price", DEC),
        ("ss_ext_wholesale_cost", DEC), ("ss_ext_list_price", DEC),
        ("ss_ext_tax", DEC), ("ss_coupon_amt", DEC),
        ("ss_net_paid", DEC), ("ss_net_paid_inc_tax", DEC),
        ("ss_net_profit", DEC),
    ],
    "store_returns": [
        ("sr_returned_date_sk", T.BIGINT), ("sr_return_time_sk", T.BIGINT),
        ("sr_item_sk", T.BIGINT), ("sr_customer_sk", T.BIGINT),
        ("sr_cdemo_sk", T.BIGINT), ("sr_hdemo_sk", T.BIGINT),
        ("sr_addr_sk", T.BIGINT), ("sr_store_sk", T.BIGINT),
        ("sr_reason_sk", T.BIGINT), ("sr_ticket_number", T.BIGINT),
        ("sr_return_quantity", T.BIGINT), ("sr_return_amt", DEC),
        ("sr_return_tax", DEC), ("sr_return_amt_inc_tax", DEC),
        ("sr_fee", DEC), ("sr_return_ship_cost", DEC),
        ("sr_refunded_cash", DEC), ("sr_reversed_charge", DEC),
        ("sr_store_credit", DEC), ("sr_net_loss", DEC),
    ],
    "catalog_sales": [
        ("cs_sold_date_sk", T.BIGINT), ("cs_sold_time_sk", T.BIGINT),
        ("cs_ship_date_sk", T.BIGINT), ("cs_bill_customer_sk", T.BIGINT),
        ("cs_bill_cdemo_sk", T.BIGINT), ("cs_bill_hdemo_sk", T.BIGINT),
        ("cs_bill_addr_sk", T.BIGINT), ("cs_ship_customer_sk", T.BIGINT),
        ("cs_ship_addr_sk", T.BIGINT), ("cs_call_center_sk", T.BIGINT),
        ("cs_catalog_page_sk", T.BIGINT), ("cs_ship_mode_sk", T.BIGINT),
        ("cs_warehouse_sk", T.BIGINT), ("cs_item_sk", T.BIGINT),
        ("cs_promo_sk", T.BIGINT), ("cs_order_number", T.BIGINT),
        ("cs_quantity", T.BIGINT), ("cs_wholesale_cost", DEC),
        ("cs_list_price", DEC), ("cs_sales_price", DEC),
        ("cs_ext_discount_amt", DEC), ("cs_ext_sales_price", DEC),
        ("cs_ext_wholesale_cost", DEC), ("cs_ext_list_price", DEC),
        ("cs_ext_tax", DEC), ("cs_coupon_amt", DEC),
        ("cs_ext_ship_cost", DEC), ("cs_net_paid", DEC),
        ("cs_net_paid_inc_tax", DEC), ("cs_net_paid_inc_ship", DEC),
        ("cs_net_paid_inc_ship_tax", DEC), ("cs_net_profit", DEC),
    ],
    "catalog_returns": [
        ("cr_returned_date_sk", T.BIGINT), ("cr_returned_time_sk", T.BIGINT),
        ("cr_item_sk", T.BIGINT), ("cr_refunded_customer_sk", T.BIGINT),
        ("cr_refunded_addr_sk", T.BIGINT),
        ("cr_returning_customer_sk", T.BIGINT),
        ("cr_call_center_sk", T.BIGINT), ("cr_catalog_page_sk", T.BIGINT),
        ("cr_ship_mode_sk", T.BIGINT), ("cr_warehouse_sk", T.BIGINT),
        ("cr_reason_sk", T.BIGINT), ("cr_order_number", T.BIGINT),
        ("cr_return_quantity", T.BIGINT), ("cr_return_amount", DEC),
        ("cr_return_tax", DEC), ("cr_return_amt_inc_tax", DEC),
        ("cr_fee", DEC), ("cr_return_ship_cost", DEC),
        ("cr_refunded_cash", DEC), ("cr_reversed_charge", DEC),
        ("cr_store_credit", DEC), ("cr_net_loss", DEC),
    ],
    "web_sales": [
        ("ws_sold_date_sk", T.BIGINT), ("ws_sold_time_sk", T.BIGINT),
        ("ws_ship_date_sk", T.BIGINT), ("ws_item_sk", T.BIGINT),
        ("ws_bill_customer_sk", T.BIGINT), ("ws_bill_cdemo_sk", T.BIGINT),
        ("ws_bill_hdemo_sk", T.BIGINT), ("ws_bill_addr_sk", T.BIGINT),
        ("ws_ship_customer_sk", T.BIGINT), ("ws_ship_addr_sk", T.BIGINT),
        ("ws_web_page_sk", T.BIGINT), ("ws_web_site_sk", T.BIGINT),
        ("ws_ship_mode_sk", T.BIGINT), ("ws_warehouse_sk", T.BIGINT),
        ("ws_promo_sk", T.BIGINT), ("ws_order_number", T.BIGINT),
        ("ws_quantity", T.BIGINT), ("ws_wholesale_cost", DEC),
        ("ws_list_price", DEC), ("ws_sales_price", DEC),
        ("ws_ext_discount_amt", DEC), ("ws_ext_sales_price", DEC),
        ("ws_ext_wholesale_cost", DEC), ("ws_ext_list_price", DEC),
        ("ws_ext_tax", DEC), ("ws_coupon_amt", DEC),
        ("ws_ext_ship_cost", DEC), ("ws_net_paid", DEC),
        ("ws_net_paid_inc_tax", DEC), ("ws_net_paid_inc_ship", DEC),
        ("ws_net_paid_inc_ship_tax", DEC), ("ws_net_profit", DEC),
    ],
    "web_returns": [
        ("wr_returned_date_sk", T.BIGINT), ("wr_returned_time_sk", T.BIGINT),
        ("wr_item_sk", T.BIGINT), ("wr_refunded_customer_sk", T.BIGINT),
        ("wr_refunded_addr_sk", T.BIGINT),
        ("wr_returning_customer_sk", T.BIGINT),
        ("wr_web_page_sk", T.BIGINT), ("wr_reason_sk", T.BIGINT),
        ("wr_order_number", T.BIGINT), ("wr_return_quantity", T.BIGINT),
        ("wr_return_amt", DEC), ("wr_return_tax", DEC),
        ("wr_return_amt_inc_tax", DEC), ("wr_fee", DEC),
        ("wr_return_ship_cost", DEC), ("wr_refunded_cash", DEC),
        ("wr_reversed_charge", DEC), ("wr_account_credit", DEC),
        ("wr_net_loss", DEC),
    ],
}

_DATE_LO = days_from_civil(1998, 1, 1)
_DATE_HI = days_from_civil(2003, 12, 31)
_N_DATES = _DATE_HI - _DATE_LO + 1  # 2191
_DATE_SK0 = 2450815  # spec-style julian base for d_date_sk

_CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
               "Men", "Music", "Shoes", "Sports", "Women"]
_CLASSES = [f"class{i:02d}" for i in range(1, 17)]
_COLORS = ["red", "blue", "green", "yellow", "black", "white", "purple",
           "orange", "brown", "pink", "cyan", "magenta", "ivory", "gold"]
_STATES = ["AL", "CA", "GA", "IL", "KS", "MI", "NY", "OH", "TX", "WA"]
_COUNTIES = [f"{s} County {i}" for s in _STATES[:5] for i in range(1, 6)]
_BUY_POTENTIAL = ["0-500", "501-1000", "1001-5000", "5001-10000", ">10000", "Unknown"]
_EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
              "Advanced Degree", "Unknown"]
_CREDIT = ["Low Risk", "Good", "High Risk", "Unknown"]
_DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday", "Friday", "Saturday"]


def scale_factor(schema: str) -> float:
    if schema == "tiny":
        return 0.01
    if schema.startswith("sf"):
        return float(schema[2:])
    raise KeyError(f"unknown tpcds schema: {schema}")


def _counts(sf: float) -> dict[str, int]:
    """Row counts (spec-shaped scaling; dims have floors)."""

    def s(base: int, floor: int = 1) -> int:
        return max(floor, int(base * sf))

    return {
        "date_dim": _N_DATES,  # fixed span (spec: 73049 covering 1900-2100)
        "time_dim": 86400 if sf >= 1 else 8640,
        "item": s(18_000, 100),
        "customer": s(100_000, 1000),
        "customer_address": s(50_000, 500),
        "customer_demographics": 19_208 if sf >= 0.1 else 1920,
        "household_demographics": 7200 if sf >= 0.1 else 720,
        "income_band": 20,
        "store": s(12, 2),
        "warehouse": s(5, 1),
        "ship_mode": 20,
        "reason": s(35, 5),
        "promotion": s(300, 10),
        "web_site": s(30, 2),
        "web_page": s(60, 4),
        "call_center": s(6, 2),
        "catalog_page": s(11_718, 100),
        "store_sales": s(2_880_404, 5000),
        "catalog_sales": s(1_441_548, 2500),
        "web_sales": s(719_384, 1200),
        "inventory": s(11_745_000, 10_000),
    }


class TpcdsConnector(Connector):
    name = "tpcds"

    def __init__(self, split_rows: int = 1 << 20):
        from trino_tpu.connectors.diskcache import DbgenDiskCache

        self.split_rows = split_rows
        self._dict_cache: dict[tuple, Dictionary] = {}
        # cross-process split cache (connectors/diskcache.py): generation
        # is deterministic per (schema, table, split), so cold processes
        # read back previous runs' bytes instead of regenerating
        self._disk_cache = DbgenDiskCache()

    # --- metadata --------------------------------------------------------

    def list_schemas(self):
        return ["tiny", "sf1", "sf10", "sf100"]

    def list_tables(self, schema):
        return sorted(_SCHEMAS)

    def get_table(self, schema, table):
        cols = _SCHEMAS.get(table)
        if cols is None:
            return None
        return TableSchema(table, tuple(ColumnSchema(n, t) for n, t in cols))

    def estimate_rows(self, schema, table):
        sf = scale_factor(schema)
        c = _counts(sf)
        if table in ("store_returns", "catalog_returns", "web_returns"):
            base = {"store_returns": "store_sales",
                    "catalog_returns": "catalog_sales",
                    "web_returns": "web_sales"}[table]
            return c[base] // 10
        return c[table]

    def table_stats(self, schema, table):
        from trino_tpu.connectors.api import ColumnStats, TableStats

        rows = float(self.estimate_rows(schema, table))
        cols: dict[str, ColumnStats] = {}
        key = _PRIMARY_SK.get(table)
        if key is not None:
            cols[key] = ColumnStats(rows, 0.0, 1, int(rows))
        return TableStats(row_count=rows, columns=cols)

    # --- splits ----------------------------------------------------------

    def get_splits(self, schema, table, target_splits, constraint=None):
        rows = self.estimate_rows(schema, table)
        n = max(1, min(target_splits, (rows + self.split_rows - 1) // self.split_rows))
        splits = [Split(table, i, n) for i in range(n)]
        return self.prune_splits(schema, table, splits, constraint)

    def split_stats(self, schema, table, split):
        key = _PRIMARY_SK.get(table)
        if key is None:
            return None
        rows = self.estimate_rows(schema, table)
        lo, hi = _range(rows, split.index, split.total)
        if hi <= lo:
            return {key: (None, None, False)}
        return {key: (lo + 1, hi, False)}

    # --- generation ------------------------------------------------------

    def read_split(self, schema, table, columns, split):
        key = (
            "tpcds", schema, table, tuple(columns), split.index, split.total
        )
        batch = self._disk_cache.get(key)
        if batch is not None:
            return batch
        sf = scale_factor(schema)
        gen = getattr(self, f"_gen_{table}")
        cols = gen(sf, split.index, split.total)
        out = [cols[c] for c in columns]
        n = out[0].data.shape[0] if out else 0
        batch = Batch(out, n)
        self._disk_cache.put(key, batch)
        return batch

    def _rng(self, table: str, index: int) -> np.random.Generator:
        return np.random.default_rng(_stable_seed("tpcds", table, index))

    def _dict(self, name: str, values: list[str]) -> Dictionary:
        # key on the VALUES, not just the name: distinct columns may reuse a
        # label and must not poison each other's cached dictionary
        key = (name, tuple(values))
        if key not in self._dict_cache:
            self._dict_cache[key] = Dictionary(values)
        return self._dict_cache[key]

    def _dcol(self, name: str, values: list[str], codes: np.ndarray) -> Column:
        return Column(T.VARCHAR, codes.astype(np.int32), None,
                      self._dict(name, values))

    def _ids(self, prefix: str, keys: np.ndarray, width: int = 16) -> Column:
        # unique id strings derived from keys; dictionary is per-split
        vals = [f"{prefix}{k:0{width}d}" for k in keys.tolist()]
        d, codes = Dictionary.from_strings(vals)
        return Column(T.VARCHAR, codes, None, d)

    # --- dimensions -------------------------------------------------------

    def _gen_date_dim(self, sf, index, total):
        n = _counts(sf)["date_dim"]
        lo, hi = _range(n, index, total)
        days = np.arange(lo, hi, dtype=np.int64)
        dates = (_DATE_LO + days).astype(np.int32)
        sk = _DATE_SK0 + days
        # civil fields via numpy datetime
        dt = dates.astype("datetime64[D]")
        Y = dt.astype("datetime64[Y]").astype(np.int64) + 1970
        month_idx = dt.astype("datetime64[M]").astype(np.int64)
        moy = month_idx % 12 + 1
        dom = (dt - dt.astype("datetime64[M]")).astype(np.int64) + 1
        dow = (days + (_DATE_LO + 4)) % 7  # 1970-01-01 was a Thursday
        weekend = np.isin(dow, [0, 6])
        return {
            "d_date_sk": Column(T.BIGINT, sk),
            "d_date_id": self._ids("D", sk),
            "d_date": Column(T.DATE, dates),
            "d_month_seq": Column(T.BIGINT, month_idx),
            "d_week_seq": Column(T.BIGINT, (_DATE_LO + days) // 7),
            "d_quarter_seq": Column(T.BIGINT, month_idx // 3),
            "d_year": Column(T.BIGINT, Y),
            "d_dow": Column(T.BIGINT, dow),
            "d_moy": Column(T.BIGINT, moy),
            "d_dom": Column(T.BIGINT, dom),
            "d_qoy": Column(T.BIGINT, (moy - 1) // 3 + 1),
            "d_fy_year": Column(T.BIGINT, Y),
            "d_day_name": self._dcol("d_day_name", _DAY_NAMES, dow),
            "d_holiday": self._dcol("yn", ["N", "Y"], (sk % 37 == 0).astype(np.int32)),
            "d_weekend": self._dcol("yn", ["N", "Y"], weekend.astype(np.int32)),
        }

    def _gen_time_dim(self, sf, index, total):
        n = _counts(sf)["time_dim"]
        lo, hi = _range(n, index, total)
        t = np.arange(lo, hi, dtype=np.int64) * (86400 // n)
        hour = t // 3600
        return {
            "t_time_sk": Column(T.BIGINT, np.arange(lo + 1, hi + 1, dtype=np.int64)),
            "t_time_id": self._ids("T", np.arange(lo + 1, hi + 1, dtype=np.int64)),
            "t_time": Column(T.BIGINT, t),
            "t_hour": Column(T.BIGINT, hour),
            "t_minute": Column(T.BIGINT, (t % 3600) // 60),
            "t_second": Column(T.BIGINT, t % 60),
            "t_am_pm": self._dcol("ampm", ["AM", "PM"], (hour >= 12).astype(np.int32)),
            "t_shift": self._dcol("shift", ["first", "second", "third"],
                                  (hour // 8).astype(np.int32) % 3),
        }

    def _gen_item(self, sf, index, total):
        n = _counts(sf)["item"]
        lo, hi = _range(n, index, total)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        rng = self._rng("item", index)
        m = hi - lo
        cat = (keys * 7) % len(_CATEGORIES)
        cls = (keys * 11) % len(_CLASSES)
        price = rng.integers(99, 30000, m)
        brand_id = (keys * 13) % 1000 + 1
        return {
            "i_item_sk": Column(T.BIGINT, keys),
            "i_item_id": self._ids("I", keys),
            "i_item_desc": self._dcol(
                "i_desc", [f"item description {i}" for i in range(256)],
                (keys % 256).astype(np.int32)),
            "i_current_price": Column(DEC, price),
            "i_wholesale_cost": Column(DEC, (price * 6) // 10),
            "i_brand_id": Column(T.BIGINT, brand_id),
            "i_brand": self._dcol(
                "i_brand", [f"Brand#{i}" for i in range(1, 101)],
                (brand_id % 100).astype(np.int32)),
            "i_class_id": Column(T.BIGINT, cls + 1),
            "i_class": self._dcol("i_class", _CLASSES, cls),
            "i_category_id": Column(T.BIGINT, cat + 1),
            "i_category": self._dcol("i_cat", _CATEGORIES, cat),
            "i_manufact_id": Column(T.BIGINT, (keys * 17) % 1000 + 1),
            "i_manufact": self._dcol(
                "i_manu", [f"manufact{i}" for i in range(100)],
                ((keys * 17) % 100).astype(np.int32)),
            "i_size": self._dcol(
                "i_size", ["small", "medium", "large", "extra large", "N/A"],
                (keys % 5).astype(np.int32)),
            "i_color": self._dcol("i_color", _COLORS,
                                  ((keys * 19) % len(_COLORS)).astype(np.int32)),
            "i_units": self._dcol(
                "i_units", ["Each", "Dozen", "Case", "Pallet"],
                (keys % 4).astype(np.int32)),
            "i_product_name": self._ids("P", keys, 12),
        }

    def _gen_customer(self, sf, index, total):
        c = _counts(sf)
        n = c["customer"]
        lo, hi = _range(n, index, total)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        rng = self._rng("customer", index)
        m = hi - lo
        first_sale = _DATE_SK0 + rng.integers(0, _N_DATES, m)
        return {
            "c_customer_sk": Column(T.BIGINT, keys),
            "c_customer_id": self._ids("C", keys),
            "c_current_cdemo_sk": Column(
                T.BIGINT, rng.integers(1, c["customer_demographics"] + 1, m)),
            "c_current_hdemo_sk": Column(
                T.BIGINT, rng.integers(1, c["household_demographics"] + 1, m)),
            "c_current_addr_sk": Column(
                T.BIGINT, rng.integers(1, c["customer_address"] + 1, m)),
            "c_first_shipto_date_sk": Column(T.BIGINT, first_sale + 30),
            "c_first_sales_date_sk": Column(T.BIGINT, first_sale),
            "c_first_name": self._dcol(
                "fname", [f"First{i}" for i in range(512)],
                (keys % 512).astype(np.int32)),
            "c_last_name": self._dcol(
                "lname", [f"Last{i}" for i in range(1024)],
                ((keys * 3) % 1024).astype(np.int32)),
            "c_birth_year": Column(T.BIGINT, 1930 + (keys % 63)),
            "c_birth_country": self._dcol(
                "country", [f"COUNTRY_{i}" for i in range(50)],
                ((keys * 7) % 50).astype(np.int32)),
            "c_email_address": self._ids("E", keys, 10),
        }

    def _gen_customer_address(self, sf, index, total):
        n = _counts(sf)["customer_address"]
        lo, hi = _range(n, index, total)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        rng = self._rng("customer_address", index)
        m = hi - lo
        state = (keys * 3) % len(_STATES)
        return {
            "ca_address_sk": Column(T.BIGINT, keys),
            "ca_address_id": self._ids("A", keys),
            "ca_street_number": self._dcol(
                "st_no", [str(i) for i in range(1, 1001)],
                (keys % 1000).astype(np.int32)),
            "ca_street_name": self._dcol(
                "st_nm", [f"Street {i}" for i in range(256)],
                ((keys * 5) % 256).astype(np.int32)),
            "ca_city": self._dcol(
                "city", [f"City{i}" for i in range(128)],
                ((keys * 11) % 128).astype(np.int32)),
            "ca_county": self._dcol("county", _COUNTIES,
                                    ((keys * 13) % len(_COUNTIES)).astype(np.int32)),
            "ca_state": self._dcol("state", _STATES, state),
            "ca_zip": self._dcol(
                "zip", [f"{i:05d}" for i in range(10000, 10000 + 512)],
                ((keys * 17) % 512).astype(np.int32)),
            "ca_country": self._dcol("us", ["United States"],
                                     np.zeros(m, dtype=np.int32)),
            "ca_gmt_offset": Column(DEC, -500 - 100 * (state % 4)),
            "ca_location_type": self._dcol(
                "loctype", ["apartment", "condo", "single family"],
                (keys % 3).astype(np.int32)),
        }

    def _gen_customer_demographics(self, sf, index, total):
        n = _counts(sf)["customer_demographics"]
        lo, hi = _range(n, index, total)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        return {
            "cd_demo_sk": Column(T.BIGINT, keys),
            "cd_gender": self._dcol("gender", ["M", "F"], (keys % 2).astype(np.int32)),
            "cd_marital_status": self._dcol(
                "marital", ["M", "S", "D", "W", "U"], (keys % 5).astype(np.int32)),
            "cd_education_status": self._dcol(
                "edu", _EDUCATION, (keys % len(_EDUCATION)).astype(np.int32)),
            "cd_purchase_estimate": Column(T.BIGINT, (keys % 20) * 500 + 500),
            "cd_credit_rating": self._dcol(
                "credit", _CREDIT, (keys % len(_CREDIT)).astype(np.int32)),
            "cd_dep_count": Column(T.BIGINT, keys % 7),
        }

    def _gen_household_demographics(self, sf, index, total):
        n = _counts(sf)["household_demographics"]
        lo, hi = _range(n, index, total)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        return {
            "hd_demo_sk": Column(T.BIGINT, keys),
            "hd_income_band_sk": Column(T.BIGINT, keys % 20 + 1),
            "hd_buy_potential": self._dcol(
                "buypot", _BUY_POTENTIAL,
                (keys % len(_BUY_POTENTIAL)).astype(np.int32)),
            "hd_dep_count": Column(T.BIGINT, keys % 10),
            "hd_vehicle_count": Column(T.BIGINT, keys % 5),
        }

    def _gen_income_band(self, sf, index, total):
        lo, hi = _range(20, index, total)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        return {
            "ib_income_band_sk": Column(T.BIGINT, keys),
            "ib_lower_bound": Column(T.BIGINT, (keys - 1) * 10000),
            "ib_upper_bound": Column(T.BIGINT, keys * 10000),
        }

    def _gen_store(self, sf, index, total):
        n = _counts(sf)["store"]
        lo, hi = _range(n, index, total)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        rng = self._rng("store", index)
        m = hi - lo
        return {
            "s_store_sk": Column(T.BIGINT, keys),
            "s_store_id": self._ids("S", keys, 8),
            "s_store_name": self._dcol(
                "sname", ["ought", "able", "pri", "ese", "anti",
                          "cally", "ation", "eing", "bar"],
                (keys % 9).astype(np.int32)),
            "s_number_employees": Column(T.BIGINT, rng.integers(200, 300, m)),
            "s_floor_space": Column(T.BIGINT, rng.integers(5_000_000, 10_000_000, m)),
            "s_hours": self._dcol("hours", ["8AM-8AM", "8AM-4PM", "8AM-12AM"],
                                  (keys % 3).astype(np.int32)),
            "s_manager": self._dcol("mgr", [f"Manager {i}" for i in range(64)],
                                    (keys % 64).astype(np.int32)),
            "s_market_id": Column(T.BIGINT, keys % 10 + 1),
            "s_city": self._dcol("s_city", [f"City{i}" for i in range(128)],
                                 ((keys * 11) % 128).astype(np.int32)),
            "s_county": self._dcol("county", _COUNTIES,
                                   ((keys * 13) % len(_COUNTIES)).astype(np.int32)),
            "s_state": self._dcol("state", _STATES,
                                  ((keys * 3) % len(_STATES)).astype(np.int32)),
            "s_zip": self._dcol(
                "zip", [f"{i:05d}" for i in range(10000, 10000 + 512)],
                ((keys * 17) % 512).astype(np.int32)),
        }

    def _gen_warehouse(self, sf, index, total):
        n = _counts(sf)["warehouse"]
        lo, hi = _range(n, index, total)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        rng = self._rng("warehouse", index)
        return {
            "w_warehouse_sk": Column(T.BIGINT, keys),
            "w_warehouse_id": self._ids("W", keys, 8),
            "w_warehouse_name": self._dcol(
                "wname", [f"Warehouse {i}" for i in range(32)],
                (keys % 32).astype(np.int32)),
            "w_warehouse_sq_ft": Column(T.BIGINT, rng.integers(50_000, 1_000_000, hi - lo)),
            "w_city": self._dcol("s_city", [f"City{i}" for i in range(128)],
                                 ((keys * 11) % 128).astype(np.int32)),
            "w_state": self._dcol("state", _STATES,
                                  ((keys * 3) % len(_STATES)).astype(np.int32)),
            "w_country": self._dcol("us", ["United States"],
                                    np.zeros(hi - lo, dtype=np.int32)),
        }

    def _gen_ship_mode(self, sf, index, total):
        lo, hi = _range(20, index, total)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        return {
            "sm_ship_mode_sk": Column(T.BIGINT, keys),
            "sm_ship_mode_id": self._ids("SM", keys, 6),
            "sm_type": self._dcol(
                "smtype", ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "LIBRARY"],
                ((keys - 1) % 5).astype(np.int32)),
            "sm_code": self._dcol("smcode", ["AIR", "SURFACE", "SEA"],
                                  (keys % 3).astype(np.int32)),
            "sm_carrier": self._dcol(
                "smcarrier", [f"Carrier{i}" for i in range(20)],
                ((keys - 1) % 20).astype(np.int32)),
        }

    def _gen_reason(self, sf, index, total):
        n = _counts(sf)["reason"]
        lo, hi = _range(n, index, total)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        return {
            "r_reason_sk": Column(T.BIGINT, keys),
            "r_reason_id": self._ids("R", keys, 6),
            "r_reason_desc": self._dcol(
                "rdesc", [f"reason {i}" for i in range(64)],
                (keys % 64).astype(np.int32)),
        }

    def _gen_promotion(self, sf, index, total):
        c = _counts(sf)
        n = c["promotion"]
        lo, hi = _range(n, index, total)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        rng = self._rng("promotion", index)
        m = hi - lo
        start = _DATE_SK0 + rng.integers(0, _N_DATES - 60, m)
        return {
            "p_promo_sk": Column(T.BIGINT, keys),
            "p_promo_id": self._ids("PR", keys, 8),
            "p_start_date_sk": Column(T.BIGINT, start),
            "p_end_date_sk": Column(T.BIGINT, start + rng.integers(10, 60, m)),
            "p_item_sk": Column(T.BIGINT, rng.integers(1, c["item"] + 1, m)),
            "p_cost": Column(DEC, rng.integers(10000, 100000, m)),
            "p_channel_dmail": self._dcol("yn", ["N", "Y"], (keys % 2).astype(np.int32)),
            "p_channel_email": self._dcol("yn", ["N", "Y"], ((keys // 2) % 2).astype(np.int32)),
            "p_channel_tv": self._dcol("yn", ["N", "Y"], ((keys // 4) % 2).astype(np.int32)),
            "p_promo_name": self._dcol(
                "pname", [f"promo{i}" for i in range(64)],
                (keys % 64).astype(np.int32)),
        }

    def _gen_web_site(self, sf, index, total):
        n = _counts(sf)["web_site"]
        lo, hi = _range(n, index, total)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        return {
            "web_site_sk": Column(T.BIGINT, keys),
            "web_site_id": self._ids("WS", keys, 8),
            "web_name": self._dcol("wname", [f"site_{i}" for i in range(32)],
                                   (keys % 32).astype(np.int32)),
            "web_manager": self._dcol("mgr", [f"Manager {i}" for i in range(64)],
                                      ((keys * 3) % 64).astype(np.int32)),
            "web_company_name": self._dcol(
                "wcomp", ["pri", "able", "ought", "ese", "anti", "cally"],
                (keys % 6).astype(np.int32)),
            "web_state": self._dcol("state", _STATES,
                                    ((keys * 3) % len(_STATES)).astype(np.int32)),
        }

    def _gen_web_page(self, sf, index, total):
        n = _counts(sf)["web_page"]
        lo, hi = _range(n, index, total)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        rng = self._rng("web_page", index)
        m = hi - lo
        return {
            "wp_web_page_sk": Column(T.BIGINT, keys),
            "wp_web_page_id": self._ids("WP", keys, 8),
            "wp_url": self._dcol("wpurl", ["http://www.foo.com"],
                                 np.zeros(m, dtype=np.int32)),
            "wp_type": self._dcol(
                "wptype", ["ad", "dynamic", "feedback", "general", "order",
                           "protected", "welcome"],
                (keys % 7).astype(np.int32)),
            "wp_char_count": Column(T.BIGINT, rng.integers(100, 8000, m)),
            "wp_link_count": Column(T.BIGINT, rng.integers(2, 25, m)),
        }

    def _gen_call_center(self, sf, index, total):
        n = _counts(sf)["call_center"]
        lo, hi = _range(n, index, total)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        rng = self._rng("call_center", index)
        return {
            "cc_call_center_sk": Column(T.BIGINT, keys),
            "cc_call_center_id": self._ids("CC", keys, 8),
            "cc_name": self._dcol(
                "ccname", [f"call center {i}" for i in range(16)],
                (keys % 16).astype(np.int32)),
            "cc_class": self._dcol("ccclass", ["small", "medium", "large"],
                                   (keys % 3).astype(np.int32)),
            "cc_employees": Column(T.BIGINT, rng.integers(50, 500, hi - lo)),
            "cc_manager": self._dcol("mgr", [f"Manager {i}" for i in range(64)],
                                     ((keys * 5) % 64).astype(np.int32)),
            "cc_county": self._dcol("county", _COUNTIES,
                                    ((keys * 13) % len(_COUNTIES)).astype(np.int32)),
            "cc_state": self._dcol("state", _STATES,
                                   ((keys * 3) % len(_STATES)).astype(np.int32)),
        }

    def _gen_catalog_page(self, sf, index, total):
        n = _counts(sf)["catalog_page"]
        lo, hi = _range(n, index, total)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        return {
            "cp_catalog_page_sk": Column(T.BIGINT, keys),
            "cp_catalog_page_id": self._ids("CP", keys, 8),
            "cp_department": self._dcol("dept", ["DEPARTMENT"],
                                        np.zeros(hi - lo, dtype=np.int32)),
            "cp_catalog_number": Column(T.BIGINT, keys // 100 + 1),
            "cp_catalog_page_number": Column(T.BIGINT, keys % 100 + 1),
            "cp_type": self._dcol("cptype", ["annual", "quarterly", "bi-annual"],
                                  (keys % 3).astype(np.int32)),
        }

    def _gen_inventory(self, sf, index, total):
        c = _counts(sf)
        n = c["inventory"]
        lo, hi = _range(n, index, total)
        idx = np.arange(lo, hi, dtype=np.int64)
        n_items = c["item"]
        n_wh = c["warehouse"]
        rng = self._rng("inventory", index)
        # weekly snapshots: week index wraps within the date_dim span so
        # inv_date_sk always joins date_dim
        week = idx // max(1, n_items * n_wh)
        return {
            "inv_date_sk": Column(T.BIGINT, _DATE_SK0 + (week * 7) % _N_DATES),
            "inv_item_sk": Column(T.BIGINT, (idx // n_wh) % n_items + 1),
            "inv_warehouse_sk": Column(T.BIGINT, idx % n_wh + 1),
            "inv_quantity_on_hand": Column(T.BIGINT, rng.integers(0, 1000, hi - lo)),
        }

    # --- facts ------------------------------------------------------------

    def _sales_common(self, table, sf, index, total):
        """Shared generator for the three sales channels."""
        c = _counts(sf)
        n = c[table]
        lo, hi = _range(n, index, total)
        m = hi - lo
        rng = self._rng(table, index)
        rows = np.arange(lo, hi, dtype=np.int64)
        # ~12 lines per order/ticket
        order = rows // 12 + 1
        item = _keyhash(rows, 1) % c["item"] + 1
        sold_date = _DATE_SK0 + (_keyhash(order, 2) % _N_DATES)
        qty = _keyhash(rows, 3) % 100 + 1
        wholesale = _keyhash(rows, 4) % 9900 + 100       # 1.00 - 99.99
        list_price = wholesale + wholesale * (_keyhash(rows, 5) % 100) // 100
        sales_price = list_price - list_price * (_keyhash(rows, 6) % 50) // 100
        ext_sales = sales_price * qty
        ext_wholesale = wholesale * qty
        ext_list = list_price * qty
        ext_discount = (list_price - sales_price) * qty
        tax = ext_sales * 8 // 100
        coupon = np.where(_keyhash(rows, 7) % 10 == 0, ext_sales // 10, 0)
        net_paid = ext_sales - coupon
        net_profit = net_paid - ext_wholesale
        return {
            "c": c, "m": m, "rng": rng, "rows": rows, "order": order,
            "item": item, "sold_date": sold_date, "qty": qty,
            "wholesale": wholesale, "list_price": list_price,
            "sales_price": sales_price, "ext_sales": ext_sales,
            "ext_wholesale": ext_wholesale, "ext_list": ext_list,
            "ext_discount": ext_discount, "tax": tax, "coupon": coupon,
            "net_paid": net_paid, "net_profit": net_profit,
        }

    def _gen_store_sales(self, sf, index, total):
        g = self._sales_common("store_sales", sf, index, total)
        c, rows = g["c"], g["rows"]
        return {
            "ss_sold_date_sk": Column(T.BIGINT, g["sold_date"]),
            "ss_sold_time_sk": Column(T.BIGINT, _keyhash(rows, 8) % c["time_dim"] + 1),
            "ss_item_sk": Column(T.BIGINT, g["item"]),
            "ss_customer_sk": Column(T.BIGINT, _keyhash(g["order"], 9) % c["customer"] + 1),
            "ss_cdemo_sk": Column(T.BIGINT, _keyhash(g["order"], 10) % c["customer_demographics"] + 1),
            "ss_hdemo_sk": Column(T.BIGINT, _keyhash(g["order"], 11) % c["household_demographics"] + 1),
            "ss_addr_sk": Column(T.BIGINT, _keyhash(g["order"], 12) % c["customer_address"] + 1),
            "ss_store_sk": Column(T.BIGINT, _keyhash(g["order"], 13) % c["store"] + 1),
            "ss_promo_sk": Column(T.BIGINT, _keyhash(rows, 14) % c["promotion"] + 1),
            "ss_ticket_number": Column(T.BIGINT, g["order"]),
            "ss_quantity": Column(T.BIGINT, g["qty"]),
            "ss_wholesale_cost": Column(DEC, g["wholesale"]),
            "ss_list_price": Column(DEC, g["list_price"]),
            "ss_sales_price": Column(DEC, g["sales_price"]),
            "ss_ext_discount_amt": Column(DEC, g["ext_discount"]),
            "ss_ext_sales_price": Column(DEC, g["ext_sales"]),
            "ss_ext_wholesale_cost": Column(DEC, g["ext_wholesale"]),
            "ss_ext_list_price": Column(DEC, g["ext_list"]),
            "ss_ext_tax": Column(DEC, g["tax"]),
            "ss_coupon_amt": Column(DEC, g["coupon"]),
            "ss_net_paid": Column(DEC, g["net_paid"]),
            "ss_net_paid_inc_tax": Column(DEC, g["net_paid"] + g["tax"]),
            "ss_net_profit": Column(DEC, g["net_profit"]),
        }

    def _gen_catalog_sales(self, sf, index, total):
        g = self._sales_common("catalog_sales", sf, index, total)
        c, rows = g["c"], g["rows"]
        ship_cost = g["ext_sales"] // 20
        return {
            "cs_sold_date_sk": Column(T.BIGINT, g["sold_date"]),
            "cs_sold_time_sk": Column(T.BIGINT, _keyhash(rows, 8) % c["time_dim"] + 1),
            "cs_ship_date_sk": Column(T.BIGINT, g["sold_date"] + _keyhash(rows, 20) % 30 + 2),
            "cs_bill_customer_sk": Column(T.BIGINT, _keyhash(g["order"], 9) % c["customer"] + 1),
            "cs_bill_cdemo_sk": Column(T.BIGINT, _keyhash(g["order"], 10) % c["customer_demographics"] + 1),
            "cs_bill_hdemo_sk": Column(T.BIGINT, _keyhash(g["order"], 11) % c["household_demographics"] + 1),
            "cs_bill_addr_sk": Column(T.BIGINT, _keyhash(g["order"], 12) % c["customer_address"] + 1),
            "cs_ship_customer_sk": Column(T.BIGINT, _keyhash(g["order"], 15) % c["customer"] + 1),
            "cs_ship_addr_sk": Column(T.BIGINT, _keyhash(g["order"], 16) % c["customer_address"] + 1),
            "cs_call_center_sk": Column(T.BIGINT, _keyhash(g["order"], 17) % c["call_center"] + 1),
            "cs_catalog_page_sk": Column(T.BIGINT, _keyhash(rows, 18) % c["catalog_page"] + 1),
            "cs_ship_mode_sk": Column(T.BIGINT, _keyhash(g["order"], 19) % 20 + 1),
            "cs_warehouse_sk": Column(T.BIGINT, _keyhash(rows, 21) % c["warehouse"] + 1),
            "cs_item_sk": Column(T.BIGINT, g["item"]),
            "cs_promo_sk": Column(T.BIGINT, _keyhash(rows, 14) % c["promotion"] + 1),
            "cs_order_number": Column(T.BIGINT, g["order"]),
            "cs_quantity": Column(T.BIGINT, g["qty"]),
            "cs_wholesale_cost": Column(DEC, g["wholesale"]),
            "cs_list_price": Column(DEC, g["list_price"]),
            "cs_sales_price": Column(DEC, g["sales_price"]),
            "cs_ext_discount_amt": Column(DEC, g["ext_discount"]),
            "cs_ext_sales_price": Column(DEC, g["ext_sales"]),
            "cs_ext_wholesale_cost": Column(DEC, g["ext_wholesale"]),
            "cs_ext_list_price": Column(DEC, g["ext_list"]),
            "cs_ext_tax": Column(DEC, g["tax"]),
            "cs_coupon_amt": Column(DEC, g["coupon"]),
            "cs_ext_ship_cost": Column(DEC, ship_cost),
            "cs_net_paid": Column(DEC, g["net_paid"]),
            "cs_net_paid_inc_tax": Column(DEC, g["net_paid"] + g["tax"]),
            "cs_net_paid_inc_ship": Column(DEC, g["net_paid"] + ship_cost),
            "cs_net_paid_inc_ship_tax": Column(DEC, g["net_paid"] + ship_cost + g["tax"]),
            "cs_net_profit": Column(DEC, g["net_profit"]),
        }

    def _gen_web_sales(self, sf, index, total):
        g = self._sales_common("web_sales", sf, index, total)
        c, rows = g["c"], g["rows"]
        ship_cost = g["ext_sales"] // 20
        return {
            "ws_sold_date_sk": Column(T.BIGINT, g["sold_date"]),
            "ws_sold_time_sk": Column(T.BIGINT, _keyhash(rows, 8) % c["time_dim"] + 1),
            "ws_ship_date_sk": Column(T.BIGINT, g["sold_date"] + _keyhash(g["order"], 20) % 60 + 1),
            "ws_item_sk": Column(T.BIGINT, g["item"]),
            "ws_bill_customer_sk": Column(T.BIGINT, _keyhash(g["order"], 9) % c["customer"] + 1),
            "ws_bill_cdemo_sk": Column(T.BIGINT, _keyhash(g["order"], 10) % c["customer_demographics"] + 1),
            "ws_bill_hdemo_sk": Column(T.BIGINT, _keyhash(g["order"], 11) % c["household_demographics"] + 1),
            "ws_bill_addr_sk": Column(T.BIGINT, _keyhash(g["order"], 12) % c["customer_address"] + 1),
            "ws_ship_customer_sk": Column(T.BIGINT, _keyhash(g["order"], 15) % c["customer"] + 1),
            "ws_ship_addr_sk": Column(T.BIGINT, _keyhash(g["order"], 16) % c["customer_address"] + 1),
            "ws_web_page_sk": Column(T.BIGINT, _keyhash(rows, 17) % c["web_page"] + 1),
            "ws_web_site_sk": Column(T.BIGINT, _keyhash(g["order"], 18) % c["web_site"] + 1),
            "ws_ship_mode_sk": Column(T.BIGINT, _keyhash(g["order"], 19) % 20 + 1),
            "ws_warehouse_sk": Column(T.BIGINT, _keyhash(g["order"], 21) % c["warehouse"] + 1),
            "ws_promo_sk": Column(T.BIGINT, _keyhash(rows, 14) % c["promotion"] + 1),
            "ws_order_number": Column(T.BIGINT, g["order"]),
            "ws_quantity": Column(T.BIGINT, g["qty"]),
            "ws_wholesale_cost": Column(DEC, g["wholesale"]),
            "ws_list_price": Column(DEC, g["list_price"]),
            "ws_sales_price": Column(DEC, g["sales_price"]),
            "ws_ext_discount_amt": Column(DEC, g["ext_discount"]),
            "ws_ext_sales_price": Column(DEC, g["ext_sales"]),
            "ws_ext_wholesale_cost": Column(DEC, g["ext_wholesale"]),
            "ws_ext_list_price": Column(DEC, g["ext_list"]),
            "ws_ext_tax": Column(DEC, g["tax"]),
            "ws_coupon_amt": Column(DEC, g["coupon"]),
            "ws_ext_ship_cost": Column(DEC, ship_cost),
            "ws_net_paid": Column(DEC, g["net_paid"]),
            "ws_net_paid_inc_tax": Column(DEC, g["net_paid"] + g["tax"]),
            "ws_net_paid_inc_ship": Column(DEC, g["net_paid"] + ship_cost),
            "ws_net_paid_inc_ship_tax": Column(DEC, g["net_paid"] + ship_cost + g["tax"]),
            "ws_net_profit": Column(DEC, g["net_profit"]),
        }

    # --- returns: ~10% of the matching sales split, same keys -------------

    def _returns_base(self, sales_table, sf, index, total):
        sales = getattr(self, f"_gen_{sales_table}")(sf, index, total)
        prefix = {"store_sales": "ss", "catalog_sales": "cs", "web_sales": "ws"}[
            sales_table
        ]
        order_col = {"store_sales": "ss_ticket_number",
                     "catalog_sales": "cs_order_number",
                     "web_sales": "ws_order_number"}[sales_table]
        item = np.asarray(sales[f"{prefix}_item_sk"].data)
        order = np.asarray(sales[order_col].data)
        rows = np.arange(len(item), dtype=np.int64)
        mask = _keyhash(order * 131 + item, 40) % 10 == 0
        sel = rows[mask]
        return sales, prefix, item[mask], order[mask], sel

    def _gen_store_returns(self, sf, index, total):
        c = _counts(sf)
        sales, _, item, order, sel = self._returns_base("store_sales", sf, index, total)
        m = len(sel)
        amt = np.asarray(sales["ss_sales_price"].data)[sel]
        qty = np.maximum(1, np.asarray(sales["ss_quantity"].data)[sel] // 2)
        ramt = amt * qty
        tax = ramt * 8 // 100
        sold = np.asarray(sales["ss_sold_date_sk"].data)[sel]
        return {
            "sr_returned_date_sk": Column(T.BIGINT, sold + _keyhash(order, 41) % 60 + 1),
            "sr_return_time_sk": Column(T.BIGINT, _keyhash(order, 42) % c["time_dim"] + 1),
            "sr_item_sk": Column(T.BIGINT, item),
            "sr_customer_sk": Column(T.BIGINT, np.asarray(sales["ss_customer_sk"].data)[sel]),
            "sr_cdemo_sk": Column(T.BIGINT, np.asarray(sales["ss_cdemo_sk"].data)[sel]),
            "sr_hdemo_sk": Column(T.BIGINT, np.asarray(sales["ss_hdemo_sk"].data)[sel]),
            "sr_addr_sk": Column(T.BIGINT, np.asarray(sales["ss_addr_sk"].data)[sel]),
            "sr_store_sk": Column(T.BIGINT, np.asarray(sales["ss_store_sk"].data)[sel]),
            "sr_reason_sk": Column(T.BIGINT, _keyhash(order, 43) % c["reason"] + 1),
            "sr_ticket_number": Column(T.BIGINT, order),
            "sr_return_quantity": Column(T.BIGINT, qty),
            "sr_return_amt": Column(DEC, ramt),
            "sr_return_tax": Column(DEC, tax),
            "sr_return_amt_inc_tax": Column(DEC, ramt + tax),
            "sr_fee": Column(DEC, np.full(m, 500, dtype=np.int64)),
            "sr_return_ship_cost": Column(DEC, ramt // 20),
            "sr_refunded_cash": Column(DEC, ramt // 2),
            "sr_reversed_charge": Column(DEC, ramt // 4),
            "sr_store_credit": Column(DEC, ramt - ramt // 2 - ramt // 4),
            "sr_net_loss": Column(DEC, ramt // 10 + 500),
        }

    def _gen_catalog_returns(self, sf, index, total):
        c = _counts(sf)
        sales, _, item, order, sel = self._returns_base("catalog_sales", sf, index, total)
        m = len(sel)
        amt = np.asarray(sales["cs_sales_price"].data)[sel]
        qty = np.maximum(1, np.asarray(sales["cs_quantity"].data)[sel] // 2)
        ramt = amt * qty
        tax = ramt * 8 // 100
        sold = np.asarray(sales["cs_sold_date_sk"].data)[sel]
        return {
            "cr_returned_date_sk": Column(T.BIGINT, sold + _keyhash(order, 41) % 60 + 1),
            "cr_returned_time_sk": Column(T.BIGINT, _keyhash(order, 42) % c["time_dim"] + 1),
            "cr_item_sk": Column(T.BIGINT, item),
            "cr_refunded_customer_sk": Column(T.BIGINT, np.asarray(sales["cs_bill_customer_sk"].data)[sel]),
            "cr_refunded_addr_sk": Column(T.BIGINT, np.asarray(sales["cs_bill_addr_sk"].data)[sel]),
            "cr_returning_customer_sk": Column(T.BIGINT, np.asarray(sales["cs_ship_customer_sk"].data)[sel]),
            "cr_call_center_sk": Column(T.BIGINT, np.asarray(sales["cs_call_center_sk"].data)[sel]),
            "cr_catalog_page_sk": Column(T.BIGINT, np.asarray(sales["cs_catalog_page_sk"].data)[sel]),
            "cr_ship_mode_sk": Column(T.BIGINT, np.asarray(sales["cs_ship_mode_sk"].data)[sel]),
            "cr_warehouse_sk": Column(T.BIGINT, np.asarray(sales["cs_warehouse_sk"].data)[sel]),
            "cr_reason_sk": Column(T.BIGINT, _keyhash(order, 43) % c["reason"] + 1),
            "cr_order_number": Column(T.BIGINT, order),
            "cr_return_quantity": Column(T.BIGINT, qty),
            "cr_return_amount": Column(DEC, ramt),
            "cr_return_tax": Column(DEC, tax),
            "cr_return_amt_inc_tax": Column(DEC, ramt + tax),
            "cr_fee": Column(DEC, np.full(m, 500, dtype=np.int64)),
            "cr_return_ship_cost": Column(DEC, ramt // 20),
            "cr_refunded_cash": Column(DEC, ramt // 2),
            "cr_reversed_charge": Column(DEC, ramt // 4),
            "cr_store_credit": Column(DEC, ramt - ramt // 2 - ramt // 4),
            "cr_net_loss": Column(DEC, ramt // 10 + 500),
        }

    def _gen_web_returns(self, sf, index, total):
        c = _counts(sf)
        sales, _, item, order, sel = self._returns_base("web_sales", sf, index, total)
        m = len(sel)
        amt = np.asarray(sales["ws_sales_price"].data)[sel]
        qty = np.maximum(1, np.asarray(sales["ws_quantity"].data)[sel] // 2)
        ramt = amt * qty
        tax = ramt * 8 // 100
        sold = np.asarray(sales["ws_sold_date_sk"].data)[sel]
        return {
            "wr_returned_date_sk": Column(T.BIGINT, sold + _keyhash(order, 41) % 60 + 1),
            "wr_returned_time_sk": Column(T.BIGINT, _keyhash(order, 42) % c["time_dim"] + 1),
            "wr_item_sk": Column(T.BIGINT, item),
            "wr_refunded_customer_sk": Column(T.BIGINT, np.asarray(sales["ws_bill_customer_sk"].data)[sel]),
            "wr_refunded_addr_sk": Column(T.BIGINT, np.asarray(sales["ws_bill_addr_sk"].data)[sel]),
            "wr_returning_customer_sk": Column(T.BIGINT, np.asarray(sales["ws_ship_customer_sk"].data)[sel]),
            "wr_web_page_sk": Column(T.BIGINT, np.asarray(sales["ws_web_page_sk"].data)[sel]),
            "wr_reason_sk": Column(T.BIGINT, _keyhash(order, 43) % c["reason"] + 1),
            "wr_order_number": Column(T.BIGINT, order),
            "wr_return_quantity": Column(T.BIGINT, qty),
            "wr_return_amt": Column(DEC, ramt),
            "wr_return_tax": Column(DEC, tax),
            "wr_return_amt_inc_tax": Column(DEC, ramt + tax),
            "wr_fee": Column(DEC, np.full(m, 500, dtype=np.int64)),
            "wr_return_ship_cost": Column(DEC, ramt // 20),
            "wr_refunded_cash": Column(DEC, ramt // 2),
            "wr_reversed_charge": Column(DEC, ramt // 4),
            "wr_account_credit": Column(DEC, ramt - ramt // 2 - ramt // 4),
            "wr_net_loss": Column(DEC, ramt // 10 + 500),
        }


_PRIMARY_SK = {
    "item": "i_item_sk", "customer": "c_customer_sk",
    "customer_address": "ca_address_sk",
    "customer_demographics": "cd_demo_sk",
    "household_demographics": "hd_demo_sk", "income_band": "ib_income_band_sk",
    "store": "s_store_sk", "warehouse": "w_warehouse_sk",
    "ship_mode": "sm_ship_mode_sk", "reason": "r_reason_sk",
    "promotion": "p_promo_sk", "web_site": "web_site_sk",
    "web_page": "wp_web_page_sk", "call_center": "cc_call_center_sk",
    "catalog_page": "cp_catalog_page_sk", "time_dim": "t_time_sk",
}


def _range(total_rows: int, index: int, total: int) -> tuple[int, int]:
    per = (total_rows + total - 1) // total
    lo = index * per
    hi = min(total_rows, lo + per)
    return lo, hi


def _stable_seed(*parts) -> int:
    """Process-stable RNG seed (PYTHONHASHSEED-independent)."""
    import hashlib

    h = hashlib.sha256(":".join(map(str, parts)).encode()).digest()
    return int.from_bytes(h[:8], "little")


def _keyhash(keys: np.ndarray, stream: int) -> np.ndarray:
    """Deterministic keyed hash stream -> non-negative int64."""
    x = keys.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15) + np.uint64(
        stream * 0xD1B54A32D192ED03 % (2**64)
    )
    x ^= x >> np.uint64(29)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(32)
    return (x >> np.uint64(1)).astype(np.int64)
