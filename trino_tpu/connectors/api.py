"""Connector SPI.

Reference interfaces: ``spi/connector/Connector.java:28-90`` (metadata,
split manager, page source provider), ``spi/connector/ConnectorSplitManager.java:23``,
``spi/connector/ConnectorPageSource.java:47``.

TPU-first simplification: a connector reads a (table, split, columns)
triple into one host :class:`Batch`; the executor moves it to device and
pads. Splits are the unit of scan parallelism (reference §2.6 item 3).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

from trino_tpu import types as T
from trino_tpu.columnar import Batch


@dataclasses.dataclass(frozen=True)
class ColumnSchema:
    name: str
    type: T.SqlType


@dataclasses.dataclass(frozen=True)
class TableSchema:
    name: str
    columns: tuple[ColumnSchema, ...]

    def column_names(self) -> list[str]:
        return [c.name for c in self.columns]

    def column(self, name: str) -> ColumnSchema | None:
        for c in self.columns:
            if c.name == name:
                return c
        return None


@dataclasses.dataclass(frozen=True)
class Split:
    """Opaque unit of scan work (reference: ``spi/connector/ConnectorSplit``)."""

    table: str
    index: int
    total: int
    info: Any = None


class Connector:
    name: str = "connector"

    # --- metadata --------------------------------------------------------
    def list_schemas(self) -> list[str]:
        raise NotImplementedError

    def list_tables(self, schema: str) -> list[str]:
        raise NotImplementedError

    def get_table(self, schema: str, table: str) -> Optional[TableSchema]:
        raise NotImplementedError

    # --- splits + data ---------------------------------------------------
    def get_splits(self, schema: str, table: str, target_splits: int) -> list[Split]:
        return [Split(table, 0, 1)]

    def read_split(
        self, schema: str, table: str, columns: Sequence[str], split: Split
    ) -> Batch:
        raise NotImplementedError

    # --- optional stats (drives join distribution / sizing) -------------
    def estimate_rows(self, schema: str, table: str) -> Optional[int]:
        return None

    # --- optional write path --------------------------------------------
    def create_table(self, schema: str, table: str, schema_def: TableSchema) -> None:
        raise NotImplementedError(f"{self.name}: CREATE TABLE not supported")

    def insert(self, schema: str, table: str, batch: Batch) -> int:
        raise NotImplementedError(f"{self.name}: INSERT not supported")

    def drop_table(self, schema: str, table: str) -> None:
        raise NotImplementedError(f"{self.name}: DROP TABLE not supported")


class CatalogManager:
    """Catalog name -> connector instance (reference:
    ``metadata/MetadataManager.java:184`` catalog routing)."""

    def __init__(self):
        self._catalogs: dict[str, Connector] = {}

    def register(self, name: str, connector: Connector) -> None:
        self._catalogs[name] = connector

    def get(self, name: str) -> Connector:
        if name not in self._catalogs:
            raise KeyError(f"catalog not found: {name}")
        return self._catalogs[name]

    def names(self) -> list[str]:
        return sorted(self._catalogs)
