"""Connector SPI.

Reference interfaces: ``spi/connector/Connector.java:28-90`` (metadata,
split manager, page source provider), ``spi/connector/ConnectorSplitManager.java:23``,
``spi/connector/ConnectorPageSource.java:47``.

TPU-first simplification: a connector reads a (table, split, columns)
triple into one host :class:`Batch`; the executor moves it to device and
pads. Splits are the unit of scan parallelism (reference §2.6 item 3).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

from trino_tpu import types as T
from trino_tpu.columnar import Batch
from trino_tpu.predicate import TupleDomain


@dataclasses.dataclass(frozen=True)
class ColumnStats:
    """Per-column statistics (reference: ``spi/statistics/ColumnStatistics``)."""

    distinct_count: Optional[float] = None
    null_fraction: Optional[float] = None
    min_value: Any = None
    max_value: Any = None


@dataclasses.dataclass(frozen=True)
class TableStats:
    """Reference: ``spi/statistics/TableStatistics`` — drives the CBO."""

    row_count: Optional[float] = None
    columns: dict[str, ColumnStats] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class ColumnSchema:
    name: str
    type: T.SqlType


@dataclasses.dataclass(frozen=True)
class TableSchema:
    name: str
    columns: tuple[ColumnSchema, ...]

    def column_names(self) -> list[str]:
        return [c.name for c in self.columns]

    def column(self, name: str) -> ColumnSchema | None:
        for c in self.columns:
            if c.name == name:
                return c
        return None


@dataclasses.dataclass(frozen=True)
class Split:
    """Opaque unit of scan work (reference: ``spi/connector/ConnectorSplit``)."""

    table: str
    index: int
    total: int
    info: Any = None


class Connector:
    name: str = "connector"
    # True when concurrent inserts from several NODES are safe (shared
    # storage): enables scaled-writer dispatch (ScaledWriterScheduler)
    supports_distributed_writes: bool = False
    # False for connectors whose reads reflect live process state rather
    # than versioned table data (system tables): the coordinator result
    # cache (trino_tpu/cache) refuses to cache queries touching them
    supports_result_caching: bool = True

    # --- metadata --------------------------------------------------------
    def list_schemas(self) -> list[str]:
        raise NotImplementedError

    def list_tables(self, schema: str) -> list[str]:
        raise NotImplementedError

    def get_table(self, schema: str, table: str) -> Optional[TableSchema]:
        raise NotImplementedError

    # --- optimizer pushdown hooks ----------------------------------------
    # Reference: ``spi/connector/ConnectorMetadata.java`` applyLimit
    # (:1064), applyTopN (:1090), applyAggregation (:932); applyFilter's
    # analog is the constraint/prune_splits path below.

    def apply_limit(self, schema: str, table: str, count: int) -> bool:
        """True if the connector will honor a read-at-most-``count`` hint
        on its scans (guarantee-free: the engine still enforces LIMIT)."""
        return False

    def apply_topn(
        self, schema: str, table: str, keys: list, count: int
    ) -> bool:
        """True ONLY if this connector's ``get_splits_with_hints`` orders
        scans by ``keys`` ([(column, ascending)]) well enough that the
        first ``count`` rows read contain the true top-N — the engine
        stops reading splits at the limit when this returns True (the
        TopN node above still sorts/cuts what was read)."""
        return False

    def get_splits_with_hints(
        self,
        schema: str,
        table: str,
        target_splits: int,
        constraint=None,
        limit: Optional[int] = None,
        topn: Optional[list] = None,
    ) -> list["Split"]:
        """Split enumeration with the optimizer's pushed limit/topn hints.

        Default ignores the hints (safe: the engine only trusts them when
        the connector's apply_limit/apply_topn accepted). Connectors that
        accept override this to cap or order their splits."""
        return self.get_splits(schema, table, target_splits, constraint)

    def apply_aggregation_count(self, schema: str, table: str):
        """Exact total row count, or None when the connector cannot answer
        without scanning. ONLY return a value that is exactly correct —
        the optimizer replaces a global count(*) with it."""
        return None

    # --- splits + data ---------------------------------------------------
    def get_splits(
        self,
        schema: str,
        table: str,
        target_splits: int,
        constraint: Optional[TupleDomain] = None,
    ) -> list[Split]:
        return self.prune_splits(schema, table, [Split(table, 0, 1)], constraint)

    def prune_splits(
        self,
        schema: str,
        table: str,
        splits: list[Split],
        constraint: Optional[TupleDomain],
    ) -> list[Split]:
        """Drop splits whose min/max stats cannot satisfy ``constraint``
        (reference: stripe/row-group pruning,
        ``lib/trino-orc/.../TupleDomainOrcPredicate.java:74,92``)."""
        if constraint is None or constraint.is_all():
            return splits
        if constraint.is_none():
            return []
        out = []
        for s in splits:
            stats = self.split_stats(schema, table, s)
            if stats is None or constraint.overlaps_stats(stats):
                out.append(s)
        return out

    def split_stats(
        self, schema: str, table: str, split: Split
    ) -> Optional[dict[str, tuple[Any, Any, bool]]]:
        """column -> (min, max, has_null) for this split, or None if unknown."""
        return None

    def read_split(
        self, schema: str, table: str, columns: Sequence[str], split: Split
    ) -> Batch:
        raise NotImplementedError

    def data_version(self, schema: str, table: str) -> Any:
        """Monotone token that changes whenever the table's data changes;
        keys the device table cache (trino_tpu/ingest.py), so mutation
        invalidates cached HBM columns by making their keys unreachable.
        Mutable connectors bump ``_version``; file-backed connectors
        override with a (file list, mtime) digest."""
        return getattr(self, "_version", 0)

    def data_versions(self, schema: str, table: str) -> Optional[list]:
        """Part-level version enumeration: ordered ``(part_id, token)``
        pairs, one per immutable storage part, or None when the connector
        cannot enumerate parts (the result cache then falls back to the
        coarse :meth:`data_version` token, where ANY change invalidates).

        Contract: a part's token never changes while its id is live; an
        APPEND adds new ids and leaves every old pair intact; any other
        mutation (rewrite, delete, truncate) removes or changes at least
        one old pair. This is what lets the result cache distinguish
        "maintain incrementally over the new parts" from "invalidate"."""
        return None

    def splits_for_parts(self, schema: str, table, part_ids) -> list["Split"]:
        """Splits covering exactly the parts named by ``part_ids`` (ids
        from :meth:`data_versions`) — the delta scan for incremental
        aggregate maintenance. Required when data_versions is implemented."""
        raise NotImplementedError(f"{self.name}: part-level splits not supported")

    # --- optional stats (drives join distribution / sizing) -------------
    def estimate_rows(self, schema: str, table: str) -> Optional[int]:
        return None

    def table_stats(self, schema: str, table: str) -> Optional[TableStats]:
        """Reference: ``ConnectorMetadata.getTableStatistics`` — CBO input."""
        rows = self.estimate_rows(schema, table)
        return TableStats(row_count=rows) if rows is not None else None

    # --- optional write path --------------------------------------------
    def create_table(self, schema: str, table: str, schema_def: TableSchema) -> None:
        raise NotImplementedError(f"{self.name}: CREATE TABLE not supported")

    def insert(self, schema: str, table: str, batch: Batch) -> int:
        raise NotImplementedError(f"{self.name}: INSERT not supported")

    def drop_table(self, schema: str, table: str) -> None:
        raise NotImplementedError(f"{self.name}: DROP TABLE not supported")


class CatalogManager:
    """Catalog name -> connector instance (reference:
    ``metadata/MetadataManager.java:184`` catalog routing)."""

    def __init__(self):
        self._catalogs: dict[str, Connector] = {}

    def register(self, name: str, connector: Connector) -> None:
        self._catalogs[name] = connector

    def get(self, name: str) -> Connector:
        if name not in self._catalogs:
            raise KeyError(f"catalog not found: {name}")
        return self._catalogs[name]

    def names(self) -> list[str]:
        return sorted(self._catalogs)


# staging quantum: slabs are padded to a multiple of this row count, so
# any power-of-two chunk size up to the quantum can dynamic_slice them —
# one staged copy serves every chunk-size setting
SLAB_PAD_QUANTUM = 1 << 22


def slab_padded_rows(rows: int, cap: int) -> int:
    """Rows a staged slab actually allocates (quantum padding)."""
    quantum = max(cap, SLAB_PAD_QUANTUM)
    return ((rows + quantum - 1) // quantum) * quantum


def slab_bytes_estimate(types: Sequence, rows: int, cap: int) -> int:
    """Bytes needed to stage ``rows`` of these column types in HBM —
    measured at the PADDED allocation (wide DECIMALs store (n, 2) int64
    lanes; +1 byte/row validity), so admission bounds reflect reality."""
    import numpy as np

    padded = slab_padded_rows(rows, cap)
    nbytes = 0
    for t in types:
        width = np.dtype(t.storage_dtype).itemsize
        if getattr(t, "wide", False):
            width *= 2
        nbytes += padded * (width + 1)
    return nbytes


def stage_device_slab(host_batches: Sequence[Batch], cap: int):
    """Stage host batches into device HBM as ONE slab padded to a
    multiple of ``cap`` rows (so a compiled streaming step can
    ``dynamic_slice`` any chunk without clamping). Per-part dictionaries
    are unified during the concat. Returns (slab_batch, num_rows).

    Shared by connectors whose data can live device-resident (memory
    pages, generated tpch splits): HBM plays the role the reference's
    worker heap plays for pinned pages."""
    import jax
    import numpy as np

    from trino_tpu.columnar import Column, concat_batches

    host = concat_batches(list(host_batches))
    total_rows = host.num_rows
    quantum = max(cap, SLAB_PAD_QUANTUM)
    padded_rows = ((total_rows + quantum - 1) // quantum) * quantum
    pad = padded_rows - total_rows
    cols = []
    for c in host.columns:
        data, valid = np.asarray(c.data), c.valid
        if pad:
            data = np.concatenate(
                [data, np.zeros((pad,) + data.shape[1:], dtype=data.dtype)]
            )
            if valid is not None:
                valid = np.concatenate(
                    [np.asarray(valid), np.zeros(pad, dtype=np.bool_)]
                )
        dev = jax.device_put(data)
        dvalid = None if valid is None else jax.device_put(valid)
        cols.append(Column(c.type, dev, dvalid, c.dictionary))
    return Batch(cols, padded_rows), total_rows


def batch_column_stats(columns, batch) -> dict:
    """Per-column (min, max, has_null) for a compacted batch — shared by
    stats-collecting connectors (the stripe-footer computation)."""
    out: dict[str, tuple] = {}
    for cs, col in zip(columns, batch.columns):
        if T.is_string(cs.type) or batch.num_rows == 0:
            continue
        data, valid = col.to_numpy()
        data = data[: batch.num_rows]
        valid = valid[: batch.num_rows]
        live = data[valid]
        has_null = bool((~valid).any())
        if live.size:
            out[cs.name] = (live.min().item(), live.max().item(), has_null)
        else:
            out[cs.name] = (None, None, has_null)
    return out


def register_catalog_spec(manager: CatalogManager, spec: str) -> None:
    """Register a connector from a ``name=kind[:arg]`` spec string.

    The ``etc/catalog/*.properties`` analog (reference:
    ``server/PluginManager.java`` / ``connector/ConnectorManager.java``):
    servers take ``--catalog data=parquet:/shared/path`` so every node of
    a cluster mounts the same catalogs at boot.
    """
    name, _, rest = spec.partition("=")
    kind, _, arg = rest.partition(":")
    name, kind = name.strip(), kind.strip()
    if kind == "memory":
        from trino_tpu.connectors.memory import MemoryConnector

        manager.register(name, MemoryConnector())
    elif kind == "blackhole":
        from trino_tpu.connectors.blackhole import BlackHoleConnector

        manager.register(name, BlackHoleConnector())
    elif kind == "file":
        from trino_tpu.connectors.file import FileConnector

        manager.register(name, FileConnector(arg))
    elif kind == "parquet":
        from trino_tpu.connectors.parquet import ParquetConnector

        manager.register(name, ParquetConnector(arg))
    elif kind == "orc":
        from trino_tpu.connectors.orc import OrcConnector

        manager.register(name, OrcConnector(arg))
    elif kind == "tpch":
        from trino_tpu.connectors.tpch import TpchConnector

        manager.register(name, TpchConnector())
    else:
        raise ValueError(f"unknown catalog kind in spec: {spec!r}")
