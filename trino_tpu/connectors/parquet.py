"""Parquet connector: directory-of-files tables (the hive-style layout).

Reference: ``plugin/trino-hive`` selecting ``lib/trino-parquet`` readers
(``HivePageSourceProvider``); splits are (file, row-group) pairs and
row-group statistics drive TupleDomain split pruning
(``TupleDomainParquetPredicate``). Layout: ``<root>/<schema>/<table>/*.parquet``;
schema is read from the first file's footer.

Writes (CTAS/INSERT) produce one parquet file per insert via the
from-scratch writer in :mod:`trino_tpu.formats.parquet`.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Sequence

from trino_tpu import types as T
from trino_tpu.columnar import Batch
from trino_tpu.connectors.api import ColumnSchema, Connector, Split, TableSchema
from trino_tpu.formats import parquet as PQ


class ParquetConnector(Connector):
    name = "parquet"
    # part-file writes land on a shared filesystem, so writer
    # tasks on any node append safely (scaled-writer eligible)
    supports_distributed_writes = True

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        # (path, mtime) -> FileMeta; footers are small and hot
        self._meta_cache: dict[tuple[str, float], PQ.FileMeta] = {}
        self._write_lock = threading.Lock()

    # --- layout -----------------------------------------------------------

    def _table_dir(self, schema: str, table: str) -> str:
        return os.path.join(self.root, schema, table)

    def _files(self, schema: str, table: str) -> list[str]:
        d = self._table_dir(schema, table)
        if not os.path.isdir(d):
            return []
        return sorted(
            os.path.join(d, f) for f in os.listdir(d) if f.endswith(".parquet")
        )

    def _meta(self, path: str) -> PQ.FileMeta:
        mtime = os.path.getmtime(path)
        key = (path, mtime)
        meta = self._meta_cache.get(key)
        if meta is None:
            with open(path, "rb") as f:
                meta = PQ.read_footer(f.read())
            self._meta_cache[key] = meta
        return meta

    # --- metadata ---------------------------------------------------------

    def list_schemas(self) -> list[str]:
        if not os.path.isdir(self.root):
            return ["default"]
        return sorted(
            {
                d
                for d in os.listdir(self.root)
                if os.path.isdir(os.path.join(self.root, d))
            }
            | {"default"}
        )

    def list_tables(self, schema: str) -> list[str]:
        d = os.path.join(self.root, schema)
        if not os.path.isdir(d):
            return []
        return sorted(
            t
            for t in os.listdir(d)
            if os.path.isdir(os.path.join(d, t))
        )

    def get_table(self, schema: str, table: str) -> Optional[TableSchema]:
        files = self._files(schema, table)
        if not files:
            return None
        meta = self._meta(files[0])
        return TableSchema(
            table,
            tuple(
                ColumnSchema(c.name, c.sql_type()) for c in meta.schema
            ),
        )

    # --- splits: one per (file, row group) --------------------------------

    def get_splits(self, schema, table, target_splits, constraint=None):
        pairs = []
        for path in self._files(schema, table):
            meta = self._meta(path)
            for rg in range(len(meta.row_groups)):
                pairs.append((path, rg))
        splits = [
            Split(table, i, len(pairs), info=pair)
            for i, pair in enumerate(pairs)
        ]
        return self.prune_splits(schema, table, splits, constraint)

    def split_stats(self, schema, table, split):
        path, rg = split.info
        return PQ.row_group_stats(self._meta(path), rg)

    def read_split(
        self, schema, table, columns: Sequence[str], split
    ) -> Batch:
        path, rg = split.info
        with open(path, "rb") as f:
            data = f.read()
        return PQ.read_batch(data, self._meta(path), rg, list(columns))

    def estimate_rows(self, schema, table) -> Optional[int]:
        files = self._files(schema, table)
        if not files:
            return None
        return sum(self._meta(p).num_rows for p in files)

    def data_version(self, schema, table):
        # part-file list + mtimes: INSERT appends a file, overwrites bump
        # mtime — either changes the device-table-cache key
        return tuple(
            (os.path.basename(p), os.path.getmtime(p))
            for p in self._files(schema, table)
        )

    def data_versions(self, schema, table):
        # one immutable uuid-named file per insert (id = basename, token =
        # mtime_ns+size): appends add pairs, rewrites change them — unlike
        # data_version()'s whole-table digest, the result cache can tell
        # which happened and maintain instead of invalidating
        if self.get_table(schema, table) is None:
            return None
        out = []
        for p in self._files(schema, table):
            try:
                st = os.stat(p)
                out.append((os.path.basename(p), (st.st_mtime_ns, st.st_size)))
            except OSError:
                out.append((os.path.basename(p), None))
        return out

    def splits_for_parts(self, schema, table, part_ids):
        want = set(part_ids)
        pairs = []
        for path in self._files(schema, table):
            if os.path.basename(path) not in want:
                continue
            meta = self._meta(path)
            for rg in range(len(meta.row_groups)):
                pairs.append((path, rg))
        return [
            Split(table, i, max(len(pairs), 1), info=pair)
            for i, pair in enumerate(pairs)
        ]

    # --- writes -----------------------------------------------------------

    def create_table(self, schema, table, schema_def: TableSchema) -> None:
        d = self._table_dir(schema, table)
        if os.path.isdir(d) and self._files(schema, table):
            raise ValueError(f"table already exists: {schema}.{table}")
        os.makedirs(d, exist_ok=True)
        self._pending_schema = schema_def  # first insert writes the footer

    def insert(self, schema, table, batch: Batch) -> int:
        d = self._table_dir(schema, table)
        if not os.path.isdir(d):
            raise KeyError(f"table not found: {schema}.{table}")
        ts = self.get_table(schema, table)
        names = (
            [c.name for c in ts.columns]
            if ts is not None
            else [c.name for c in getattr(self, "_pending_schema").columns]
        )
        with self._write_lock:
            import uuid

            # node-unique part names: concurrent writer tasks on several
            # nodes append without coordination (scaled writers)
            n = len(self._files(schema, table))
            path = os.path.join(
                d, f"part-{n:05d}-{uuid.uuid4().hex[:8]}.parquet"
            )
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                PQ.write_parquet(f, names, [batch])
            os.replace(tmp, path)
        return batch.compact().num_rows

    def drop_table(self, schema, table) -> None:
        import shutil

        d = self._table_dir(schema, table)
        if os.path.isdir(d):
            shutil.rmtree(d)
