"""In-memory connector (reference: ``plugin/trino-memory``,
``MemoryPagesStore.java:41``): CREATE TABLE AS / INSERT / scan."""

from __future__ import annotations

from typing import Sequence

from trino_tpu.columnar import Batch, concat_batches
from trino_tpu.connectors.api import Connector, Split, TableSchema


class MemoryConnector(Connector):
    name = "memory"

    def __init__(self):
        self._tables: dict[tuple[str, str], TableSchema] = {}
        self._data: dict[tuple[str, str], list[Batch]] = {}

    def list_schemas(self):
        return sorted({s for s, _ in self._tables} | {"default"})

    def list_tables(self, schema):
        return sorted(t for s, t in self._tables if s == schema)

    def get_table(self, schema, table):
        return self._tables.get((schema, table))

    def create_table(self, schema, table, schema_def):
        if (schema, table) in self._tables:
            raise ValueError(f"table already exists: {schema}.{table}")
        self._tables[(schema, table)] = schema_def
        self._data[(schema, table)] = []

    def insert(self, schema, table, batch):
        if (schema, table) not in self._tables:
            raise KeyError(f"table not found: {schema}.{table}")
        compacted = batch.compact()
        self._data[(schema, table)].append(compacted)
        return compacted.num_rows

    def drop_table(self, schema, table):
        self._tables.pop((schema, table), None)
        self._data.pop((schema, table), None)

    def estimate_rows(self, schema, table):
        parts = self._data.get((schema, table))
        if parts is None:
            return None
        return sum(b.num_rows for b in parts)

    def get_splits(self, schema, table, target_splits):
        parts = self._data.get((schema, table), [])
        n = max(1, len(parts))
        return [Split(table, i, n) for i in range(n)]

    def read_split(self, schema, table, columns: Sequence[str], split):
        ts = self._tables[(schema, table)]
        parts = self._data[(schema, table)]
        name_to_idx = {c.name: i for i, c in enumerate(ts.columns)}
        if not parts:
            import numpy as np

            from trino_tpu.columnar import Column

            cols = [
                Column(ts.columns[name_to_idx[c]].type,
                       np.zeros(0, dtype=ts.columns[name_to_idx[c]].type.storage_dtype))
                for c in columns
            ]
            return Batch(cols, 0)
        b = parts[split.index]
        cols = [b.columns[name_to_idx[c]] for c in columns]
        return Batch(cols, b.num_rows, b.sel)
