"""In-memory connector (reference: ``plugin/trino-memory``,
``MemoryPagesStore.java:41``): CREATE TABLE AS / INSERT / scan.

TPU-native twist: where the reference keeps pages pinned in worker JVM
memory, this connector can additionally stage a table into device HBM
(:meth:`device_slab`), so repeated scans stream device-resident slabs
through the step program with zero host->device traffic."""

from __future__ import annotations

from typing import Sequence

from trino_tpu.columnar import Batch, Column, concat_batches
from trino_tpu.connectors.api import Connector, Split, TableSchema


def _slice_rows(b: Batch, lo: int, hi: int) -> Batch:
    """Row-range view [lo, hi) of a stored batch (host-side slicing; row
    slices on axis 0 cover wide-decimal 2-D lanes too)."""
    cols = [
        Column(
            c.type,
            c.data[lo:hi],
            None if c.valid is None else c.valid[lo:hi],
            c.dictionary,
        )
        for c in b.columns
    ]
    return Batch(cols, hi - lo, None if b.sel is None else b.sel[lo:hi])


class MemoryConnector(Connector):
    name = "memory"

    def __init__(self):
        self._tables: dict[tuple[str, str], TableSchema] = {}
        self._data: dict[tuple[str, str], list[Batch]] = {}
        self._stats: dict[tuple[str, str], dict[int, dict]] = {}
        self._version = 0  # bumped on any mutation; keys the device cache
        self._device: dict[tuple, tuple] = {}
        # stable per-part ids for data_versions(): an INSERT appends a
        # fresh id, every other mutation re-ids (coarse `_version` is
        # connector-GLOBAL, so it alone cannot tell an append to THIS
        # table from a write to a sibling — the id list can)
        self._part_seq = 0
        self._part_ids: dict[tuple[str, str], list[int]] = {}

    def list_schemas(self):
        return sorted({s for s, _ in self._tables} | {"default"})

    def list_tables(self, schema):
        return sorted(t for s, t in self._tables if s == schema)

    def get_table(self, schema, table):
        return self._tables.get((schema, table))

    def create_table(self, schema, table, schema_def):
        if (schema, table) in self._tables:
            raise ValueError(f"table already exists: {schema}.{table}")
        self._tables[(schema, table)] = schema_def
        self._data[(schema, table)] = []
        self._part_ids[(schema, table)] = []

    def insert(self, schema, table, batch):
        if (schema, table) not in self._tables:
            raise KeyError(f"table not found: {schema}.{table}")
        compacted = batch.compact()
        self._data[(schema, table)].append(compacted)
        self._part_ids.setdefault((schema, table), []).append(self._next_part_id())
        self._stats.pop((schema, table), None)
        self._invalidate()
        return compacted.num_rows

    def _next_part_id(self) -> int:
        self._part_seq += 1
        return self._part_seq

    def _invalidate(self):
        self._version += 1
        self._device.clear()

    def device_slab(self, schema, table, columns: Sequence[str], cap: int,
                    max_bytes: int):
        """Stage the table's requested columns into device HBM as ONE slab
        padded to a multiple of ``cap`` rows (so a compiled step can
        ``dynamic_slice`` any chunk without clamping). Returns
        (slab_batch, num_rows) or None when the table exceeds
        ``max_bytes`` (the stream then falls back to host chunking).

        Cached per (columns, cap, version): repeated queries pay zero
        host->device transfer — HBM is this connector's page store."""
        import numpy as np

        parts = self._data.get((schema, table))
        if parts is None:
            return None
        key = (schema, table, tuple(columns), self._version)
        hit = self._device.get(key)
        if hit is not None and hit[0].capacity % cap == 0:
            return hit
        total_rows = sum(b.num_rows for b in parts)
        if total_rows == 0:
            return None
        ts = self._tables[(schema, table)]
        name_to_idx = {c.name: i for i, c in enumerate(ts.columns)}
        from trino_tpu.connectors.api import (
            slab_bytes_estimate,
            stage_device_slab,
        )

        nbytes = slab_bytes_estimate(
            [ts.columns[name_to_idx[c]].type for c in columns],
            total_rows, cap,
        )
        if nbytes > max_bytes:
            return None

        staged = stage_device_slab(
            [
                Batch(
                    [b.columns[name_to_idx[c]] for c in columns],
                    b.num_rows,
                    b.sel,
                )
                for b in parts
            ],
            cap,
        )
        self._device[key] = staged
        return staged

    # --- transaction snapshot support (see trino_tpu.transaction) --------

    def snapshot_state(self):
        return (
            dict(self._tables),
            {k: list(v) for k, v in self._data.items()},
        )

    def restore_state(self, snap):
        tables, data = snap
        self._tables = dict(tables)
        self._data = {k: list(v) for k, v in data.items()}
        # fresh ids for every part: a rollback is a rewrite as far as
        # cached results are concerned (conservatively invalidates)
        self._part_ids = {
            k: [self._next_part_id() for _ in v] for k, v in self._data.items()
        }
        self._stats.clear()
        self._invalidate()

    def truncate(self, schema, table):
        if (schema, table) not in self._tables:
            raise KeyError(f"table not found: {schema}.{table}")
        self._data[(schema, table)] = []
        self._part_ids[(schema, table)] = []
        self._stats.pop((schema, table), None)
        self._invalidate()

    def drop_table(self, schema, table):
        self._tables.pop((schema, table), None)
        self._data.pop((schema, table), None)
        self._part_ids.pop((schema, table), None)
        self._stats.pop((schema, table), None)
        self._invalidate()

    def estimate_rows(self, schema, table):
        parts = self._data.get((schema, table))
        if parts is None:
            return None
        return sum(b.num_rows for b in parts)

    def data_versions(self, schema, table):
        parts = self._data.get((schema, table))
        if parts is None:
            return None
        ids = self._part_ids.get((schema, table))
        if ids is None or len(ids) != len(parts):
            # parts mutated outside insert/truncate (legacy direct writes):
            # re-id everything so cached results read as fully stale
            ids = [self._next_part_id() for _ in parts]
            self._part_ids[(schema, table)] = ids
        return [(pid, b.num_rows) for pid, b in zip(ids, parts)]

    def splits_for_parts(self, schema, table, part_ids):
        parts = self._data.get((schema, table), [])
        ids = self._part_ids.get((schema, table), [])
        want = set(part_ids)
        ranges = [
            (i, 0, parts[i].num_rows)
            for i, pid in enumerate(ids)
            if pid in want and i < len(parts)
        ]
        return [Split(table, j, len(ranges), info=r) for j, r in enumerate(ranges)]

    # --- optimizer pushdown (ConnectorMetadata.applyLimit/applyAggregation)
    def apply_limit(self, schema, table, count):
        return True  # scans stop pulling stored parts once covered

    def apply_aggregation_count(self, schema, table):
        return self.estimate_rows(schema, table)  # stored parts: exact

    def get_splits(self, schema, table, target_splits, constraint=None):
        parts = self._data.get((schema, table), [])
        if not parts:
            return self.prune_splits(
                schema, table, [Split(table, 0, 1)], constraint
            )
        # subdivide large stored batches into row ranges so a table built
        # from one big INSERT still fans out across target_splits workers
        # (without this, a 2M-row single-part table lands on one shard and
        # every other shard pads to its full capacity)
        total = sum(b.num_rows for b in parts)
        chunk = max(4096, -(-total // max(1, target_splits)))
        ranges: list[tuple[int, int, int]] = []
        for i, b in enumerate(parts):
            lo = 0
            while True:
                hi = min(b.num_rows, lo + chunk)
                ranges.append((i, lo, hi))
                lo = hi
                if lo >= b.num_rows:
                    break
        splits = [
            Split(table, j, len(ranges), info=r)
            for j, r in enumerate(ranges)
        ]
        return self.prune_splits(schema, table, splits, constraint)

    @staticmethod
    def _split_range(split, parts):
        """(part_index, row_lo, row_hi) for a split; legacy splits without
        ``info`` cover their whole stored batch. Accepts a list too: the
        cluster wire round-trips ``info`` through JSON."""
        if isinstance(split.info, (tuple, list)) and len(split.info) == 3:
            part, lo, hi = split.info
            return int(part), int(lo), int(hi)
        i = split.index
        return i, 0, parts[i].num_rows if i < len(parts) else 0

    def split_stats(self, schema, table, split):
        """Per-split (stored-batch row range) min/max over numeric/date
        columns, computed lazily and cached (reference:
        MemoryMetadata#getTableStatistics)."""
        parts = self._data.get((schema, table))
        if not parts:
            return None
        part, lo, hi = self._split_range(split, parts)
        if part >= len(parts):
            return None
        cache = self._stats.setdefault((schema, table), {})
        key = (part, lo, hi)
        if key not in cache:
            from trino_tpu.connectors.api import batch_column_stats

            ts = self._tables[(schema, table)]
            b = parts[part]
            if (lo, hi) != (0, b.num_rows):
                b = _slice_rows(b, lo, hi)
            cache[key] = batch_column_stats(ts.columns, b)
        return cache[key]

    def read_split(self, schema, table, columns: Sequence[str], split):
        ts = self._tables[(schema, table)]
        parts = self._data[(schema, table)]
        name_to_idx = {c.name: i for i, c in enumerate(ts.columns)}
        if not parts:
            import numpy as np

            from trino_tpu.columnar import Column

            cols = [
                Column(ts.columns[name_to_idx[c]].type,
                       np.zeros(0, dtype=ts.columns[name_to_idx[c]].type.storage_dtype))
                for c in columns
            ]
            return Batch(cols, 0)
        part, lo, hi = self._split_range(split, parts)
        b = parts[part]
        if (lo, hi) != (0, b.num_rows):
            b = _slice_rows(b, lo, hi)
        cols = [b.columns[name_to_idx[c]] for c in columns]
        return Batch(cols, b.num_rows, b.sel)
