"""In-memory connector (reference: ``plugin/trino-memory``,
``MemoryPagesStore.java:41``): CREATE TABLE AS / INSERT / scan.

TPU-native twist: where the reference keeps pages pinned in worker JVM
memory, this connector can additionally stage a table into device HBM
(:meth:`device_slab`), so repeated scans stream device-resident slabs
through the step program with zero host->device traffic."""

from __future__ import annotations

from typing import Sequence

from trino_tpu.columnar import Batch, concat_batches
from trino_tpu.connectors.api import Connector, Split, TableSchema


class MemoryConnector(Connector):
    name = "memory"

    def __init__(self):
        self._tables: dict[tuple[str, str], TableSchema] = {}
        self._data: dict[tuple[str, str], list[Batch]] = {}
        self._stats: dict[tuple[str, str], dict[int, dict]] = {}
        self._version = 0  # bumped on any mutation; keys the device cache
        self._device: dict[tuple, tuple] = {}

    def list_schemas(self):
        return sorted({s for s, _ in self._tables} | {"default"})

    def list_tables(self, schema):
        return sorted(t for s, t in self._tables if s == schema)

    def get_table(self, schema, table):
        return self._tables.get((schema, table))

    def create_table(self, schema, table, schema_def):
        if (schema, table) in self._tables:
            raise ValueError(f"table already exists: {schema}.{table}")
        self._tables[(schema, table)] = schema_def
        self._data[(schema, table)] = []

    def insert(self, schema, table, batch):
        if (schema, table) not in self._tables:
            raise KeyError(f"table not found: {schema}.{table}")
        compacted = batch.compact()
        self._data[(schema, table)].append(compacted)
        self._stats.pop((schema, table), None)
        self._invalidate()
        return compacted.num_rows

    def _invalidate(self):
        self._version += 1
        self._device.clear()

    def device_slab(self, schema, table, columns: Sequence[str], cap: int,
                    max_bytes: int):
        """Stage the table's requested columns into device HBM as ONE slab
        padded to a multiple of ``cap`` rows (so a compiled step can
        ``dynamic_slice`` any chunk without clamping). Returns
        (slab_batch, num_rows) or None when the table exceeds
        ``max_bytes`` (the stream then falls back to host chunking).

        Cached per (columns, cap, version): repeated queries pay zero
        host->device transfer — HBM is this connector's page store."""
        import numpy as np

        parts = self._data.get((schema, table))
        if parts is None:
            return None
        key = (schema, table, tuple(columns), self._version)
        hit = self._device.get(key)
        if hit is not None and hit[0].capacity % cap == 0:
            return hit
        total_rows = sum(b.num_rows for b in parts)
        if total_rows == 0:
            return None
        ts = self._tables[(schema, table)]
        name_to_idx = {c.name: i for i, c in enumerate(ts.columns)}
        from trino_tpu.connectors.api import (
            slab_bytes_estimate,
            stage_device_slab,
        )

        nbytes = slab_bytes_estimate(
            [ts.columns[name_to_idx[c]].type for c in columns],
            total_rows, cap,
        )
        if nbytes > max_bytes:
            return None

        staged = stage_device_slab(
            [
                Batch(
                    [b.columns[name_to_idx[c]] for c in columns],
                    b.num_rows,
                    b.sel,
                )
                for b in parts
            ],
            cap,
        )
        self._device[key] = staged
        return staged

    # --- transaction snapshot support (see trino_tpu.transaction) --------

    def snapshot_state(self):
        return (
            dict(self._tables),
            {k: list(v) for k, v in self._data.items()},
        )

    def restore_state(self, snap):
        tables, data = snap
        self._tables = dict(tables)
        self._data = {k: list(v) for k, v in data.items()}
        self._stats.clear()
        self._invalidate()

    def truncate(self, schema, table):
        if (schema, table) not in self._tables:
            raise KeyError(f"table not found: {schema}.{table}")
        self._data[(schema, table)] = []
        self._stats.pop((schema, table), None)
        self._invalidate()

    def drop_table(self, schema, table):
        self._tables.pop((schema, table), None)
        self._data.pop((schema, table), None)
        self._stats.pop((schema, table), None)
        self._invalidate()

    def estimate_rows(self, schema, table):
        parts = self._data.get((schema, table))
        if parts is None:
            return None
        return sum(b.num_rows for b in parts)

    # --- optimizer pushdown (ConnectorMetadata.applyLimit/applyAggregation)
    def apply_limit(self, schema, table, count):
        return True  # scans stop pulling stored parts once covered

    def apply_aggregation_count(self, schema, table):
        return self.estimate_rows(schema, table)  # stored parts: exact

    def get_splits(self, schema, table, target_splits, constraint=None):
        parts = self._data.get((schema, table), [])
        n = max(1, len(parts))
        splits = [Split(table, i, n) for i in range(n)]
        return self.prune_splits(schema, table, splits, constraint)

    def split_stats(self, schema, table, split):
        """Per-stored-batch min/max over numeric/date columns, computed
        lazily and cached (reference: MemoryMetadata#getTableStatistics)."""
        parts = self._data.get((schema, table))
        if not parts or split.index >= len(parts):
            return None
        cache = self._stats.setdefault((schema, table), {})
        if split.index not in cache:
            from trino_tpu.connectors.api import batch_column_stats

            ts = self._tables[(schema, table)]
            cache[split.index] = batch_column_stats(ts.columns, parts[split.index])
        return cache[split.index]

    def read_split(self, schema, table, columns: Sequence[str], split):
        ts = self._tables[(schema, table)]
        parts = self._data[(schema, table)]
        name_to_idx = {c.name: i for i, c in enumerate(ts.columns)}
        if not parts:
            import numpy as np

            from trino_tpu.columnar import Column

            cols = [
                Column(ts.columns[name_to_idx[c]].type,
                       np.zeros(0, dtype=ts.columns[name_to_idx[c]].type.storage_dtype))
                for c in columns
            ]
            return Batch(cols, 0)
        b = parts[split.index]
        cols = [b.columns[name_to_idx[c]] for c in columns]
        return Batch(cols, b.num_rows, b.sel)
