"""ORC connector: directory-of-files tables (hive-style layout).

Reference: ``plugin/trino-hive`` selecting ``lib/trino-orc`` readers and
writers (``OrcReader.java:66,251``, ``OrcWriter.java``); splits are
(file, stripe) pairs and stripe statistics drive TupleDomain split
pruning (``TupleDomainOrcPredicate.java:74``). Layout:
``<root>/<schema>/<table>/*.orc``; the table schema is read from the
first file's footer. Writes (CTAS/INSERT) produce one ORC file per
insert via the from-scratch writer in :mod:`trino_tpu.formats.orc`.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import Batch
from trino_tpu.connectors.api import ColumnSchema, Connector, Split, TableSchema
from trino_tpu.formats import orc as ORC


class OrcConnector(Connector):
    name = "orc"
    # part-file writes land on a shared filesystem, so writer
    # tasks on any node append safely (scaled-writer eligible)
    supports_distributed_writes = True

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._file_cache: dict[tuple[str, float], ORC.OrcFile] = {}

    # --- layout -----------------------------------------------------------

    def _table_dir(self, schema: str, table: str) -> str:
        return os.path.join(self.root, schema, table)

    def _files(self, schema: str, table: str) -> list[str]:
        d = self._table_dir(schema, table)
        if not os.path.isdir(d):
            return []
        return sorted(
            os.path.join(d, f) for f in os.listdir(d) if f.endswith(".orc")
        )

    def _file(self, path: str) -> ORC.OrcFile:
        mtime = os.path.getmtime(path)
        key = (path, mtime)
        f = self._file_cache.get(key)
        if f is None:
            with open(path, "rb") as fh:
                f = ORC.OrcFile(fh.read())
            self._file_cache[key] = f
        return f

    # --- metadata ---------------------------------------------------------

    def list_schemas(self) -> list[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(
            d
            for d in os.listdir(self.root)
            if os.path.isdir(os.path.join(self.root, d))
        )

    def list_tables(self, schema: str) -> list[str]:
        d = os.path.join(self.root, schema)
        if not os.path.isdir(d):
            return []
        return sorted(
            t for t in os.listdir(d) if os.path.isdir(os.path.join(d, t))
        )

    def get_table(self, schema: str, table: str) -> Optional[TableSchema]:
        files = self._files(schema, table)
        if not files:
            return None
        f = self._file(files[0])
        cols = []
        for name, type_id in zip(f.column_names, f.column_type_ids):
            cols.append(ColumnSchema(name, f.types[type_id].sql_type()))
        return TableSchema(table, tuple(cols))

    # --- splits: one per (file, stripe) -----------------------------------

    def get_splits(self, schema, table, target_splits, constraint=None):
        pairs = []
        for path in self._files(schema, table):
            f = self._file(path)
            for si in range(len(f.stripes)):
                pairs.append((path, si))
        splits = [
            Split(table, i, len(pairs), info=pair)
            for i, pair in enumerate(pairs)
        ]
        return self.prune_splits(schema, table, splits, constraint)

    def split_stats(self, schema, table, split):
        """Stripe column stats -> (min, max, has_null) per column name for
        the split pruner (reference TupleDomainOrcPredicate)."""
        path, si = split.info
        f = self._file(path)
        stats = f.stripe_stats(si)
        if not stats:
            return None
        out = {}
        for name, type_id in zip(f.column_names, f.column_type_ids):
            s = stats.get(type_id)
            if s is None or s.min_value is None:
                continue
            mn, mx = s.min_value, s.max_value
            t = f.types[type_id]
            if t.kind == ORC.KIND_DECIMAL and isinstance(mn, str):
                import decimal

                # exact: float64 loses digits past ~15 significant figures
                # (and the default Decimal context rounds past 28), which
                # could prune a split that still contains matches
                scale = t.scale
                with decimal.localcontext() as ctx:
                    ctx.prec = 60
                    mn = int(decimal.Decimal(mn).scaleb(scale).to_integral_value())
                    mx = int(decimal.Decimal(mx).scaleb(scale).to_integral_value())
            out[name] = (mn, mx, s.has_null)
        return out or None

    def read_split(
        self, schema, table, columns: Sequence[str], split
    ) -> Batch:
        path, si = split.info
        f = self._file(path)
        cols = f.read_stripe(f.stripes[si], set(columns))
        out = [cols[c] for c in columns]
        n = f.stripes[si].num_rows
        return Batch(out, n)

    def estimate_rows(self, schema, table) -> Optional[int]:
        files = self._files(schema, table)
        if not files:
            return None
        return sum(self._file(p).num_rows for p in files)

    def data_version(self, schema, table):
        # file list + mtimes key the device table cache: INSERT appends a
        # file, so warm cached scans miss instead of serving stale rows
        return tuple(
            (os.path.basename(p), os.path.getmtime(p))
            for p in self._files(schema, table)
        )

    def data_versions(self, schema, table):
        # one immutable uuid-named file per insert (id = basename, token =
        # mtime_ns+size): part-level pairs let the result cache classify a
        # change as append (maintain) vs rewrite (invalidate), which the
        # whole-table data_version() digest cannot
        if self.get_table(schema, table) is None:
            return None
        out = []
        for p in self._files(schema, table):
            try:
                st = os.stat(p)
                out.append((os.path.basename(p), (st.st_mtime_ns, st.st_size)))
            except OSError:
                out.append((os.path.basename(p), None))
        return out

    def splits_for_parts(self, schema, table, part_ids):
        want = set(part_ids)
        pairs = []
        for path in self._files(schema, table):
            if os.path.basename(path) not in want:
                continue
            f = self._file(path)
            for si in range(len(f.stripes)):
                pairs.append((path, si))
        return [
            Split(table, i, max(len(pairs), 1), info=pair)
            for i, pair in enumerate(pairs)
        ]

    # --- writes: one ORC file per insert ----------------------------------

    def create_table(self, schema, table, schema_def: TableSchema) -> None:
        d = self._table_dir(schema, table)
        if os.path.isdir(d) and self._files(schema, table):
            raise ValueError(f"table already exists: {schema}.{table}")
        os.makedirs(d, exist_ok=True)
        self._pending_schema = schema_def  # first insert writes the file

    def insert(self, schema, table, batch: Batch) -> int:
        import threading

        d = self._table_dir(schema, table)
        if not os.path.isdir(d):
            raise KeyError(f"table not found: {schema}.{table}")
        lock = getattr(self, "_write_lock", None)
        if lock is None:
            lock = self._write_lock = threading.Lock()
        ts = self.get_table(schema, table)
        names = (
            [c.name for c in ts.columns]
            if ts is not None
            else [c.name for c in getattr(self, "_pending_schema").columns]
        )
        with lock:
            import uuid

            # node-unique part names: concurrent writer tasks on several
            # nodes append without coordination (scaled writers)
            n = len(self._files(schema, table))
            path = os.path.join(
                d, f"part-{n:05d}-{uuid.uuid4().hex[:8]}.orc"
            )
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                ORC.write_orc(f, names, [batch])
            os.replace(tmp, path)
        return batch.compact().num_rows

    def drop_table(self, schema, table) -> None:
        import shutil

        d = self._table_dir(schema, table)
        if os.path.isdir(d):
            shutil.rmtree(d)
