"""System catalog: runtime introspection tables.

Reference: ``core/trino-main/.../connector/system/`` —
``system.runtime.queries`` / ``system.runtime.nodes`` (and
``system.metadata.catalogs``), backed by the coordinator's live state.
"""

from __future__ import annotations

import time
from typing import Sequence

from trino_tpu import types as T
from trino_tpu.columnar import Batch
from trino_tpu.connectors.api import ColumnSchema, Connector, Split, TableSchema

_SCHEMAS: dict[str, list[tuple[str, T.SqlType]]] = {
    ("runtime", "queries"): [
        ("query_id", T.VARCHAR),
        ("state", T.VARCHAR),
        ("user", T.VARCHAR),
        ("source", T.VARCHAR),
        ("query", T.VARCHAR),
        ("elapsed_ms", T.BIGINT),
        ("peak_memory_bytes", T.BIGINT),
        ("output_rows", T.BIGINT),
    ],
    ("runtime", "nodes"): [
        ("node_id", T.VARCHAR),
        ("http_uri", T.VARCHAR),
        ("node_version", T.VARCHAR),
        ("coordinator", T.BOOLEAN),
        ("state", T.VARCHAR),
    ],
    ("runtime", "tasks"): [
        ("task_id", T.VARCHAR),
        ("state", T.VARCHAR),
        ("fragment", T.BIGINT),
        ("elapsed_ms", T.BIGINT),
        ("execution_path", T.VARCHAR),
        ("error", T.VARCHAR),
    ],
    ("runtime", "metrics"): [
        ("name", T.VARCHAR),
        ("kind", T.VARCHAR),
        ("value", T.DOUBLE),
    ],
    ("runtime", "programs"): [
        ("fingerprint", T.VARCHAR),
        ("program", T.VARCHAR),
        ("hits", T.BIGINT),
        ("misses", T.BIGINT),
        ("compile_ms", T.DOUBLE),
        ("flops", T.DOUBLE),
        ("peak_hbm_bytes", T.BIGINT),
        ("bytes_accessed", T.DOUBLE),
    ],
    ("runtime", "history"): [
        ("fingerprint", T.VARCHAR),
        ("count", T.BIGINT),
        ("elapsed_ewma_ms", T.DOUBLE),
        ("elapsed_p50_ms", T.DOUBLE),
        ("elapsed_p90_ms", T.DOUBLE),
        ("rows", T.BIGINT),
        ("overflow_retries", T.BIGINT),
        ("compile_halvings", T.BIGINT),
        ("padding_ratio", T.DOUBLE),
        ("peak_hbm_bytes", T.BIGINT),
        ("flops", T.DOUBLE),
        ("capacity_sites", T.BIGINT),
        ("path", T.VARCHAR),
    ],
    ("metadata", "catalogs"): [
        ("catalog_name", T.VARCHAR),
        ("connector_name", T.VARCHAR),
    ],
}


class SystemConnector(Connector):
    """Bound to an Engine; rows materialize live state at scan time."""

    name = "system"
    # live process state, not versioned data: never result-cacheable
    supports_result_caching = False

    def __init__(self, engine):
        self._engine = engine

    def list_schemas(self):
        return sorted({s for s, _ in _SCHEMAS})

    def list_tables(self, schema):
        return sorted(t for s, t in _SCHEMAS if s == schema)

    def get_table(self, schema, table):
        cols = _SCHEMAS.get((schema, table))
        if cols is None:
            return None
        return TableSchema(
            table, tuple(ColumnSchema(n, ty) for n, ty in cols)
        )

    def get_splits(self, schema, table, target_splits, constraint=None):
        return [Split(table, 0, 1, info=schema)]

    def read_split(self, schema, table, columns: Sequence[str], split):
        schema = split.info or schema
        spec = _SCHEMAS[(schema, table)]
        rows = self._rows(schema, table)
        names, batch = [n for n, _ in spec], Batch.from_pylist(spec, rows)
        idx = {n: i for i, n in enumerate(names)}
        cols = [batch.columns[idx[c]] for c in columns]
        return Batch(cols, batch.num_rows)

    def _rows(self, schema: str, table: str) -> list[tuple]:
        eng = self._engine
        if (schema, table) == ("runtime", "queries"):
            return [
                (
                    q["queryId"], q["state"], q["user"], q.get("source", ""),
                    q["query"], q["elapsedTimeMillis"],
                    q.get("peakMemoryBytes", 0), q.get("outputRows", 0),
                )
                for q in eng.runtime_queries()
            ]
        if (schema, table) == ("runtime", "nodes"):
            return [n for n in eng.runtime_nodes()]
        if (schema, table) == ("runtime", "tasks"):
            return [
                (
                    t["taskId"], str(t["state"]), t.get("fragment"),
                    int(float(t.get("elapsed") or 0.0) * 1000),
                    t.get("executionPath", ""), t.get("error"),
                )
                for t in eng.runtime_tasks()
            ]
        if (schema, table) == ("runtime", "metrics"):
            return list(eng.runtime_metrics())
        if (schema, table) == ("runtime", "programs"):
            return [
                (
                    p["fingerprint"], p["program"], p["hits"], p["misses"],
                    p["compile_ms"], p.get("flops"),
                    p.get("peak_hbm_bytes"), p.get("bytes_accessed"),
                )
                for p in eng.runtime_programs()
            ]
        if (schema, table) == ("runtime", "history"):
            return [
                (
                    h["fingerprint"], h.get("count", 0),
                    h.get("elapsed_ms"), h.get("elapsed_p50_ms"),
                    h.get("elapsed_p90_ms"), h.get("rows"),
                    h.get("overflow_retries", 0),
                    h.get("compile_halvings", 0),
                    h.get("padding_ratio"), h.get("peak_hbm_bytes"),
                    h.get("flops"), len(h.get("capacities") or {}),
                    h.get("path", ""),
                )
                for h in eng.runtime_history()
            ]
        if (schema, table) == ("metadata", "catalogs"):
            return [
                (name, eng.catalogs.get(name).name) for name in eng.catalogs.names()
            ]
        return []
