"""File connector: durable tables in the native pages format.

Reference tier: the storage-connector family (``plugin/trino-hive`` +
``lib/trino-orc``/``lib/trino-parquet``) — durable columnar files with
per-file statistics for split pruning. Our format is the engine's own
compressed pages wire format (:mod:`trino_tpu.serde`, PagesSerde analog):
one ``<table>/part-N.ttp`` file per inserted batch plus a JSON schema
sidecar, with min/max column stats collected at write time (the moral
equivalent of ORC stripe footers driving
``TupleDomainOrcPredicate``-style pruning).
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import Batch
from trino_tpu.connectors.api import ColumnSchema, Connector, Split, TableSchema
from trino_tpu.serde import deserialize_batch, serialize_batch

_SCHEMA_FILE = "_schema.json"
_STATS_FILE = "_stats.json"


def _retry_fnf(fn, attempts: int = 50, delay: float = 0.01):
    """Retry around replace_data's brief rename window: a concurrent DELETE
    swaps the table dir with two renames; readers landing in between see
    FileNotFoundError transiently, not table loss."""
    import time

    for i in range(attempts):
        try:
            return fn()
        except FileNotFoundError:
            if i == attempts - 1:
                raise
            time.sleep(delay)


class FileConnector(Connector):
    name = "file"
    # part-file writes land on a shared filesystem, so writer
    # tasks on any node append safely (scaled-writer eligible)
    supports_distributed_writes = True

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        # stats cache keyed by (schema, table) -> (mtime, parsed)
        self._stats_cache: dict[tuple[str, str], tuple[float, dict]] = {}

    # --- layout helpers ---------------------------------------------------

    def _table_dir(self, schema: str, table: str) -> str:
        return os.path.join(self.root, schema, table)

    def _in_swap_window(self, d: str) -> bool:
        """True while replace_data is between its two renames (the table dir
        is transiently absent but its staging/trash twin exists)."""
        return os.path.isdir(d + ".staging") or os.path.isdir(d + ".trash")

    def _await_swap(self, d: str, attempts: int = 200, delay: float = 0.01) -> None:
        import time

        for _ in range(attempts):
            if os.path.isdir(d) or not self._in_swap_window(d):
                return
            time.sleep(delay)

    @staticmethod
    def _parts_in(d: str) -> list[str]:
        if not os.path.isdir(d):
            return []
        return sorted(f for f in os.listdir(d) if f.endswith(".ttp"))

    def _parts(self, schema: str, table: str) -> list[str]:
        d = self._table_dir(schema, table)
        if not os.path.isdir(d):
            # a query planning mid-swap must not silently see an empty table
            self._await_swap(d)
        return self._parts_in(d)

    # --- metadata ---------------------------------------------------------

    def list_schemas(self):
        if not os.path.isdir(self.root):
            return ["default"]
        return sorted(
            {d for d in os.listdir(self.root)
             if os.path.isdir(os.path.join(self.root, d))} | {"default"}
        )

    def list_tables(self, schema):
        d = os.path.join(self.root, schema)
        if not os.path.isdir(d):
            return []
        return sorted(
            t for t in os.listdir(d)
            if os.path.exists(os.path.join(d, t, _SCHEMA_FILE))
        )

    def get_table(self, schema, table) -> Optional[TableSchema]:
        d = self._table_dir(schema, table)
        path = os.path.join(d, _SCHEMA_FILE)
        if not os.path.exists(path):
            self._await_swap(d)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            spec = json.load(f)
        return TableSchema(
            table,
            tuple(ColumnSchema(c["name"], T.parse_type(c["type"])) for c in spec["columns"]),
        )

    # --- DDL / write path --------------------------------------------------

    def create_table(self, schema, table, schema_def: TableSchema):
        d = self._table_dir(schema, table)
        if os.path.exists(os.path.join(d, _SCHEMA_FILE)):
            raise ValueError(f"table already exists: {schema}.{table}")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, _SCHEMA_FILE), "w") as f:
            json.dump(
                {"columns": [{"name": c.name, "type": str(c.type)} for c in schema_def.columns]},
                f,
            )

    def insert(self, schema, table, batch: Batch) -> int:
        return self.insert_part(schema, table, batch)[0]

    def insert_part(self, schema, table, batch: Batch) -> tuple[int, str]:
        """Insert returning (rows, part-file name) so scaled-writer
        coordinators can roll back committed parts when a sibling writer
        fails (reference: TableWriterOperator fragment IDs +
        TableFinishOperator commit)."""
        ts = self.get_table(schema, table)
        if ts is None:
            raise KeyError(f"table not found: {schema}.{table}")
        d = self._table_dir(schema, table)
        rows, part = self._write_part_into(d, ts, batch)
        return rows, part

    def delete_parts(self, schema, table, parts) -> None:
        """Best-effort removal of named part files (+ their stats
        entries) — the scaled-INSERT abort path."""
        d = self._table_dir(schema, table)
        for part in parts:
            if not part:
                continue
            try:
                os.remove(os.path.join(d, part))
            except OSError:
                pass
        stats_path = os.path.join(d, _STATS_FILE)
        try:
            with open(stats_path) as f:
                all_stats = json.load(f)
            for part in parts:
                all_stats.pop(part, None)
            tmp = f"{stats_path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(all_stats, f)
            os.replace(tmp, stats_path)
        except OSError:
            pass

    def _write_part_into(self, d: str, ts: TableSchema, batch: Batch) -> tuple[int, str]:
        """Write one part file + stats into an explicit directory (used by
        both the live-table insert path and replace_data staging)."""
        import uuid

        compacted = batch.compact()
        # node-unique names: concurrent scaled-writer tasks must not collide
        part = f"part-{len(self._parts_in(d)):05d}-{uuid.uuid4().hex[:8]}.ttp"
        with open(os.path.join(d, part), "wb") as f:
            f.write(serialize_batch(compacted))
        # per-file column stats (the ORC stripe-footer analog)
        from trino_tpu.connectors.api import batch_column_stats

        stats = {
            name: list(vals)
            for name, vals in batch_column_stats(ts.columns, compacted).items()
        }
        stats_path = os.path.join(d, _STATS_FILE)
        all_stats = {}
        if os.path.exists(stats_path):
            with open(stats_path) as f:
                all_stats = json.load(f)
        all_stats[part] = {"rows": compacted.num_rows, "columns": stats}
        # unique tmp per writer: scaled-writer tasks on several nodes swap
        # concurrently; a lost stats entry only disables pruning for that
        # part (split_stats -> None), never correctness
        tmp = f"{stats_path}.tmp{os.getpid()}-{uuid.uuid4().hex[:6]}"
        with open(tmp, "w") as f:  # atomic swap: a crash never truncates
            json.dump(all_stats, f)
        os.replace(tmp, stats_path)
        return compacted.num_rows, part

    def truncate(self, schema, table):
        d = self._table_dir(schema, table)
        for p in self._parts(schema, table):
            os.remove(os.path.join(d, p))
        sp = os.path.join(d, _STATS_FILE)
        if os.path.exists(sp):
            os.remove(sp)
        self._stats_cache.pop((schema, table), None)

    def replace_data(self, schema, table, batch: Batch) -> None:
        """Atomically replace the table's data (DELETE's keep-set swap):
        stage a full new table directory, then rename into place — a crash
        leaves either the old or the new data, never neither."""
        import shutil

        ts = self.get_table(schema, table)
        if ts is None:
            raise KeyError(f"table not found: {schema}.{table}")
        d = self._table_dir(schema, table)
        staging = d + ".staging"
        trash = d + ".trash"
        for tmp in (staging, trash):
            if os.path.isdir(tmp):
                shutil.rmtree(tmp)
        os.makedirs(staging)
        shutil.copy(os.path.join(d, _SCHEMA_FILE), os.path.join(staging, _SCHEMA_FILE))
        if batch.num_rows:
            self._write_part_into(staging, ts, batch)
        os.rename(d, trash)
        os.rename(staging, d)
        shutil.rmtree(trash)
        self._stats_cache.pop((schema, table), None)

    def drop_table(self, schema, table):
        import shutil

        d = self._table_dir(schema, table)
        if os.path.isdir(d):
            shutil.rmtree(d)

    # --- splits + scan -----------------------------------------------------

    def _file_stats(self, schema: str, table: str) -> dict:
        path = os.path.join(self._table_dir(schema, table), _STATS_FILE)
        if not os.path.exists(path):
            return {}
        mtime = os.path.getmtime(path)
        cached = self._stats_cache.get((schema, table))
        if cached is not None and cached[0] == mtime:
            return cached[1]
        with open(path) as f:
            parsed = json.load(f)
        self._stats_cache[(schema, table)] = (mtime, parsed)
        return parsed

    def estimate_rows(self, schema, table):
        if self.get_table(schema, table) is None:
            return None
        return sum(
            s.get("rows", 0) for s in self._file_stats(schema, table).values()
        )

    def get_splits(self, schema, table, target_splits, constraint=None):
        parts = self._parts(schema, table)
        splits = [
            Split(table, i, max(len(parts), 1), info=p)
            for i, p in enumerate(parts)
        ]
        return self.prune_splits(schema, table, splits, constraint)

    def data_version(self, schema, table):
        # part-file list + mtimes (device-table-cache key): appends and
        # rewrites both change it
        d = self._table_dir(schema, table)
        out = []
        for p in self._parts(schema, table):
            try:
                out.append((p, os.path.getmtime(os.path.join(d, p))))
            except OSError:
                out.append((p, 0.0))
        return tuple(out)

    def data_versions(self, schema, table):
        # part files are written once under uuid names (id = filename):
        # an append adds names, a rewrite swaps/mutates them — exactly the
        # data_versions() contract, with mtime_ns+size as the part token
        # (data_version()'s float mtime is a whole-table digest and too
        # coarse to tell the two apart)
        if self.get_table(schema, table) is None:
            return None
        d = self._table_dir(schema, table)
        out = []
        for p in self._parts(schema, table):
            try:
                st = os.stat(os.path.join(d, p))
                out.append((p, (st.st_mtime_ns, st.st_size)))
            except OSError:
                out.append((p, None))
        return out

    def splits_for_parts(self, schema, table, part_ids):
        want = set(part_ids)
        chosen = [p for p in self._parts(schema, table) if p in want]
        return [
            Split(table, i, max(len(chosen), 1), info=p)
            for i, p in enumerate(chosen)
        ]

    def split_stats(self, schema, table, split):
        entry = self._file_stats(schema, table).get(split.info)
        if entry is None:
            return None
        return {
            col: (mn, mx, bool(hn))
            for col, (mn, mx, hn) in entry.get("columns", {}).items()
        }

    def read_split(self, schema, table, columns: Sequence[str], split) -> Batch:
        ts = self.get_table(schema, table)
        d = self._table_dir(schema, table)

        def _read() -> bytes:
            with open(os.path.join(d, split.info), "rb") as f:
                return f.read()

        batch = deserialize_batch(_retry_fnf(_read))
        name_to_idx = {c.name: i for i, c in enumerate(ts.columns)}
        cols = [batch.columns[name_to_idx[c]] for c in columns]
        return Batch(cols, batch.num_rows)
