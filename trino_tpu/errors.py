"""Shared error classification for /v1/query info and query events.

Reference: ``spi/StandardErrorCode.java`` — every failure maps to a
stable (code, name, type) triple so clients and event listeners can
branch on class (USER_ERROR vs INTERNAL_ERROR vs
INSUFFICIENT_RESOURCES) without string-matching messages. Lives at the
top of the package (not under ``server/``) because both the engine's
event firing and the server's ManagedQuery need it without creating an
engine ↔ server import cycle.
"""

from __future__ import annotations

from typing import Tuple

GENERIC_INTERNAL_ERROR = (65536, "GENERIC_INTERNAL_ERROR", "INTERNAL_ERROR")


def classify_error(e: BaseException) -> Tuple[int, str, str]:
    """Map an exception to its (error_code, error_name, error_type).

    Imports are deferred: classification happens once per failed query,
    and the analyzer/planner modules this touches are heavyweight.
    """
    from trino_tpu.analyzer import SemanticError
    from trino_tpu.ft.retry import TaskFailure
    from trino_tpu.memory import ExceededMemoryLimitError
    from trino_tpu.obs.history import HistoryHbmRejected
    from trino_tpu.planner.sanity import PlanValidationError
    from trino_tpu.sql.lexer import SqlSyntaxError

    if isinstance(e, HistoryHbmRejected):
        # the admission gate rejected the query because its fingerprint's
        # OBSERVED peak HBM cannot fit the device — same class the
        # compile-time failure it preempts would have carried
        return (131075, "EXCEEDED_MEMORY_LIMIT", "INSUFFICIENT_RESOURCES")
    if isinstance(e, SqlSyntaxError):
        return (1, "SYNTAX_ERROR", "USER_ERROR")
    if isinstance(e, SemanticError):
        return (2, "SEMANTIC_ERROR", "USER_ERROR")
    if isinstance(e, TaskFailure):
        # a remote task attempt failed beyond what the retry policy could
        # absorb (covers TaskRetriesExhausted too)
        return (65540, "REMOTE_TASK_ERROR", "INTERNAL_ERROR")
    if isinstance(e, PlanValidationError):
        # a sanity checker rejected the plan: an engine bug, not a
        # user error — name the checker in the /v1/query error
        return (65537, "PLAN_VALIDATION_ERROR", "INTERNAL_ERROR")
    if isinstance(e, ExceededMemoryLimitError):
        return (131075, "EXCEEDED_MEMORY_LIMIT", "INSUFFICIENT_RESOURCES")
    if isinstance(e, KeyError):
        return (2, "SEMANTIC_ERROR", "USER_ERROR")
    return GENERIC_INTERNAL_ERROR
