"""trino_tpu — a TPU-native distributed SQL query engine.

A from-scratch reimplementation of the capabilities of Trino (reference:
jirassimok/trino, Trino 356-SNAPSHOT) designed TPU-first:

- Columnar batches are structs of fixed-width device arrays with validity
  masks (reference: ``core/trino-spi/src/main/java/io/trino/spi/Page.java``).
- The "codegen tier" (reference: ``core/trino-main/.../sql/gen/``) is XLA:
  expression IR is traced into jnp ops and jit-compiled.
- Group-by/joins use sort + segment-reduce formulations that map to the MXU
  and avoid scatter-heavy hash tables (reference hash specs:
  ``operator/MultiChannelGroupByHash.java``, ``operator/PagesHash.java``).
- Distribution is SPMD over a ``jax.sharding.Mesh``; Trino's HTTP shuffle
  (reference: ``execution/buffer/``, ``operator/ExchangeClient.java``)
  becomes ``lax.all_to_all``/``psum`` collectives over ICI.
"""

from trino_tpu.config import enable_x64

enable_x64()


def _enable_compile_cache() -> None:
    """Persistent XLA compile cache: plans are re-traced per query (like the
    reference re-plans per query), but identical fragment programs hit the
    on-disk XLA cache instead of recompiling."""
    import os

    try:
        import jax

        # JAX_COMPILATION_CACHE_DIR (the upstream variable; CI points it at
        # a dir pre-warmed by scripts/prewarm_cache.py) wins over the
        # package-specific override and the home-dir default
        cache = (
            os.environ.get("JAX_COMPILATION_CACHE_DIR")
            or os.environ.get("TRINO_TPU_COMPILE_CACHE")
            or os.path.join(os.path.expanduser("~"), ".cache", "trino_tpu_xla")
        )
        if cache:
            jax.config.update("jax_compilation_cache_dir", cache)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass


_enable_compile_cache()

__version__ = "0.1.0"
