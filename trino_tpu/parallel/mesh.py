"""Device mesh + sharded batch construction.

The engine uses a 1-D mesh axis ``"shards"`` for inter-chip partitioned
parallelism (Trino's FIXED_HASH_DISTRIBUTION analog). Batches are global
``jax.Array``s sharded on the row axis; padding makes per-shard row counts
equal (selection masks carry validity).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from trino_tpu.columnar import Batch, Column

AXIS = "shards"


def smap(f, mesh: Mesh, in_specs, out_specs):
    """Version-compatible shard_map (check_vma/check_rep rename across JAX)."""
    try:
        from jax import shard_map as _sm

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)
    except (ImportError, TypeError):
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False)


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:n]), (AXIS,))


def make_local_mesh() -> Mesh:
    """Mesh over this process's devices only. Inside a jax.distributed
    group, per-task execution must not span processes (its collectives
    would wait on programs the other processes never launch)."""
    return Mesh(np.asarray(jax.local_devices()), (AXIS,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def prepare_shards(mesh: Mesh, parts: Sequence[Batch]):
    """Host-side shard assembly: pad per-device parts to one capacity,
    build selection masks, unify dictionaries, remap codes.

    Shared by :func:`shard_batch` (per-column device_put) and the
    coalesced-arena ingest path (``trino_tpu/ingest.py``), so both
    produce bit-identical device batches. Returns
    ``(cap, sels, columns)`` where ``sels`` is None or per-device bool
    arrays and ``columns`` is ``[(type, dictionary, datas, valids)]``
    with ``valids`` None when every part is full-capacity all-valid.
    """
    n = mesh.devices.size
    assert len(parts) == n, f"need {n} parts, got {len(parts)}"
    cap = max(1, max(p.capacity for p in parts))
    width = parts[0].width
    # full parts with no selection need no mask — skipping it avoids the
    # host->device mask bytes entirely for full streaming chunks
    if all(p.sel is None and p.num_rows == cap == p.capacity for p in parts):
        sels = None
    else:
        sels = []
        for p in parts:
            mask = np.zeros(cap, dtype=np.bool_)
            mask[: p.num_rows] = True
            if p.sel is not None:
                local = np.zeros(cap, dtype=np.bool_)
                local[: p.capacity] = np.asarray(p.sel)
                mask &= local
            sels.append(mask)
    dictionaries = _unify_part_dictionaries(parts)
    columns = []
    for j in range(width):
        t = parts[0].columns[j].type  # same schema across parts
        datas, valids = [], []
        no_valid = all(
            p.columns[j].valid is None and p.columns[j].capacity == cap
            for p in parts
        )
        for pi, p in enumerate(parts):
            c = p.columns[j]
            data = np.asarray(c.data)
            if dictionaries[j] is not None and c.dictionary is not None:
                remap = dictionaries[j][1][pi]
                if remap is not None:
                    data = np.where(data >= 0, remap[np.maximum(data, 0)], -1).astype(
                        np.int32
                    )
            if data.shape[0] < cap:
                # wide DECIMAL columns carry (N, 2) hi/lo lanes — pad rows,
                # keep trailing dims
                pad_shape = (cap - data.shape[0],) + data.shape[1:]
                data = np.concatenate(
                    [data, np.zeros(pad_shape, dtype=data.dtype)]
                )
            datas.append(data)
            if not no_valid:
                valid = np.ones(cap, dtype=np.bool_)
                if c.valid is not None:
                    v = np.asarray(c.valid)
                    valid[: v.shape[0]] = v
                    valid[v.shape[0]:] = False
                valids.append(valid)
        d = dictionaries[j][0] if dictionaries[j] is not None else None
        columns.append((t, d, datas, None if no_valid else valids))
    return cap, sels, columns


def shard_batch(mesh: Mesh, parts: Sequence[Batch]) -> Batch:
    """Assemble per-shard host batches into one globally-sharded Batch.

    ``parts`` has one Batch per mesh device (same schema). Rows are padded
    to the max per-shard capacity; the result's ``sel`` masks padding.
    """
    n = mesh.devices.size
    cap, sels, columns = prepare_shards(mesh, parts)
    sharding = row_sharding(mesh)
    sel = None if sels is None else _global(mesh, sharding, sels)
    cols: list[Column] = []
    for t, d, datas, valids in columns:
        data_g = _global(mesh, sharding, datas)
        valid_g = None if valids is None else _global(mesh, sharding, valids)
        cols.append(Column(t, data_g, valid_g, d))
    return Batch(cols, cap * n, sel)


def _unify_part_dictionaries(parts: Sequence[Batch]):
    """Per column: merge per-part dictionaries into one; remap tables."""
    out = []
    width = parts[0].width
    for j in range(width):
        dicts = [p.columns[j].dictionary for p in parts]
        if all(d is None for d in dicts):
            out.append(None)
            continue
        base = None
        remaps = []
        for d in dicts:
            if d is None:
                remaps.append(None)
                continue
            if base is None:
                base = d
                remaps.append(None)
            elif d is base:
                remaps.append(None)
            else:
                base, remap = base.merged(d)
                remaps.append(remap)
        out.append((base, remaps))
    return out


def _global(mesh: Mesh, sharding: NamedSharding, arrs: list[np.ndarray]) -> jax.Array:
    """Build a global sharded array from per-device host shards.

    Multi-host: each process device_puts only the shards of its own
    addressable devices; the global shape covers all of them (every
    process computes identical ``arrs``, see SpmdRunner)."""
    me = jax.process_index()
    singles = [
        jax.device_put(a, d)
        for a, d in zip(arrs, list(mesh.devices.flat))
        if d.process_index == me
    ]
    shape = (sum(a.shape[0] for a in arrs),) + arrs[0].shape[1:]
    return jax.make_array_from_single_device_arrays(shape, sharding, singles)
