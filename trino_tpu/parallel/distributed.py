"""Distributed (mesh-SPMD) executor.

Reference: Trino's distributed execution — stages over workers
(``SqlQueryScheduler.java:538``), partitioned/broadcast joins
(``DetermineJoinDistributionType.java``), partial/final aggregation split
(``AggregationNode`` steps + ``spi/function`` combine contract).

TPU translation:
- scans: splits assigned round-robin to mesh shards (SOURCE_DISTRIBUTION)
- filter/project: elementwise on row-sharded global arrays (sharding
  propagates; XLA fuses)
- aggregation: per-shard partial (shard_map sort+segment-reduce) ->
  small partial tables gathered -> final re-aggregation (combine)
- joins: broadcast (all_gather build side) or partitioned
  (lax.all_to_all hash repartition of both sides) chosen by size
- sort/topN/limit/output: final gather (SINGLE_DISTRIBUTION analog)
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as PS

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column, bucket_capacity
from trino_tpu.config import Session
from trino_tpu.connectors.api import CatalogManager
from trino_tpu.exec.local import ExecutionError, LocalExecutor, Result
from trino_tpu.ops import join as J
from trino_tpu.ops.aggregation import AggSpec, group_aggregate
from trino_tpu.parallel.mesh import AXIS, make_mesh, shard_batch, smap
from trino_tpu.parallel import exchange as X
from trino_tpu.planner import plan as P


class DistributedExecutor(LocalExecutor):
    """Executes logical plans SPMD over a device mesh."""

    def __init__(
        self,
        catalogs: CatalogManager,
        session: Session,
        mesh: Optional[Mesh] = None,
        memory_ctx=None,
    ):
        super().__init__(catalogs, session, memory_ctx=memory_ctx)
        self.mesh = mesh or make_mesh()
        # per-query exchange observability (surfaced via /v1/query as
        # exchangeStats); the fused executor adds traced counters, the
        # interpreter path bumps these host-side
        self.exchange_stats: dict = {
            "exchanges": 0,
            "shuffle_rows": 0,
            "padded_shuffle_rows": 0,
            "shuffle_bytes": 0,
            "hot_keys": 0,
            "salted_rows": 0,
            "overflow_retries": 0,
            # dispatched compiled programs on the surviving attempt
            # (whole-pipeline fusion exists to push this toward 1) and
            # fragments that executed inside fused multi-fragment programs
            "dispatchRoundTrips": 0,
            "fusedFragments": 0,
            # RESOURCE_EXHAUSTED compile failures recovered by halving
            # capacities (exec/fragments.py::_Caps.shrink_all)
            "compile_halvings": 0,
        }
        # device-level profiling (obs/profiler.py): per-program XLA
        # cost/memory stats keyed by a stable program label. The fused
        # executor fills this at fragment compile time; this eager path
        # captures its shard_map programs via _profiled_call.
        self.device_stats: dict[str, dict] = {}
        self._device_profiling = bool(session.get("device_profiling"))
        self._profiled_cache: dict = {}  # (label, arg shapes) -> Compiled

    @property
    def n_shards(self) -> int:
        return self.mesh.devices.size

    # === device profiling ===============================================

    def _record_device_stats(
        self, label: str, ds: Optional[dict] = None, compile_ms: float = 0.0
    ) -> None:
        """Fold one program execution's captured XLA stats into the
        per-query map and export the per-program gauges. Called with
        ``ds=None`` for executions of an already-profiled program."""
        ent = self.device_stats.setdefault(
            label, {"executions": 0, "compile_ms": 0.0}
        )
        ent["executions"] += 1
        if compile_ms:
            ent["compile_ms"] = round(ent["compile_ms"] + compile_ms, 3)
        for k, v in (ds or {}).items():
            ent[k] = v
        if ds:
            from trino_tpu.obs.metrics import get_registry

            reg = get_registry()
            if "flops" in ds:
                reg.gauge("trino_tpu_program_flops", fragment=label).set(
                    ds["flops"]
                )
            if "peak_hbm_bytes" in ds:
                reg.gauge(
                    "trino_tpu_program_peak_hbm_bytes", fragment=label
                ).set(ds["peak_hbm_bytes"])

    def device_stats_snapshot(self) -> Optional[dict]:
        """Per-query device-profiling rollup (engine attaches this to the
        statement result; /v1/query serves it as ``deviceStats``)."""
        if not self.device_stats:
            return None
        from trino_tpu.obs.profiler import rollup_device_stats

        snap = rollup_device_stats(self.device_stats)
        snap["programs"] = {k: dict(v) for k, v in self.device_stats.items()}
        return snap

    def _profiled_call(self, label: str, fn, *args):
        """Run one eager shard_map program; with ``device_profiling`` on
        it is AOT-compiled (``jax.jit`` of the same function — identical
        numerics) so XLA cost/memory analysis lands in
        ``device_stats[label]``. Compiled executables are cached per
        argument shapes; any failure falls back to the plain eager call,
        so profiling can never fail a query."""
        if not self._device_profiling:
            return fn(*args)
        import time as _time

        try:
            from trino_tpu.obs.profiler import capture_device_stats

            shapes = tuple(
                (getattr(a, "shape", None), str(getattr(a, "dtype", "")))
                for a in jax.tree_util.tree_leaves(args)
            )
            key = (label, shapes)
            compiled = self._profiled_cache.get(key)
            if compiled is None:
                t0 = _time.perf_counter()
                compiled = jax.jit(fn).lower(*args).compile()
                compile_ms = (_time.perf_counter() - t0) * 1000.0
                self._record_device_stats(
                    label, capture_device_stats(compiled), compile_ms
                )
                if len(self._profiled_cache) >= 64:
                    self._profiled_cache.pop(next(iter(self._profiled_cache)))
                self._profiled_cache[key] = compiled
            else:
                self._record_device_stats(label)
            return compiled(*args)
        except Exception:  # noqa: BLE001 — profiling must never fail a query
            return fn(*args)

    # === scan: splits round-robin over shards ===========================
    def _exec_tablescan(self, node: P.TableScan) -> Result:
        from trino_tpu.columnar import concat_batches

        connector = self.catalogs.get(node.catalog)
        n = self.n_shards
        splits = connector.get_splits(
            node.schema, node.table, target_splits=n * 4, constraint=node.constraint
        )
        if not splits:  # constraint pruned everything
            # shard-compatible empty: one unselected row per shard (a
            # 0-capacity batch would feed zero-sized operands into
            # shard_map programs, which the partitioner rejects)
            from trino_tpu.columnar import Dictionary as _Dict

            parts = []
            for _ in range(n):
                cols = []
                for s in node.symbols:
                    wide = isinstance(s.type, T.DecimalType) and s.type.wide
                    shape = (1, 2) if wide else (1,)
                    cols.append(
                        Column(
                            s.type,
                            np.zeros(shape, dtype=s.type.storage_dtype),
                            None,
                            _Dict([]) if T.is_string(s.type) else None,
                        )
                    )
                parts.append(Batch(cols, 1, np.zeros(1, dtype=np.bool_)))
            return Result(
                shard_batch(self.mesh, parts),
                {s.name: i for i, s in enumerate(node.symbols)},
            )
        layout = {s.name: i for i, s in enumerate(node.symbols)}
        stats = self.ingest_stats
        stats.setdefault("h2d_bytes", 0)

        # device table cache: a warm repeat scan of an unchanged table
        # returns the HBM-resident batch — zero decode, zero H2D
        cache_key = None
        if self.table_cache is not None and self.session.get("table_cache"):
            from trino_tpu.ingest import table_cache_key

            cache_key = table_cache_key(
                node.catalog,
                node.schema,
                node.table,
                connector.data_version(node.schema, node.table),
                node.column_names,
                splits,
                self.mesh,
            )
            cached = self.table_cache.lookup(cache_key)
            if cached is not None:
                stats["table_cache_hits"] = stats.get("table_cache_hits", 0) + 1
                return Result(cached, layout)
            stats["table_cache_misses"] = (
                stats.get("table_cache_misses", 0) + 1
            )

        import time as _time

        from trino_tpu.obs.trace import get_tracer

        t0 = _time.perf_counter()
        per_shard: list[list[Batch]] = [[] for _ in range(n)]
        for i, b in enumerate(
            self._read_splits(
                connector, node.schema, node.table, node.column_names, splits
            )
        ):
            per_shard[i % n].append(b)
        get_tracer().record(
            "ingest.decode",
            (_time.perf_counter() - t0) * 1000.0,
            attrs={"table": node.table, "splits": len(splits)},
        )
        parts = []
        empty_proto = None
        for shard_batches in per_shard:
            if shard_batches:
                parts.append(
                    concat_batches(shard_batches)
                    if len(shard_batches) > 1
                    else shard_batches[0]
                )
                empty_proto = parts[-1]
            else:
                parts.append(None)
        for i, p in enumerate(parts):
            if p is None:
                cols = [
                    Column(c.type, np.zeros(0, dtype=np.asarray(c.data).dtype), None, c.dictionary)
                    for c in empty_proto.columns
                ]
                parts[i] = Batch(cols, 0)
        if self.session.get("coalesced_h2d"):
            from trino_tpu.ingest import shard_batch_coalesced

            batch = shard_batch_coalesced(
                self.mesh,
                parts,
                use_native=bool(self.session.get("native_decode")),
                stats=stats,
                min_bytes=int(self.session.get("coalesce_min_bytes")),
            )
        else:
            batch = shard_batch(self.mesh, parts)

        if cache_key is not None:
            from trino_tpu.memory import batch_nbytes

            peak_hint = max(
                (
                    v.get("peak_hbm_bytes", 0)
                    for v in self.device_stats.values()
                ),
                default=0,
            )
            self.table_cache.admit(
                cache_key,
                batch,
                batch_nbytes(batch),
                max_bytes=int(self.session.get("table_cache_max_bytes")),
                peak_hbm_hint=peak_hint,
            )
        return Result(batch, layout)

    # === partial/final aggregation ======================================
    def _exec_aggregate(self, node: P.Aggregate) -> Result:
        res = self._exec(node.source)
        if not _is_sharded(res.batch):
            return self._aggregate_result(node, res)
        if any(
            fn.distinct or fn.kind == "array_agg" for _, fn in node.aggregates
        ):
            # DISTINCT / array_agg aggregates need a global view — run the
            # single-program path (XLA gathers as needed).
            return self._aggregate_result(node, res)
        if any(
            isinstance(fn.result_type, T.DecimalType) and fn.result_type.wide
            for _, fn in node.aggregates
        ) or any(
            isinstance(k.type, T.DecimalType) and k.type.wide
            for k in node.group_keys
        ):
            # wide DECIMAL sums/keys use 128-bit (hi, lo) lanes whose shapes
            # the stacked partial/combine path below does not carry; the
            # single-program path is exact (XLA shards the segment sums)
            return self._aggregate_result(node, res)
        if not node.group_keys:
            # global agg: compute per-shard partials via masked group-by with
            # a single dummy key, then combine on host
            return self._global_agg_distributed(node, res)

        sel = res.batch.selection_mask()
        keys = [res.pair(k) for k in node.group_keys]
        key_dicts = [res.column(k).dictionary for k in node.group_keys]
        agg_inputs, specs, string_aggs = self._prepare_agg_inputs(node, res)
        n = self.n_shards
        nkeys = len(keys)
        G = 1 << 12
        return self._partial_final_agg(
            node, keys, key_dicts, sel, agg_inputs, specs, string_aggs, G, n, nkeys
        )

    def _partial_final_agg(
        self, node, keys, key_dicts, sel, agg_inputs, specs, string_aggs, G, n, nkeys
    ) -> Result:

        in_specs = tuple(PS(AXIS) for _ in range(2 * nkeys + 1)) + tuple(
            PS(AXIS) for _ in range(sum(2 if p else 0 for p in agg_inputs))
        )

        flat_inputs = []
        for kd, kv in keys:
            flat_inputs.extend([kd, kv])
        flat_inputs.append(sel)
        for p in agg_inputs:
            if p is not None:
                flat_inputs.extend([p[0], p[1]])

        shapes = [bool(p) for p in agg_inputs]

        def partial_agg(*flat):
            i = 0
            local_keys = []
            for _ in range(nkeys):
                local_keys.append((flat[i], flat[i + 1]))
                i += 2
            local_sel = flat[i]
            i += 1
            local_inputs = []
            for has in shapes:
                if has:
                    local_inputs.append((flat[i], flat[i + 1]))
                    i += 2
                else:
                    local_inputs.append(None)
            (kd, kv), results, ng, ovf = group_aggregate(
                local_keys, local_sel, local_inputs, specs, G
            )
            # normalize results to (value, count) pairs — kept as separate
            # arrays (no dtype-unifying stack: int64 sums must stay exact)
            flat_vals = []
            flat_cnts = []
            for spec, r in zip(specs, results):
                if spec.kind in ("count", "count_star"):
                    flat_vals.append(r.astype(jnp.int64))
                    flat_cnts.append(r.astype(jnp.int64))
                else:
                    flat_vals.append(r[0])
                    flat_cnts.append(r[1])
            key_data = jnp.stack([kd[i2].astype(jnp.int64) for i2 in range(nkeys)])
            key_valid = jnp.stack([kv[i2] for i2 in range(nkeys)])
            live = jnp.arange(G) < ng
            ovf_any = jax.lax.pmax(ovf.astype(jnp.int32), AXIS)
            return key_data.T, key_valid.T, tuple(flat_vals), tuple(flat_cnts), live, ovf_any

        mapped = smap(
            partial_agg,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(
                PS(AXIS),
                PS(AXIS),
                tuple(PS(AXIS) for _ in specs),
                tuple(PS(AXIS) for _ in specs),
                PS(AXIS),
                PS(),
            ),
        )
        key_data_g, key_valid_g, vals_g, cnts_g, live_g, ovf_g = (
            self._profiled_call("partial_agg", mapped, *flat_inputs)
        )
        if bool(np.asarray(ovf_g).max()):
            # some shard exceeded G groups — retry with larger capacity
            if G > (1 << 24):
                raise ExecutionError("per-shard group cardinality too large")
            return self._partial_final_agg(
                node, keys, key_dicts, sel, agg_inputs, specs, string_aggs,
                G << 2, n, nkeys,
            )
        # host-side final combine over n*G partial rows (small)
        kd_np = np.asarray(key_data_g)
        kv_np = np.asarray(key_valid_g)
        vals_np = np.stack([np.asarray(v) for v in vals_g], axis=1)
        cnts_np = np.stack([np.asarray(c) for c in cnts_g], axis=1)
        live_np = np.asarray(live_g)
        return self._final_combine(
            node, kd_np, kv_np, vals_np, cnts_np, live_np, key_dicts, string_aggs
        )

    def _prepare_agg_inputs(self, node, res):
        from trino_tpu.columnar import Dictionary

        agg_inputs = []
        specs = []
        string_aggs: list = []
        for _, fn in node.aggregates:
            if fn.kind == "count_star":
                if fn.filter is not None:
                    fc = res.column(P.Symbol(fn.filter.name, T.BOOLEAN))
                    ones = jnp.ones_like(fc.data, dtype=jnp.int64)
                    agg_inputs.append((ones, fc.data & fc.valid_mask()))
                    specs.append(AggSpec("count"))
                    string_aggs.append(None)
                    continue
                pair = None
                string_aggs.append(None)
            else:
                sym = P.Symbol(fn.argument.name, fn.argument.type)
                c = res.column(sym)
                data, valid = c.data, c.valid_mask()
                if c.dictionary is not None and fn.kind in ("min", "max"):
                    from trino_tpu.exec.local import rank_codes

                    data = rank_codes(c.dictionary, data)
                    string_aggs.append(c.dictionary)
                else:
                    string_aggs.append(None)
                if fn.filter is not None:
                    fc = res.column(P.Symbol(fn.filter.name, T.BOOLEAN))
                    valid = valid & fc.data & fc.valid_mask()
                pair = (data, valid)
            agg_inputs.append(pair)
            specs.append(AggSpec(fn.kind))
        return agg_inputs, specs, string_aggs

    def _final_combine(
        self, node, kd_np, kv_np, vals_np, cnts_np, live_np, key_dicts, string_aggs
    ) -> Result:
        """Combine per-shard partial aggregates (Trino's combine step)."""
        rows = live_np
        kd_np = kd_np[rows]
        kv_np = kv_np[rows]
        vals_np = vals_np[rows]
        cnts_np = cnts_np[rows]
        m = kd_np.shape[0]
        keys = [
            (jnp.asarray(kd_np[:, i]), jnp.asarray(kv_np[:, i]))
            for i in range(len(node.group_keys))
        ]
        combine_inputs = []
        combine_specs = []
        for i, (_, fn) in enumerate(node.aggregates):
            v = jnp.asarray(vals_np[:, i])
            c = jnp.asarray(cnts_np[:, i])
            if fn.kind in ("count", "count_star"):
                combine_inputs.append((v, jnp.ones(m, bool)))
                combine_specs.append(AggSpec("sum"))
            elif fn.kind in ("sum", "avg"):
                combine_inputs.append((v, c > 0))
                combine_specs.append(AggSpec("sum"))
                combine_inputs.append((c, jnp.ones(m, bool)))
                combine_specs.append(AggSpec("sum"))
            else:  # min/max
                combine_inputs.append((v, c > 0))
                combine_specs.append(AggSpec(fn.kind))
                combine_inputs.append((c, jnp.ones(m, bool)))
                combine_specs.append(AggSpec("sum"))
        max_groups = max(1 << 12, bucket_capacity(max(m, 1)))
        sel = jnp.ones(m, bool) if m else jnp.zeros(0, bool)
        if m == 0:
            # no groups anywhere
            cols = [
                Column(k.type, np.zeros(0, dtype=k.type.storage_dtype), None, d)
                for k, d in zip(node.group_keys, key_dicts)
            ]
            for s, fn in node.aggregates:
                cols.append(Column(fn.result_type, np.zeros(0, dtype=fn.result_type.storage_dtype)))
            return Result(
                Batch(cols, 0),
                {s.name: i for i, s in enumerate(node.output_symbols)},
            )
        (fkd, fkv), fres, ng, ovf = group_aggregate(
            keys, sel, combine_inputs, combine_specs, max_groups
        )
        if bool(ovf):
            raise ExecutionError("final aggregation overflow")
        ng = int(ng)
        cols = []
        for i, k in enumerate(node.group_keys):
            valid = np.asarray(fkv[i])[:ng]
            cols.append(
                Column(
                    k.type,
                    np.asarray(fkd[i])[:ng].astype(k.type.storage_dtype),
                    None if valid.all() else valid,
                    key_dicts[i],
                )
            )
        # reassemble per-aggregate results from the combine outputs
        j = 0
        raw_results = []
        for _, fn in node.aggregates:
            if fn.kind in ("count", "count_star"):
                ssum, _cnt = fres[j]
                raw_results.append(np.asarray(ssum)[:ng])
                j += 1
            else:
                vsum, _vcnt = fres[j]
                csum, _ccnt = fres[j + 1]
                raw_results.append((np.asarray(vsum)[:ng], np.asarray(csum)[:ng]))
                j += 2
        cols.extend(
            self._finalize_aggs(node, raw_results, ng, None, string_aggs)
        )
        return Result(
            Batch(cols, ng), {s.name: i for i, s in enumerate(node.output_symbols)}
        )

    def _global_agg_distributed(self, node: P.Aggregate, res: Result) -> Result:
        # add a constant group key, reuse grouped path, then strip it
        dummy = P.Symbol(P.fresh_name("g0"), T.BIGINT)
        ones = jnp.zeros(res.batch.capacity, dtype=jnp.int64)
        cols = list(res.batch.columns) + [Column(T.BIGINT, ones)]
        layout = dict(res.layout)
        layout[dummy.name] = len(cols) - 1
        res2 = Result(Batch(cols, res.batch.num_rows, res.batch.sel), layout)
        node2 = P.Aggregate(node.source, [dummy], node.aggregates, node.step)
        sel = res2.batch.selection_mask()
        keys = [res2.pair(dummy)]
        agg_inputs, specs, string_aggs = self._prepare_agg_inputs(node2, res2)
        out = self._partial_final_agg(
            node2, keys, [None], sel, agg_inputs, specs, string_aggs,
            8, self.n_shards, 1,
        )
        # drop the dummy key column; single row (or zero -> one null row)
        b = out.batch
        agg_cols = b.columns[1:]
        if b.num_rows == 0:
            cols = []
            for (s, fn) in node.aggregates:
                if fn.kind in ("count", "count_star"):
                    cols.append(Column(fn.result_type, np.asarray([0], dtype=np.int64)))
                else:
                    cols.append(
                        Column(
                            fn.result_type,
                            np.zeros(1, dtype=fn.result_type.storage_dtype),
                            np.asarray([False]),
                        )
                    )
            return Result(
                Batch(cols, 1),
                {s.name: i for i, (s, _) in enumerate(node.aggregates)},
            )
        return Result(
            Batch(agg_cols, b.num_rows),
            {s.name: i for i, (s, _) in enumerate(node.aggregates)},
        )

    # === joins ==========================================================
    def _exec_join(self, node: P.Join) -> Result:
        if node.join_type in ("CROSS", "SEMI", "ANTI", "RIGHT", "FULL"):
            return super()._exec_join(node)
        if node.join_type == "LEFT" and node.filter is not None:
            # ON-clause filters on outer joins need the null-extension
            # repair implemented in the local join path
            return super()._exec_join(node)
        if node.single_row:
            # correlated scalar subquery: the local path enforces the
            # one-match-per-row error semantics (EnforceSingleRowNode)
            return super()._exec_join(node)
        right = self._exec(node.right)  # build first: enables dynamic filter
        left = self._exec(self._apply_dynamic_filters(node, right))
        if not (_is_sharded(left.batch) or _is_sharded(right.batch)):
            return self._local_join(node, left, right)
        if not node.criteria:
            return super()._exec_join(node)

        lkeys, rkeys = self._join_keys(left, right, node.criteria)
        ph, pv = J.hash_keys(lkeys)
        bh, bv = J.hash_keys(rkeys)

        threshold = self.session.get("broadcast_join_threshold_rows")
        forced = self.session.get("join_distribution_type")
        build_rows = right.batch.count_rows()
        broadcast = build_rows <= threshold
        if forced == "PARTITIONED":
            broadcast = False
        elif forced == "BROADCAST":
            broadcast = True
        if node.distribution == "partitioned":
            broadcast = False
        elif node.distribution == "replicated":
            broadcast = True

        if broadcast:
            return self._broadcast_join(node, left, right, lkeys, rkeys, ph, pv, bh, bv)
        return self._partitioned_join(node, left, right)

    def _local_join(self, node, left, right):
        return self._join_result(node, left, right)

    def _broadcast_join(self, node, left, right, lkeys, rkeys, ph, pv, bh, bv):
        mesh = self.mesh
        n = self.n_shards
        # replicate build side (arrays + selection)
        build_arrays = []
        build_schema = []
        for s in node.right.output_symbols:
            c = right.column(s)
            build_arrays.append(_as_global(mesh, c.data))
            build_arrays.append(_as_global(mesh, c.valid_mask()))
            build_schema.append((s, c.dictionary))
        build_key_arrays = []
        for kd, kv in rkeys:
            build_key_arrays.append(_as_global(mesh, kd))
            build_key_arrays.append(_as_global(mesh, kv))
        bsel = right.batch.selection_mask()
        all_build, bsel_rep = X.broadcast_all(
            mesh, build_arrays + build_key_arrays + [_as_global(mesh, bh)], _as_global(mesh, bsel)
        )
        nb = len(build_arrays)
        rep_build_cols = all_build[:nb]
        rep_build_keys = all_build[nb:-1]
        rep_bh = all_build[-1]

        probe_sel = left.batch.selection_mask()
        probe_rows = left.batch.count_rows()
        per_shard_cap = bucket_capacity(max(1024, (probe_rows * 3) // max(n, 1)))

        probe_cols = []
        probe_schema = []
        for s in node.left.output_symbols:
            c = left.column(s)
            probe_cols.append(c.data)
            probe_cols.append(c.valid_mask())
            probe_schema.append((s, c.dictionary))
        probe_key_arrays = []
        for kd, kv in lkeys:
            probe_key_arrays.append(kd)
            probe_key_arrays.append(kv)

        join_type = node.join_type
        nlk = len(lkeys)

        while True:
            out = _sharded_probe(
                mesh,
                probe_cols,
                probe_key_arrays,
                ph,
                probe_sel,
                rep_build_cols,
                rep_build_keys,
                rep_bh,
                bsel_rep,
                per_shard_cap,
                join_type,
                nlk,
                profiler=self._profiled_call,
            )
            out_cols, out_sel, overflow = out
            if not bool(np.asarray(overflow).max()):
                break
            per_shard_cap <<= 1
        cols: list[Column] = []
        layout: dict[str, int] = {}
        i = 0
        for s, d in probe_schema:
            cols.append(Column(s.type, out_cols[i], out_cols[i + 1], d))
            layout[s.name] = len(cols) - 1
            i += 2
        for s, d in build_schema:
            cols.append(Column(s.type, out_cols[i], out_cols[i + 1], d))
            layout[s.name] = len(cols) - 1
            i += 2
        total = out_cols[0].shape[0]
        result = Result(Batch(cols, total, out_sel), layout)
        if node.filter is not None:
            from trino_tpu.compiler import ExprCompiler

            expr = self._bind(node.filter, result.layout)
            mask = ExprCompiler(
                result.batch.columns, params=getattr(self, "_params", None)
            ).predicate_mask(expr)
            result = Result(
                Batch(result.batch.columns, total, mask & out_sel), layout
            )
        return result

    def _partitioned_join(self, node, left, right):
        """Repartition both sides by join-key hash, then shard-local join."""
        mesh = self.mesh
        lkeys, rkeys = self._join_keys(left, right, node.criteria)
        ph, _pv = J.hash_keys(lkeys)
        bh, _bv = J.hash_keys(rkeys)

        def flatten(side_res, side_node, keys, khash):
            arrays = []
            schema = []
            for s in side_node.output_symbols:
                c = side_res.column(s)
                arrays.append(_as_global(mesh, c.data))
                arrays.append(_as_global(mesh, c.valid_mask()))
                schema.append((s, c.dictionary))
            for kd, kv in keys:
                arrays.append(_as_global(mesh, kd))
                arrays.append(_as_global(mesh, kv))
            arrays.append(_as_global(mesh, khash))
            return arrays, schema

        larrs, lschema = flatten(left, node.left, lkeys, ph)
        rarrs, rschema = flatten(right, node.right, rkeys, bh)
        lsel = _as_global(mesh, left.batch.selection_mask())
        rsel = _as_global(mesh, right.batch.selection_mask())

        n = self.n_shards
        hybrid = None
        if node.join_type in ("INNER", "LEFT") and bool(
            self.session.get("skew_handling")
        ):
            hybrid = self._hybrid_repartition(mesh, larrs, lsel, rarrs, rsel)
        if hybrid is not None:
            lout, lsel2, rout, rsel2 = hybrid
        else:
            # size buckets exactly (one cheap counting pass beats overflow
            # retries — each retry re-traces the exchange program)
            lbucket = bucket_capacity(X.needed_bucket(mesh, larrs[-1], lsel), minimum=8)
            rbucket = bucket_capacity(X.needed_bucket(mesh, rarrs[-1], rsel), minimum=8)
            lout, lsel2, lovf = X.hash_repartition(mesh, larrs, larrs[-1], lsel, lbucket)
            rout, rsel2, rovf = X.hash_repartition(mesh, rarrs, rarrs[-1], rsel, rbucket)
            assert not bool(np.asarray(lovf).max()) and not bool(np.asarray(rovf).max())
            st = self.exchange_stats
            st["exchanges"] += 2
            st["padded_shuffle_rows"] += n * n * (lbucket + rbucket)
            st["shuffle_rows"] += int(
                np.asarray(lsel).sum() + np.asarray(rsel).sum()
            )

        # build shard-local Results and delegate to the local join kernel via
        # shard_map: both sides now co-partitioned by key hash
        nlk = len(lkeys)  # wide criteria expand into two lane pairs
        probe_cols = lout[: 2 * len(lschema)]
        probe_keys = lout[2 * len(lschema) : -1]
        ph2 = lout[-1]
        build_cols = rout[: 2 * len(rschema)]
        build_keys = rout[2 * len(rschema) : -1]
        bh2 = rout[-1]
        per_shard_cap = bucket_capacity(
            max(1024, 2 * (left.batch.count_rows() + right.batch.count_rows()) // max(n, 1))
        )
        while True:
            out_cols, out_sel, overflow = _sharded_probe(
                mesh,
                probe_cols,
                probe_keys,
                ph2,
                lsel2,
                build_cols,
                build_keys,
                bh2,
                rsel2,
                per_shard_cap,
                node.join_type,
                nlk,
                build_sharded=True,
                profiler=self._profiled_call,
            )
            if not bool(np.asarray(overflow).max()):
                break
            per_shard_cap <<= 1
        cols: list[Column] = []
        layout: dict[str, int] = {}
        i = 0
        for s, d in lschema:
            cols.append(Column(s.type, out_cols[i], out_cols[i + 1], d))
            layout[s.name] = len(cols) - 1
            i += 2
        for s, d in rschema:
            cols.append(Column(s.type, out_cols[i], out_cols[i + 1], d))
            layout[s.name] = len(cols) - 1
            i += 2
        total = out_cols[0].shape[0]
        result = Result(Batch(cols, total, out_sel), layout)
        if node.filter is not None:
            from trino_tpu.compiler import ExprCompiler

            expr = self._bind(node.filter, result.layout)
            mask = ExprCompiler(
                result.batch.columns, params=getattr(self, "_params", None)
            ).predicate_mask(expr)
            result = Result(Batch(result.batch.columns, total, mask & out_sel), layout)
        return result

    def _hybrid_repartition(self, mesh, larrs, lsel, rarrs, rsel):
        """Skew-aware hybrid exchange for a partitioned join (interpreter
        path, eager): detect heavy hitters over the probe-side key hashes,
        keep hot probe rows on their source shard, replicate just the hot
        build slice, and repartition the cold remainder through exactly
        sized two-tier buckets. Returns None when no key is hot (caller
        falls back to the plain exact-bucket exchange)."""
        from trino_tpu.ops import skew as SK

        k = max(1, int(self.session.get("skew_hot_k")))
        frac = float(self.session.get("skew_hot_threshold_frac"))
        hh, hv, n_hot, _total = SK.hot_key_hashes(mesh, larrs[-1], lsel, k, frac)
        if int(np.asarray(n_hot).max()) == 0:
            return None
        lcold, lhot = X.skew_split_counts(mesh, larrs[-1], lsel, hh, hv)
        rcold, rhot = X.skew_split_counts(mesh, rarrs[-1], rsel, hh, hv)
        lb = bucket_capacity(lcold, minimum=8)
        rb = bucket_capacity(rcold, minimum=8)
        lhot_cap = bucket_capacity(lhot, minimum=8)
        rhot_cap = bucket_capacity(rhot, minimum=8)
        # cold buckets are exact, so the spill tier is vestigial-minimal
        lout, lsel2, lflags, lcnt, _ = X.skewed_repartition(
            mesh, larrs, larrs[-1], lsel, lb, 8,
            hot_mode="local", hot_cap=lhot_cap, hot_set=(hh, hv),
        )
        rout, rsel2, rflags, rcnt, _ = X.skewed_repartition(
            mesh, rarrs, rarrs[-1], rsel, rb, 8,
            hot_mode="replicate", hot_cap=rhot_cap, hot_set=(hh, hv),
        )
        assert not any(
            bool(np.asarray(f).max()) for f in (*lflags, *rflags)
        )
        n = mesh.devices.size
        st = self.exchange_stats
        st["exchanges"] += 2
        st["hot_keys"] += int(np.asarray(n_hot).max())
        st["shuffle_rows"] += int(np.asarray(lcnt[0]).max()) + int(
            np.asarray(rcnt[0]).max()
        )
        st["salted_rows"] += int(np.asarray(lcnt[1]).max()) + int(
            np.asarray(rcnt[1]).max()
        )
        st["padded_shuffle_rows"] += n * (n * lb + 8) + n * (
            n * rb + 8 + rhot_cap
        )
        return lout, lsel2, rout, rsel2


def _is_sharded(batch: Batch) -> bool:
    for c in batch.columns:
        if isinstance(c.data, jax.Array) and len(c.data.sharding.device_set) > 1:
            return True
    return False


def _as_global(mesh: Mesh, arr) -> jax.Array:
    """Ensure an array is a jax Array (shard if it is a host array)."""
    if isinstance(arr, jax.Array) and len(arr.sharding.device_set) > 1:
        return arr
    a = jnp.asarray(arr)
    from trino_tpu.parallel.mesh import row_sharding

    n = mesh.devices.size
    pad = (-a.shape[0]) % n
    if pad:
        # wide DECIMAL columns carry (N, 2) hi/lo lanes: pad rows only
        a = jnp.concatenate(
            [a, jnp.zeros((pad,) + a.shape[1:], dtype=a.dtype)]
        )
    return jax.device_put(a, row_sharding(mesh))


def _sharded_probe(
    mesh,
    probe_cols,
    probe_keys,
    ph,
    probe_sel,
    build_cols,
    build_keys,
    bh,
    build_sel,
    per_shard_cap,
    join_type,
    nlk,
    build_sharded=False,
    profiler=None,
    strategy="sort",
    table_cap=None,
):
    """Per-shard join: build local table from (replicated or co-partitioned)
    build side, probe local rows, expand into fixed capacity.

    ``strategy`` picks the join kernel: ``sort`` (ops/join.py bitonic
    build + binary-search probe), ``dense`` (ops/dense_join.py
    open-addressing table of ``table_cap`` slots), or ``matmul`` (same
    table addressed by identity binning of the single key column).
    Non-sort strategies return a FOURTH element — the table-overflow
    flag whose ``densejoin@…`` capacity site the executor's retry ladder
    doubles (graceful re-hash instead of the spill cliff).

    ``profiler`` (``DistributedExecutor._profiled_call``) optionally wraps
    the shard_map program so its XLA cost/memory analysis is captured."""
    n = mesh.devices.size

    def pad_side(cols, keys, h, sel):
        """Kernels reject 0-capacity arrays; pad an empty relation to n
        unselected rows (one per shard)."""
        if h.shape[0] > 0:
            return cols, keys, h, sel
        cols = [
            jnp.zeros((n,) + c.shape[1:], dtype=c.dtype) for c in cols
        ]
        keys = [
            jnp.zeros((n,) + k.shape[1:], dtype=k.dtype) for k in keys
        ]
        return (
            cols,
            keys,
            jnp.zeros((n,), dtype=h.dtype),
            jnp.zeros((n,), dtype=jnp.bool_),
        )

    probe_cols, probe_keys, ph, probe_sel = pad_side(
        probe_cols, probe_keys, ph, probe_sel
    )
    build_cols, build_keys, bh, build_sel = pad_side(
        build_cols, build_keys, bh, build_sel
    )
    n_probe = len(probe_cols)
    n_build = len(build_cols)
    build_spec = PS(AXIS) if build_sharded else PS()

    in_specs = (
        tuple(PS(AXIS) for _ in probe_cols)
        + tuple(PS(AXIS) for _ in probe_keys)
        + (PS(AXIS), PS(AXIS))
        + tuple(build_spec for _ in build_cols)
        + tuple(build_spec for _ in build_keys)
        + (build_spec, build_spec)
    )

    out_specs = (tuple(PS(AXIS) for _ in range(n_probe + n_build)), PS(AXIS), PS())
    if strategy != "sort":
        out_specs = out_specs + (PS(),)

    @partial(smap, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    def go(*ops):
        i = 0
        p_cols = ops[i : i + n_probe]; i += n_probe
        p_keys = ops[i : i + 2 * nlk]; i += 2 * nlk
        p_hash = ops[i]; i += 1
        p_sel = ops[i]; i += 1
        b_cols = ops[i : i + n_build]; i += n_build
        b_keys = ops[i : i + 2 * nlk]; i += 2 * nlk
        b_hash = ops[i]; i += 1
        b_sel = ops[i]; i += 1

        # key validity: all key columns non-null
        pk_pairs = [(p_keys[2 * k], p_keys[2 * k + 1]) for k in range(nlk)]
        bk_pairs = [(b_keys[2 * k], b_keys[2 * k + 1]) for k in range(nlk)]
        pv = jnp.ones_like(p_sel)
        for _, kv in pk_pairs:
            pv = pv & kv
        bv = jnp.ones_like(b_sel)
        for _, kv in bk_pairs:
            bv = bv & kv
        jt = "left" if join_type == "LEFT" else "inner"
        tovf = None
        if strategy == "sort":
            sbk, sbi, bcount = J.build_side(b_hash, bv, b_sel)
            ppos, bpos, osel, total, ovf = J.probe_join(
                sbk, sbi, bcount, p_hash, pv, p_sel, per_shard_cap, jt,
            )
        else:
            from trino_tpu.ops import dense_join as DJ

            if strategy == "matmul":
                # identity binning of the single key column (the caller
                # gates matmul on nlk == 1 and an integer key dtype)
                use_b = bv & b_sel
                kmin = jnp.min(
                    jnp.where(
                        use_b,
                        bk_pairs[0][0].astype(jnp.int64),
                        jnp.iinfo(jnp.int64).max,
                    )
                )
                bbase = DJ.slot_base_binned(bk_pairs[0][0], kmin, table_cap)
                pbase = DJ.slot_base_binned(pk_pairs[0][0], kmin, table_cap)
            else:
                bbase = DJ.slot_base_hash(b_hash, table_cap)
                pbase = DJ.slot_base_hash(p_hash, table_cap)
            table, tovf = DJ.build_table(bbase, bv, b_sel, table_cap)
            ppos, bpos, osel, total, ovf = DJ.probe_table(
                table, b_hash, pbase, p_hash, pv, p_sel, per_shard_cap, jt,
            )
        osel = J.verify_equal(pk_pairs, bk_pairs, ppos, bpos, osel)
        is_outer = bpos == J.MISSING
        safe_bpos = jnp.where(is_outer, 0, bpos)
        outs = []
        for k in range(0, n_probe, 2):
            outs.append(p_cols[k][ppos])
            outs.append(p_cols[k + 1][ppos])
        for k in range(0, n_build, 2):
            outs.append(b_cols[k][safe_bpos])
            outs.append(b_cols[k + 1][safe_bpos] & ~is_outer)
        ovf_any = jax.lax.pmax(ovf.astype(jnp.int32), AXIS)
        if tovf is None:
            return tuple(outs), osel, ovf_any
        tovf_any = jax.lax.pmax(tovf.astype(jnp.int32), AXIS)
        return tuple(outs), osel, ovf_any, tovf_any

    args = (
        list(probe_cols)
        + list(probe_keys)
        + [ph, probe_sel]
        + list(build_cols)
        + list(build_keys)
        + [bh, build_sel]
    )
    if profiler is not None:
        label = "probe_join" + ("_partitioned" if build_sharded else "_broadcast")
        res = profiler(label, go, *args)
    else:
        res = go(*args)
    if strategy == "sort":
        outs, osel, ovf = res
        return list(outs), osel, ovf
    outs, osel, ovf, tovf = res
    return list(outs), osel, ovf, tovf
