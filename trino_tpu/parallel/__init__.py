"""Distribution: device mesh, exchanges as collectives, distributed executor.

Reference: Trino's distribution stack — ``PlanFragmenter.java:88`` (stage
cutting), ``SystemPartitioningHandle.java:58-66`` (partitioning taxonomy),
``execution/buffer/`` + ``operator/ExchangeClient.java`` (HTTP shuffle),
``AddExchanges.java:115`` (distribution choice).

TPU-first translation (SURVEY.md §2.6/§2.7): a stage is an SPMD region over
a ``jax.sharding.Mesh``; the pull-based HTTP shuffle becomes
``lax.all_to_all`` (hash repartition) / replication constraints (broadcast)
inside jit-compiled programs, with XLA inserting the collectives.
"""

from trino_tpu.parallel.mesh import make_mesh, shard_batch  # noqa: F401
