"""Exchange kernels: repartition/broadcast as mesh collectives.

Reference: Trino's data plane — ``PartitionedOutputOperator.java:55``
(hash-partition pages to N buffers), ``BroadcastOutputBuffer``,
``ExchangeClient.java:149`` (pull + ack). TPU translation (SURVEY §2.7):

- hash repartition -> inside ``shard_map``: bucket rows by destination
  shard, pad buckets to a fixed per-destination capacity, ``lax.all_to_all``
  the [n_dest, B] blocks, locally re-flatten; a validity mask marks live
  rows. Fixed-size chunks + count headers replace the reference's
  backpressured streaming (SURVEY §7 "shuffle without dynamic connectivity").
- broadcast -> ``lax.all_gather`` (replicate the build side).

Overflow (a destination receiving more than B rows from one source) is
reported via a flag; the caller retries with a larger bucket.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as PS

from trino_tpu.parallel.mesh import AXIS, smap


def hash_repartition(
    mesh: Mesh,
    arrays: Sequence[jax.Array],
    key_hash: jax.Array,
    sel: jax.Array,
    bucket: int,
):
    """Repartition rows so that key_hash % n lands on shard (key_hash % n).

    Args:
      arrays: per-column global arrays sharded on rows (shape (N,)).
      key_hash: int64 hash per row (same sharding); rows with sel=False are
        not sent anywhere.
      bucket: per-(src,dst) block capacity B.

    Returns (out_arrays, out_sel, overflow): out arrays have per-shard
    length n*B (global length n*n*B), out_sel marks live rows, overflow is
    a host-checkable bool (any src->dst block overflowed).
    """
    n = mesh.devices.size

    @partial(
        smap,
        mesh=mesh,
        in_specs=(PS(AXIS),) * (len(arrays) + 2),
        out_specs=(
            tuple(PS(AXIS) for _ in arrays),
            PS(AXIS),
            PS(),
        ),
    )
    def go(*ops):
        *cols, khash, s = ops
        local_n = khash.shape[0]
        dest = (khash % n).astype(jnp.int32)
        dest = jnp.where(s, dest, n)  # dead rows -> virtual dest n (dropped)
        # stable sort rows by destination: dest and row index packed into
        # ONE int32 lane (dest <= n fits above the index bits), so the
        # unstable single-operand sort is deterministic — is_stable or a
        # second operand would double XLA:TPU's sort compile time
        idx_bits = max(1, (local_n - 1).bit_length())
        wide = idx_bits + (n + 1).bit_length() > 31
        lt = jnp.int64 if wide else jnp.int32
        lane = (dest.astype(lt) << idx_bits) | jnp.arange(local_n, dtype=lt)
        s_lane = jax.lax.sort((lane,), num_keys=1, is_stable=False)[0]
        order = (s_lane & ((1 << idx_bits) - 1)).astype(jnp.int32)
        d_sorted = (s_lane >> idx_bits).astype(jnp.int32)
        # position of each row within its destination run
        counts = jnp.bincount(d_sorted, length=n + 1)
        starts = jnp.cumsum(counts) - counts
        within = jnp.arange(local_n) - starts[d_sorted]
        overflow = jnp.any(counts[:n] > bucket)
        # scatter into [n, B] blocks
        blocks = []
        live = (d_sorted < n) & (within < bucket)
        slot = jnp.where(live, d_sorted * bucket + within, n * bucket)
        valid_block = (
            jnp.zeros((n * bucket,), dtype=jnp.bool_)
            .at[slot]
            .set(live, mode="drop")
            .reshape(n, bucket)
        )
        for c in cols:
            b = (
                jnp.zeros((n * bucket,), dtype=c.dtype)
                .at[slot]
                .set(c[order], mode="drop")
                .reshape(n, bucket)
            )
            blocks.append(b)
        # exchange: block [d, :] goes to shard d
        out_cols = []
        for b in blocks:
            out = jax.lax.all_to_all(b, AXIS, split_axis=0, concat_axis=0)
            out_cols.append(out.reshape(n * bucket))
        out_valid = jax.lax.all_to_all(
            valid_block, AXIS, split_axis=0, concat_axis=0
        ).reshape(n * bucket)
        overflow_any = jax.lax.pmax(overflow.astype(jnp.int32), AXIS)
        return tuple(out_cols), out_valid, overflow_any

    out_cols, out_sel, overflow = go(*arrays, key_hash, sel)
    return list(out_cols), out_sel, overflow


def needed_bucket(mesh: Mesh, key_hash: jax.Array, sel: jax.Array) -> int:
    """Exact per-(src,dst) bucket size for hash_repartition: the max count
    of rows any one source sends to any one destination. One cheap pass —
    avoids overflow retries (each retry re-traces the exchange)."""
    n = mesh.devices.size

    @partial(
        smap,
        mesh=mesh,
        in_specs=(PS(AXIS), PS(AXIS)),
        out_specs=PS(),
    )
    def go(khash, s):
        dest = jnp.where(s, (khash % n).astype(jnp.int32), n)
        counts = jnp.bincount(dest, length=n + 1)[:n]
        local_max = jnp.max(counts)
        return jax.lax.pmax(local_max, AXIS)

    return max(8, int(np.asarray(go(key_hash, sel)).max()))


def broadcast_all(mesh: Mesh, arrays: Sequence[jax.Array], sel: jax.Array):
    """Replicate row-sharded arrays to every shard (build-side broadcast).

    Returns per-shard-replicated global arrays of the full length.
    """
    n_ = mesh.devices.size
    cap = sel.shape[0]
    if cap % n_:
        # tiny sources (e.g. a one-row scalar subquery result) pad up to
        # the mesh width; padding rows are unselected
        pad = n_ - cap % n_
        arrays = [
            jnp.concatenate(
                [a, jnp.zeros((pad,) + a.shape[1:], dtype=a.dtype)]
            )
            for a in arrays
        ]
        sel = jnp.concatenate([sel, jnp.zeros(pad, dtype=jnp.bool_)])

    @partial(
        smap,
        mesh=mesh,
        in_specs=(PS(AXIS),) * (len(arrays) + 1),
        out_specs=(tuple(PS() for _ in arrays), PS()),
    )
    def go(*ops):
        *cols, s = ops
        out = tuple(
            jax.lax.all_gather(c, AXIS, axis=0, tiled=True) for c in cols
        )
        s_out = jax.lax.all_gather(s, AXIS, axis=0, tiled=True)
        return out, s_out

    out, s = go(*arrays, sel)
    return list(out), s
