"""Exchange kernels: repartition/broadcast as mesh collectives.

Reference: Trino's data plane — ``PartitionedOutputOperator.java:55``
(hash-partition pages to N buffers), ``BroadcastOutputBuffer``,
``ExchangeClient.java:149`` (pull + ack). TPU translation (SURVEY §2.7):

- hash repartition -> inside ``shard_map``: bucket rows by destination
  shard, pad buckets to a fixed per-destination capacity, ``lax.all_to_all``
  the [n_dest, B] blocks, locally re-flatten; a validity mask marks live
  rows. Fixed-size chunks + count headers replace the reference's
  backpressured streaming (SURVEY §7 "shuffle without dynamic connectivity").
- broadcast -> ``lax.all_gather`` (replicate the build side).

Overflow (a destination receiving more than B rows from one source) is
reported via a flag; the caller retries with a larger bucket.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as PS

from trino_tpu.parallel.mesh import AXIS, smap


def hash_repartition(
    mesh: Mesh,
    arrays: Sequence[jax.Array],
    key_hash: jax.Array,
    sel: jax.Array,
    bucket: int,
):
    """Repartition rows so that key_hash % n lands on shard (key_hash % n).

    Args:
      arrays: per-column global arrays sharded on rows (shape (N,)).
      key_hash: int64 hash per row (same sharding); rows with sel=False are
        not sent anywhere.
      bucket: per-(src,dst) block capacity B.

    Returns (out_arrays, out_sel, overflow): out arrays have per-shard
    length n*B (global length n*n*B), out_sel marks live rows, overflow is
    a host-checkable bool (any src->dst block overflowed).
    """
    n = mesh.devices.size

    @partial(
        smap,
        mesh=mesh,
        in_specs=(PS(AXIS),) * (len(arrays) + 2),
        out_specs=(
            tuple(PS(AXIS) for _ in arrays),
            PS(AXIS),
            PS(),
        ),
    )
    def go(*ops):
        *cols, khash, s = ops
        local_n = khash.shape[0]
        dest = (khash % n).astype(jnp.int32)
        dest = jnp.where(s, dest, n)  # dead rows -> virtual dest n (dropped)
        # stable sort rows by destination: dest and row index packed into
        # ONE int32 lane (dest <= n fits above the index bits), so the
        # unstable single-operand sort is deterministic — is_stable or a
        # second operand would double XLA:TPU's sort compile time
        idx_bits = max(1, (local_n - 1).bit_length())
        wide = idx_bits + (n + 1).bit_length() > 31
        lt = jnp.int64 if wide else jnp.int32
        lane = (dest.astype(lt) << idx_bits) | jnp.arange(local_n, dtype=lt)
        s_lane = jax.lax.sort((lane,), num_keys=1, is_stable=False)[0]
        order = (s_lane & ((1 << idx_bits) - 1)).astype(jnp.int32)
        d_sorted = (s_lane >> idx_bits).astype(jnp.int32)
        # position of each row within its destination run
        counts = jnp.bincount(d_sorted, length=n + 1)
        starts = jnp.cumsum(counts) - counts
        within = jnp.arange(local_n) - starts[d_sorted]
        overflow = jnp.any(counts[:n] > bucket)
        # scatter into [n, B] blocks
        blocks = []
        live = (d_sorted < n) & (within < bucket)
        slot = jnp.where(live, d_sorted * bucket + within, n * bucket)
        valid_block = (
            jnp.zeros((n * bucket,), dtype=jnp.bool_)
            .at[slot]
            .set(live, mode="drop")
            .reshape(n, bucket)
        )
        for c in cols:
            b = (
                jnp.zeros((n * bucket,), dtype=c.dtype)
                .at[slot]
                .set(c[order], mode="drop")
                .reshape(n, bucket)
            )
            blocks.append(b)
        # exchange: block [d, :] goes to shard d
        out_cols = []
        for b in blocks:
            out = jax.lax.all_to_all(b, AXIS, split_axis=0, concat_axis=0)
            out_cols.append(out.reshape(n * bucket))
        out_valid = jax.lax.all_to_all(
            valid_block, AXIS, split_axis=0, concat_axis=0
        ).reshape(n * bucket)
        overflow_any = jax.lax.pmax(overflow.astype(jnp.int32), AXIS)
        return tuple(out_cols), out_valid, overflow_any

    out_cols, out_sel, overflow = go(*arrays, key_hash, sel)
    return list(out_cols), out_sel, overflow


def skewed_repartition(
    mesh: Mesh,
    arrays: Sequence[jax.Array],
    key_hash: jax.Array,
    sel: jax.Array,
    bucket: int,
    spill: int,
    hot_mode: str | None = None,
    hot_cap: int = 0,
    hot_set=None,
    detect=None,
):
    """Two-tier (+ optionally salted) repartition.

    Replaces ``hash_repartition``'s single worst-case ``B`` with a small
    per-(src,dst) cold ``bucket`` plus a shared ``spill`` tier: rows
    overflowing their cold block pack into one per-source [spill] block
    that is all_gathered with a destination lane — each receiver keeps the
    spill rows addressed to it, so the layout stays destination-preserving
    and the result is row-set-identical to ``hash_repartition``.

    Skew handling adds a third *hot* region for keys in a heavy-hitter
    set (``ops/skew.py``), which never touch cold or spill tiers:

    - ``hot_mode="local"`` (probe side): hot rows stay on their source
      shard — zero wire cost, the source shard is the salt.
    - ``hot_mode="replicate"`` (build side): each source's hot rows are
      all_gathered to every shard (partial broadcast of just the hot
      slice), so every shard can join its local hot probe rows.

    The hot set comes either from ``hot_set=(hot_hashes, hot_valid)``
    (replicated tables from a prior sketch) or ``detect=(k, frac)`` which
    runs ``hot_key_sketch`` in-program over this exchange's own hashes and
    returns the tables for the peer exchange to reuse.

    Returns ``(out_cols, out_sel, flags, counters, hotset)``:
      flags: ``(spill_overflow, hot_overflow)`` int32, host-checkable;
      counters: ``(sent_rows, hot_rows, hot_keys)`` int64 — live rows
        entering the exchange, rows routed hot, hot keys detected;
      hotset: ``(hot_hashes, hot_valid, n_hot)`` in detect mode, else ().
    Per-shard output length is ``n*bucket + n*spill + H`` where H is 0
    (no hot region), ``hot_cap`` (local) or ``n*hot_cap`` (replicate).
    """
    from trino_tpu.ops import skew as SK

    n = mesh.devices.size
    assert hot_mode in (None, "local", "replicate")
    assert (hot_mode is None) == (hot_set is None and detect is None)
    hot_extra = 2 if hot_set is not None else 0
    in_specs = (PS(AXIS),) * (len(arrays) + 2) + (PS(),) * hot_extra
    hotset_specs = (PS(), PS(), PS()) if detect is not None else ()
    out_specs = (
        tuple(PS(AXIS) for _ in arrays),
        PS(AXIS),
        (PS(), PS()),
        (PS(), PS(), PS()),
        hotset_specs,
    )

    @partial(smap, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    def go(*ops):
        if hot_set is not None:
            *cols, khash, s, hh, hv = ops
            n_hot = jnp.sum(hv.astype(jnp.int64))
            hotset_out = ()
        else:
            *cols, khash, s = ops
            if detect is not None:
                k, frac = detect
                hh, hv, n_hot, _total = SK.hot_key_sketch(khash, s, k, frac)
                hotset_out = (hh, hv, n_hot)
            else:
                hh = hv = None
                n_hot = jnp.zeros((), dtype=jnp.int64)
                hotset_out = ()
        local_n = khash.shape[0]
        dest = (khash % n).astype(jnp.int32)
        if hh is not None:
            dest = jnp.where(SK.is_hot(hh, hv, khash) & s, n, dest)
        dest = jnp.where(s, dest, n + 1)  # dead rows -> dropped id
        # same packed-lane deterministic sort as hash_repartition, with
        # two extra ids: n = hot, n+1 = dead
        idx_bits = max(1, (local_n - 1).bit_length())
        wide = idx_bits + (n + 1).bit_length() > 31
        lt = jnp.int64 if wide else jnp.int32
        lane = (dest.astype(lt) << idx_bits) | jnp.arange(local_n, dtype=lt)
        s_lane = jax.lax.sort((lane,), num_keys=1, is_stable=False)[0]
        order = (s_lane & ((1 << idx_bits) - 1)).astype(jnp.int32)
        d_sorted = (s_lane >> idx_bits).astype(jnp.int32)
        counts = jnp.bincount(d_sorted, length=n + 2)
        starts = jnp.cumsum(counts) - counts
        within = (jnp.arange(local_n) - starts[d_sorted]).astype(jnp.int32)
        cold = d_sorted < n
        in_cold = cold & (within < bucket)
        sp = (cold & (within >= bucket)).astype(jnp.int32)
        spill_pos = (jnp.cumsum(sp) - sp).astype(jnp.int32)
        n_spilled = jnp.sum(sp)
        hot_region = hot_cap if hot_mode is not None else 0
        base_spill = n * bucket
        base_hot = base_spill + spill
        total_slots = base_hot + hot_region
        slot = jnp.where(in_cold, d_sorted * bucket + within, total_slots)
        slot = jnp.where(
            (sp > 0) & (spill_pos < spill), base_spill + spill_pos, slot
        )
        if hot_mode is not None:
            slot = jnp.where(
                (d_sorted == n) & (within < hot_cap), base_hot + within, slot
            )
        landed = slot < total_slots
        valid_buf = (
            jnp.zeros((total_slots,), dtype=jnp.bool_)
            .at[slot]
            .set(landed, mode="drop")
        )
        dest_buf = (
            jnp.full((total_slots,), n, dtype=jnp.int32)
            .at[slot]
            .set(d_sorted, mode="drop")
        )
        me = jax.lax.axis_index(AXIS)

        def ship(buf):
            cold_b = buf[:base_spill].reshape((n, bucket) + buf.shape[1:])
            cold_out = jax.lax.all_to_all(
                cold_b, AXIS, split_axis=0, concat_axis=0
            ).reshape((base_spill,) + buf.shape[1:])
            spill_out = jax.lax.all_gather(
                buf[base_spill:base_hot], AXIS, axis=0, tiled=True
            )
            parts = [cold_out, spill_out]
            if hot_mode == "replicate":
                parts.append(
                    jax.lax.all_gather(buf[base_hot:], AXIS, axis=0, tiled=True)
                )
            elif hot_mode == "local":
                parts.append(buf[base_hot:])
            return jnp.concatenate(parts) if len(parts) > 1 else cold_out

        out_cols = tuple(
            ship(
                jnp.zeros((total_slots,) + c.shape[1:], dtype=c.dtype)
                .at[slot]
                .set(c[order], mode="drop")
            )
            for c in cols
        )
        out_valid = ship(valid_buf)
        # spill rows were gathered everywhere; keep only those addressed here
        gdest = jax.lax.all_gather(
            dest_buf[base_spill:base_hot], AXIS, axis=0, tiled=True
        )
        spill_keep = jnp.concatenate(
            [
                jnp.ones((base_spill,), dtype=jnp.bool_),
                gdest == me,
                jnp.ones((out_valid.shape[0] - base_spill - n * spill,), dtype=jnp.bool_),
            ]
        )
        out_valid = out_valid & spill_keep
        flags = (
            jax.lax.pmax((n_spilled > spill).astype(jnp.int32), AXIS),
            jax.lax.pmax((counts[n] > hot_cap).astype(jnp.int32), AXIS)
            if hot_mode is not None
            else jnp.zeros((), dtype=jnp.int32),
        )
        # sent counts LIVE rows entering the exchange (the padding-ratio
        # denominator) — not wire slots; hot-local rows still count, so
        # skew-on and skew-off runs share a comparable baseline
        counters = (
            jax.lax.psum(jnp.sum(s.astype(jnp.int64)), AXIS),
            jax.lax.psum(counts[n].astype(jnp.int64), AXIS),
            n_hot,
        )
        return out_cols, out_valid, flags, counters, hotset_out

    args = list(arrays) + [key_hash, sel]
    if hot_set is not None:
        args += [hot_set[0], hot_set[1]]
    out_cols, out_sel, flags, counters, hotset = go(*args)
    return list(out_cols), out_sel, flags, counters, hotset


def skew_split_counts(
    mesh: Mesh, key_hash: jax.Array, sel: jax.Array, hot_hashes, hot_valid
):
    """Exact sizing for a hybrid exchange (interpreter path): the max
    per-(src,dst) count over *cold* rows and the max per-source count of
    *hot* rows. One cheap pass, like ``needed_bucket``."""
    from trino_tpu.ops import skew as SK

    n = mesh.devices.size

    @partial(
        smap,
        mesh=mesh,
        in_specs=(PS(AXIS), PS(AXIS), PS(), PS()),
        out_specs=(PS(), PS()),
    )
    def go(khash, s, hh, hv):
        dest = jnp.where(s, (khash % n).astype(jnp.int32), n + 1)
        dest = jnp.where(SK.is_hot(hh, hv, khash) & s, n, dest)
        counts = jnp.bincount(dest, length=n + 2)
        return (
            jax.lax.pmax(jnp.max(counts[:n]), AXIS),
            jax.lax.pmax(counts[n], AXIS),
        )

    import time as _time

    from trino_tpu.obs.trace import get_tracer

    t0 = _time.perf_counter()
    cold_max, hot_max = go(key_hash, sel, hot_hashes, hot_valid)
    out = (
        max(8, int(np.asarray(cold_max).max())),
        max(8, int(np.asarray(hot_max).max())),
    )
    # eager host-blocking sizing pass (the repartition kernels themselves
    # are traced collectives — no host-side span possible there)
    get_tracer().record(
        "exchange_sizing",
        (_time.perf_counter() - t0) * 1000.0,
        attrs={"kind": "skew", "cold_max": out[0], "hot_max": out[1]},
    )
    return out


def needed_bucket(mesh: Mesh, key_hash: jax.Array, sel: jax.Array) -> int:
    """Exact per-(src,dst) bucket size for hash_repartition: the max count
    of rows any one source sends to any one destination. One cheap pass —
    avoids overflow retries (each retry re-traces the exchange)."""
    n = mesh.devices.size

    @partial(
        smap,
        mesh=mesh,
        in_specs=(PS(AXIS), PS(AXIS)),
        out_specs=PS(),
    )
    def go(khash, s):
        dest = jnp.where(s, (khash % n).astype(jnp.int32), n)
        counts = jnp.bincount(dest, length=n + 1)[:n]
        local_max = jnp.max(counts)
        return jax.lax.pmax(local_max, AXIS)

    import time as _time

    from trino_tpu.obs.trace import get_tracer

    t0 = _time.perf_counter()
    bucket = max(8, int(np.asarray(go(key_hash, sel)).max()))
    get_tracer().record(
        "exchange_sizing",
        (_time.perf_counter() - t0) * 1000.0,
        attrs={"kind": "bucket", "bucket": bucket},
    )
    return bucket


def broadcast_all(mesh: Mesh, arrays: Sequence[jax.Array], sel: jax.Array):
    """Replicate row-sharded arrays to every shard (build-side broadcast).

    Returns per-shard-replicated global arrays of the full length.
    """
    n_ = mesh.devices.size
    cap = sel.shape[0]
    if cap % n_:
        # tiny sources (e.g. a one-row scalar subquery result) pad up to
        # the mesh width; padding rows are unselected
        pad = n_ - cap % n_
        arrays = [
            jnp.concatenate(
                [a, jnp.zeros((pad,) + a.shape[1:], dtype=a.dtype)]
            )
            for a in arrays
        ]
        sel = jnp.concatenate([sel, jnp.zeros(pad, dtype=jnp.bool_)])

    @partial(
        smap,
        mesh=mesh,
        in_specs=(PS(AXIS),) * (len(arrays) + 1),
        out_specs=(tuple(PS() for _ in arrays), PS()),
    )
    def go(*ops):
        *cols, s = ops
        out = tuple(
            jax.lax.all_gather(c, AXIS, axis=0, tiled=True) for c in cols
        )
        s_out = jax.lax.all_gather(s, AXIS, axis=0, tiled=True)
        return out, s_out

    out, s = go(*arrays, sel)
    return list(out), s
