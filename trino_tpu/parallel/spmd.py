"""Multi-host SPMD query execution: N server processes, one global mesh.

Reference shape: Trino runs a fragment as tasks on many workers with HTTP
shuffle between them (``SqlQueryScheduler.java:538``); its TPU-native
translation (SURVEY §2.7) runs each fragment as ONE multi-host pjit
program over a ``jax.distributed`` mesh — intra-host ICI and cross-host
DCN collectives replace the HTTP data plane entirely. The control plane
only ships the *plan*: every process traces and launches the same jitted
programs in the same order, so XLA's collectives rendezvous without any
explicit message passing.

Protocol (two-phase):
- All server processes boot with ``jax.distributed.initialize`` (rank 0 is
  the coordinator) and build the same global mesh.
- A query arrives at the coordinator. If the plan is fusable it assigns a
  sequence number and **prepares** it on every worker
  (``POST /v1/spmd`` with ``phase=prepare`` — plan + session are staged,
  nothing launches). If any peer is unreachable the coordinator aborts the
  slot (``phase=commit, go=false``) and the query falls back to per-task
  cluster scheduling — a lost peer costs one round-trip, not an error.
- On all-ready the coordinator **commits** (``phase=commit, go=true``);
  every process (coordinator included) executes committed slots strictly
  in sequence order, so the jitted program streams launch identically and
  XLA's multi-host collectives rendezvous. Aborted slots advance the
  sequence without launching anything.
- Multiple queries may be in flight: sequence allocation and the prepare
  round-trips overlap freely; only the launch order is serialized.
- Capacity-overflow retries re-trace identically on every process
  (overflow flags are globally reduced), keeping the streams aligned.
- The root result is replicated to all processes (tiny by then), and the
  coordinator answers the client.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from typing import Optional

import numpy as np

from trino_tpu.config import Session
from trino_tpu.exec.local import ExecutionError
from trino_tpu.planner import plan as P


class SpmdUnsupported(Exception):
    """Plan not executable as one fused multi-host program."""


def session_to_json(session: Session) -> dict:
    return {
        "user": session.user,
        "catalog": session.catalog,
        "schema": session.schema,
        "properties": {
            k: v
            for k, v in session.properties.items()
            if isinstance(v, (str, int, float, bool))
        },
    }


def session_from_json(d: dict) -> Session:
    s = Session(
        user=d.get("user", "spmd"),
        catalog=d.get("catalog", "tpch"),
        schema=d.get("schema", "tiny"),
    )
    for k, v in d.get("properties", {}).items():
        s.properties[k] = v
    return s


class SpmdRunner:
    """Per-process SPMD execution endpoint (coordinator and workers)."""

    def __init__(self, engine):
        import jax

        from trino_tpu.parallel.mesh import make_mesh

        self.engine = engine
        self.mesh = make_mesh()  # global mesh over every process's devices
        self.process_count = jax.process_count()
        self._seq_lock = threading.Lock()  # sequence allocation only
        self._seq = 0
        self._done_seq = -1
        self._cond = threading.Condition()
        self._pending: dict[int, dict] = {}  # staged prepares (worker side)

    # --- launch-order gate ------------------------------------------------

    def _await_turn(self, seq: int, timeout: float = 600.0) -> bool:
        """Block until every earlier slot completed or was aborted."""
        with self._cond:
            while self._done_seq < seq - 1:
                if not self._cond.wait(timeout=timeout):
                    return False
        return True

    def _finish(self, seq: int) -> None:
        with self._cond:
            self._done_seq = max(self._done_seq, seq)
            self._cond.notify_all()

    # --- shared execution body -------------------------------------------

    def _execute(self, plan: P.PlanNode, session: Session):
        from trino_tpu.exec.fragments import FragmentedExecutor, query_fusable
        from trino_tpu.planner.fragmenter import fragment_plan

        if not query_fusable(fragment_plan(plan)):
            raise SpmdUnsupported("plan contains non-fusable nodes")
        local = Session(
            user=session.user, catalog=session.catalog, schema=session.schema
        )
        for k, v in session.properties.items():
            if k not in ("execution_mode",):
                local.properties[k] = v
        # spill deferral would diverge program streams across processes
        local.properties["spill_enabled"] = False
        executor = FragmentedExecutor(self.engine.catalogs, local, self.mesh)
        return executor.execute(plan)

    # --- coordinator side -------------------------------------------------

    def execute(self, plan: P.PlanNode, session: Session, peers: list[str]):
        """Run one query SPMD across all processes; returns (batch, names).

        ``peers`` are the worker base URIs (everyone but this process).
        """
        from trino_tpu.exec.fragments import query_fusable
        from trino_tpu.planner.fragmenter import fragment_plan
        from trino_tpu.planner.serde import node_to_json

        # decide fusability BEFORE broadcasting: non-fusable plans fall
        # back to per-task cluster scheduling without touching workers
        if not query_fusable(fragment_plan(plan)):
            raise SpmdUnsupported("plan contains non-fusable nodes")
        if len(peers) != self.process_count - 1:
            # the pjit program needs EVERY rank of the fixed jax.distributed
            # group; an un-announced (or lapsed) rank would never launch it
            # and the collective would hang — fall back to task scheduling
            raise SpmdUnsupported(
                f"{len(peers)} peers announced, need {self.process_count - 1}"
            )
        with self._seq_lock:
            seq = self._seq
            self._seq += 1

        def post(uri: str, body: dict, timeout: float) -> dict:
            from trino_tpu.server import auth

            req = urllib.request.Request(
                f"{uri}/v1/spmd",
                data=json.dumps(body).encode(),
                method="POST",
                headers=auth.headers(),
            )
            req.add_header("Content-Type", "application/json")
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return json.loads(r.read().decode())

        def broadcast(body: dict, timeout: float) -> list:
            """POST to all peers concurrently -> list of (uri, reply|exc)."""
            results: list = [None] * len(peers)

            def one(i: int, uri: str):
                try:
                    results[i] = (uri, post(uri, body, timeout))
                except Exception as e:  # noqa: BLE001
                    results[i] = (uri, e)

            ts = [
                threading.Thread(target=one, args=(i, u), daemon=True)
                for i, u in enumerate(peers)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=timeout + 30)
            return results

        # phase 1 — prepare: stage the plan everywhere; nothing launches,
        # so a dead peer here is recoverable (fall back to task scheduling)
        prepare = {
            "phase": "prepare",
            "seq": seq,
            "plan": node_to_json(plan),
            "session": session_to_json(session),
        }
        failed = [
            (uri, r)
            for uri, r in broadcast(prepare, timeout=30)
            if isinstance(r, Exception) or r.get("error")
        ]
        if failed:
            # abort the slot everywhere so sequence numbers stay aligned
            broadcast({"phase": "commit", "seq": seq, "go": False}, timeout=30)
            self._await_turn(seq)
            self._finish(seq)
            raise SpmdUnsupported(
                f"peer unavailable at prepare ({failed[0][0]}): {failed[0][1]}"
            )

        # phase 2 — commit: everyone (us included) launches in seq order
        errors: list[str] = []
        commit_threads = []

        def commit(uri: str):
            try:
                body = post(
                    uri, {"phase": "commit", "seq": seq, "go": True}, 600
                )
                if body.get("error"):
                    errors.append(body["error"])
            except Exception as e:  # noqa: BLE001
                errors.append(f"{uri}: {e}")

        for uri in peers:
            t = threading.Thread(target=commit, args=(uri,), daemon=True)
            t.start()
            commit_threads.append(t)
        if not self._await_turn(seq):
            # predecessors abandoned: advance past them and run this slot
            with self._cond:
                self._done_seq = max(self._done_seq, seq - 1)
                self._cond.notify_all()
        try:
            result = self._execute(plan, session)
        finally:
            self._finish(seq)
            for t in commit_threads:
                t.join(timeout=600)
        if errors:
            raise ExecutionError(f"spmd worker failed: {errors[0]}")
        return result

    # --- worker side ------------------------------------------------------

    def execute_remote(self, payload: dict) -> dict:
        """Handle POST /v1/spmd on a worker (two-phase)."""
        from trino_tpu.planner.serde import node_from_json

        seq = int(payload["seq"])
        phase = payload.get("phase", "prepare")
        if phase == "prepare":
            with self._cond:
                if self._done_seq >= seq:
                    return {"error": f"seq {seq} slot already passed"}
            self._pending[seq] = payload
            return {"ready": True, "seq": seq}

        go = bool(payload.get("go", True))
        pend = self._pending.pop(seq, None)
        with self._cond:
            if self._done_seq >= seq:
                # this slot was declared abandoned while its commit was in
                # flight; launching now would be out of launch order
                return {"error": f"seq {seq} slot already passed"}
        if not self._await_turn(seq):
            # predecessors abandoned (commit or abort never arrived, e.g.
            # a missed go=False broadcast): advance past THEM and serve
            # this slot — the abandoned slots' late commits are rejected
            # by the guard above, so the healthy query is not the victim
            with self._cond:
                self._done_seq = max(self._done_seq, seq - 1)
                self._cond.notify_all()
        # TOCTOU re-check: while this commit was blocked in _await_turn a
        # SUCCESSOR's timeout may have advanced done_seq past this slot
        # (declared it abandoned). Launching now would execute jitted
        # programs out of launch order across processes — the aligned-
        # stream invariant multi-host XLA collectives depend on.
        with self._cond:
            if self._done_seq >= seq:
                return {"error": f"seq {seq} slot already passed"}
        try:
            if not go:
                return {"skipped": True, "seq": seq}
            if pend is None:
                return {"error": f"seq {seq} committed without prepare"}
            plan = node_from_json(pend["plan"])
            session = session_from_json(pend.get("session", {}))
            self._execute(plan, session)
            return {"ok": True, "seq": seq}
        except Exception as e:  # noqa: BLE001
            return {"error": f"{type(e).__name__}: {e}", "seq": seq}
        finally:
            self._finish(seq)


def initialize_spmd(coordinator: str, num_processes: int, process_id: int):
    """Join the jax.distributed group (call before any jax computation)."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
