"""Multi-host SPMD query execution: N server processes, one global mesh.

Reference shape: Trino runs a fragment as tasks on many workers with HTTP
shuffle between them (``SqlQueryScheduler.java:538``); its TPU-native
translation (SURVEY §2.7) runs each fragment as ONE multi-host pjit
program over a ``jax.distributed`` mesh — intra-host ICI and cross-host
DCN collectives replace the HTTP data plane entirely. The control plane
only ships the *plan*: every process traces and launches the same jitted
programs in the same order, so XLA's collectives rendezvous without any
explicit message passing.

Protocol:
- All server processes boot with ``jax.distributed.initialize`` (rank 0 is
  the coordinator) and build the same global mesh.
- A query arrives at the coordinator. If the plan is fusable it assigns a
  sequence number, broadcasts ``{seq, plan, session}`` to every worker's
  ``POST /v1/spmd``, and starts executing itself.
- Workers execute strictly in sequence order; capacity-overflow retries
  re-trace identically on every process (overflow flags are globally
  reduced), keeping the program streams aligned.
- The root result is replicated to all processes (tiny by then), and the
  coordinator answers the client.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from typing import Optional

import numpy as np

from trino_tpu.config import Session
from trino_tpu.exec.local import ExecutionError
from trino_tpu.planner import plan as P


class SpmdUnsupported(Exception):
    """Plan not executable as one fused multi-host program."""


def session_to_json(session: Session) -> dict:
    return {
        "user": session.user,
        "catalog": session.catalog,
        "schema": session.schema,
        "properties": {
            k: v
            for k, v in session.properties.items()
            if isinstance(v, (str, int, float, bool))
        },
    }


def session_from_json(d: dict) -> Session:
    s = Session(
        user=d.get("user", "spmd"),
        catalog=d.get("catalog", "tpch"),
        schema=d.get("schema", "tiny"),
    )
    for k, v in d.get("properties", {}).items():
        s.properties[k] = v
    return s


class SpmdRunner:
    """Per-process SPMD execution endpoint (coordinator and workers)."""

    def __init__(self, engine):
        import jax

        from trino_tpu.parallel.mesh import make_mesh

        self.engine = engine
        self.mesh = make_mesh()  # global mesh over every process's devices
        self.process_count = jax.process_count()
        self._lock = threading.Lock()  # one SPMD query at a time
        self._seq = 0
        self._done_seq = -1
        self._cond = threading.Condition()

    # --- shared execution body -------------------------------------------

    def _execute(self, plan: P.PlanNode, session: Session):
        from trino_tpu.exec.fragments import FragmentedExecutor, query_fusable
        from trino_tpu.planner.fragmenter import fragment_plan

        if not query_fusable(fragment_plan(plan)):
            raise SpmdUnsupported("plan contains non-fusable nodes")
        local = Session(
            user=session.user, catalog=session.catalog, schema=session.schema
        )
        for k, v in session.properties.items():
            if k not in ("execution_mode",):
                local.properties[k] = v
        # spill deferral would diverge program streams across processes
        local.properties["spill_enabled"] = False
        executor = FragmentedExecutor(self.engine.catalogs, local, self.mesh)
        return executor.execute(plan)

    # --- coordinator side -------------------------------------------------

    def execute(self, plan: P.PlanNode, session: Session, peers: list[str]):
        """Run one query SPMD across all processes; returns (batch, names).

        ``peers`` are the worker base URIs (everyone but this process).
        """
        from trino_tpu.exec.fragments import query_fusable
        from trino_tpu.planner.fragmenter import fragment_plan
        from trino_tpu.planner.serde import node_to_json

        # decide fusability BEFORE broadcasting: non-fusable plans fall
        # back to per-task cluster scheduling without touching workers
        if not query_fusable(fragment_plan(plan)):
            raise SpmdUnsupported("plan contains non-fusable nodes")
        if len(peers) != self.process_count - 1:
            # the pjit program needs EVERY rank of the fixed jax.distributed
            # group; an un-announced (or lapsed) rank would never launch it
            # and the collective would hang — fall back to task scheduling
            raise SpmdUnsupported(
                f"{len(peers)} peers announced, need {self.process_count - 1}"
            )
        with self._lock:
            seq = self._seq
            self._seq += 1
            payload = json.dumps(
                {
                    "seq": seq,
                    "plan": node_to_json(plan),
                    "session": session_to_json(session),
                }
            ).encode()
            errors: list[str] = []
            threads = []

            def post(uri: str):
                from trino_tpu.server import auth

                req = urllib.request.Request(
                    f"{uri}/v1/spmd",
                    data=payload,
                    method="POST",
                    headers=auth.headers(),
                )
                req.add_header("Content-Type", "application/json")
                try:
                    with urllib.request.urlopen(req, timeout=600) as r:
                        body = json.loads(r.read().decode())
                    if body.get("error"):
                        errors.append(body["error"])
                except Exception as e:  # noqa: BLE001
                    errors.append(f"{uri}: {e}")

            for uri in peers:
                t = threading.Thread(target=post, args=(uri,), daemon=True)
                t.start()
                threads.append(t)
            try:
                result = self._execute(plan, session)
            finally:
                for t in threads:
                    t.join(timeout=600)
            if errors:
                raise ExecutionError(f"spmd worker failed: {errors[0]}")
            return result

    # --- worker side ------------------------------------------------------

    def execute_remote(self, payload: dict) -> dict:
        """Handle POST /v1/spmd on a worker: execute in sequence order."""
        from trino_tpu.planner.serde import node_from_json

        seq = int(payload["seq"])
        plan = node_from_json(payload["plan"])
        session = session_from_json(payload.get("session", {}))
        with self._cond:
            if self._done_seq >= seq:
                # a predecessor's timeout already skipped this slot; running
                # it now would launch programs out of order
                return {"error": f"seq {seq} arrived after being skipped"}
            deadline = 600.0
            while self._done_seq < seq - 1:
                if not self._cond.wait(timeout=deadline):
                    # advance past the lost predecessor so later queries
                    # aren't head-of-line blocked forever
                    self._done_seq = max(self._done_seq, seq)
                    self._cond.notify_all()
                    return {"error": f"timed out waiting for seq {seq - 1}"}
        try:
            self._execute(plan, session)
            return {"ok": True, "seq": seq}
        except Exception as e:  # noqa: BLE001
            return {"error": f"{type(e).__name__}: {e}", "seq": seq}
        finally:
            with self._cond:
                self._done_seq = max(self._done_seq, seq)
                self._cond.notify_all()


def initialize_spmd(coordinator: str, num_processes: int, process_id: int):
    """Join the jax.distributed group (call before any jax computation)."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
