"""Columnar batch model: the TPU-native analog of Trino's Page/Block.

Reference: ``core/trino-spi/src/main/java/io/trino/spi/Page.java:53-85`` and
the 14 Block implementations under ``spi/block/``.

Design (TPU-first):
- A :class:`Column` is a fixed-width device array plus an optional validity
  mask. Strings carry a host-side :class:`Dictionary` (int32 codes on device).
- A :class:`Batch` is a list of equal-capacity columns plus a *selection*
  mask. Filters AND into the selection instead of compacting (static shapes
  for XLA); compaction happens at exchange/output boundaries where we are on
  the host anyway.
- Batches are registered as JAX pytrees so whole batches flow through
  ``jax.jit`` boundaries; dictionaries/types are static aux data.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T

_trace_tls = threading.local()


class Dictionary:
    """Host-side string dictionary. Code i <-> string values[i].

    Codes are dense int32. ``sorted_ranks`` supports order comparisons on
    codes (rank[code] preserves lexicographic order) without device strings.

    A *trace log* (opened per-thread via :meth:`begin_trace_log`, since
    jax traces on the calling thread and worker tasks trace concurrently)
    records which dictionaries contributed *growth-sensitive* constants to
    a trace: rank tables, and equality encodes that missed. Streaming uses
    this to decide whether appending values to a dictionary mid-stream
    would invalidate an already-compiled step (see ``exec/streaming.py``).
    """

    __slots__ = ("values", "_index", "_ranks")

    @staticmethod
    def begin_trace_log():
        """Open a fresh per-thread log; returns the previous one to restore."""
        prev = getattr(_trace_tls, "log", None)
        _trace_tls.log = {}
        return prev

    @staticmethod
    def end_trace_log(prev) -> dict:
        """Close the current per-thread log (restoring ``prev``) and return it."""
        log = getattr(_trace_tls, "log", None)
        _trace_tls.log = prev
        return log or {}

    def __init__(self, values: Sequence[str]):
        self.values: list[str] = list(values)
        self._index: dict[str, int] | None = None
        self._ranks: np.ndarray | None = None

    def __len__(self) -> int:
        return len(self.values)

    def decode(self, code: int) -> str | None:
        if code < 0:
            return None
        return self.values[code]

    def index(self) -> dict[str, int]:
        if self._index is None:
            self._index = {v: i for i, v in enumerate(self.values)}
        return self._index

    def encode(self, value: str) -> int:
        """Code for value, or -1 if absent (useful for predicates)."""
        code = self.index().get(value, -1)
        log = getattr(_trace_tls, "log", None)
        if code < 0 and log is not None:
            # a miss traced as the constant -1 stops being correct if this
            # dictionary later absorbs the value
            log.setdefault("growth_sensitive", set()).add(id(self))
        return code

    def ranks(self) -> np.ndarray:
        """rank[code] gives the lexicographic rank of each dictionary entry."""
        log = getattr(_trace_tls, "log", None)
        if log is not None:
            log.setdefault("growth_sensitive", set()).add(id(self))
        if self._ranks is None:
            order = np.argsort(np.asarray(self.values, dtype=object), kind="stable")
            ranks = np.empty(len(self.values), dtype=np.int32)
            ranks[order] = np.arange(len(self.values), dtype=np.int32)
            self._ranks = ranks
        return self._ranks

    def absorb(self, other: "Dictionary") -> tuple[np.ndarray | None, bool]:
        """Merge ``other``'s values into *this* dictionary in place
        (append-only: existing codes stay valid, so programs already traced
        against this object keep working unless they embedded
        growth-sensitive constants — see ``trace_log``).

        Returns (remap, grew): ``remap[other_code] -> my code`` (None when
        the dictionaries already agree code-for-code), and whether new
        values were appended (invalidates cached ranks)."""
        if other is self:
            return None, False
        index = self.index()
        remap = np.empty(len(other.values), dtype=np.int32)
        grew = False
        identical = len(other.values) <= len(self.values)
        for i, v in enumerate(other.values):
            code = index.get(v)
            if code is None:
                code = len(self.values)
                self.values.append(v)
                index[v] = code
                grew = True
                identical = False
            elif code != i:
                identical = False
            remap[i] = code
        if grew:
            self._ranks = None
        return (None if identical else remap), grew

    @staticmethod
    def from_strings(strings: Iterable[str]) -> tuple["Dictionary", np.ndarray]:
        """Build a dictionary and the code array for a string sequence.
        Hot host loop — uses the native hash table (native/columnar.cpp
        tt_dict_encode) when built, with a Python fallback inside."""
        from trino_tpu.native import dict_encode

        strings = strings if isinstance(strings, list) else list(strings)
        codes, values = dict_encode(strings)
        return Dictionary(values), codes

    def merged(self, other: "Dictionary") -> tuple["Dictionary", np.ndarray]:
        """Merge other into a new dictionary; returns (merged, remap) where
        remap[old_other_code] = new code."""
        values = list(self.values)
        index = dict(self.index())
        remap = np.empty(len(other.values), dtype=np.int32)
        for i, v in enumerate(other.values):
            code = index.get(v)
            if code is None:
                code = len(values)
                index[v] = code
                values.append(v)
            remap[i] = code
        d = Dictionary(values)
        d._index = index
        return d, remap


@dataclasses.dataclass
class Column:
    """One column: device data + optional validity + optional dictionary."""

    type: T.SqlType
    data: jax.Array | np.ndarray
    valid: jax.Array | np.ndarray | None = None  # None = all valid
    dictionary: Dictionary | None = None

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    def valid_mask(self) -> jax.Array:
        if self.valid is None:
            return jnp.ones(self.data.shape[0], dtype=jnp.bool_)
        return self.valid

    def to_numpy(self) -> tuple[np.ndarray, np.ndarray]:
        data = np.asarray(self.data)
        valid = (
            np.ones(data.shape[0], dtype=np.bool_)
            if self.valid is None
            else np.asarray(self.valid)
        )
        return data, valid

    @staticmethod
    def from_values(type_: T.SqlType, values: Sequence[Any]) -> "Column":
        """Build a column from Python values (None = NULL). Test/glue path."""
        n = len(values)
        valid = np.asarray([v is not None for v in values], dtype=np.bool_)
        if T.is_string(type_):
            strings = [v if v is not None else "" for v in values]
            dictionary, codes = Dictionary.from_strings(strings)
            codes = np.where(valid, codes, -1).astype(np.int32)
            return Column(type_, codes, None if valid.all() else valid, dictionary)
        dtype = type_.storage_dtype
        if isinstance(type_, T.DecimalType):
            from decimal import Decimal

            # exact: go through Decimal, not float (float loses >2^53)
            filled = [
                int(Decimal(str(v)).scaleb(type_.scale).to_integral_value())
                if v is not None
                else 0
                for v in values
            ]
        elif isinstance(type_, T.DateType):
            import datetime

            epoch = datetime.date(1970, 1, 1)
            filled = [
                (datetime.date.fromisoformat(v) - epoch).days
                if isinstance(v, str)
                else (0 if v is None else int(v))
                for v in values
            ]
        else:
            filled = [0 if v is None else v for v in values]
        data = np.asarray(filled, dtype=dtype)
        return Column(type_, data, None if valid.all() else valid, None)


@dataclasses.dataclass
class Batch:
    """A batch of rows: equal-capacity columns + selection mask + row count.

    ``num_rows`` is the count of *physical* rows (leading); rows past it are
    padding. ``sel`` (optional, shape (capacity,)) marks rows surviving
    filters. Logical rows = first num_rows AND sel.
    """

    columns: list[Column]
    num_rows: int
    sel: jax.Array | np.ndarray | None = None

    @property
    def capacity(self) -> int:
        if self.columns:
            return self.columns[0].capacity
        if self.sel is not None:
            return int(self.sel.shape[0])
        return self.num_rows

    @property
    def width(self) -> int:
        return len(self.columns)

    def selection_mask(self) -> jax.Array:
        """Full boolean mask over capacity combining num_rows and sel."""
        base = jnp.arange(self.capacity, dtype=jnp.int32) < self.num_rows
        if self.sel is not None:
            base = base & self.sel
        return base

    def count_rows(self) -> int:
        """Logical row count (host sync if sel is set)."""
        if self.sel is None:
            return self.num_rows
        return int(np.asarray(self.selection_mask()).sum())

    def to_host(self, extras: Sequence | None = None):
        """Pull every device array to host in ONE packed D2H transfer.

        Device→host transfers pay a large fixed latency per transfer (the
        TPU runtime round-trip dwarfs the bytes for result-sized arrays),
        so pulling a batch column-by-column costs ``(2·width+1)`` latencies.
        Instead, every packable array becomes uint32 words (int64 as lo/hi
        word lanes — TPU x64 rewriting forbids 64-bit bitcasts), one
        device-side concatenate, one transfer, host views back.

        ``extras`` (optional device arrays, e.g. deferred overflow flags)
        ride the same transfer; when given, returns (batch, extra_values).
        """
        bufs: list = []  # (kind, col_idx) aligned with `arrays`
        arrays: list = []

        def note(kind, idx, a):
            if isinstance(a, jax.Array) and _packable(a.dtype):
                arrays.append(a)
                bufs.append((kind, idx))
                return None
            return np.asarray(a) if isinstance(a, jax.Array) else a

        host_data = [note("data", j, c.data) for j, c in enumerate(self.columns)]
        host_valid = [
            None if c.valid is None else note("valid", j, c.valid)
            for j, c in enumerate(self.columns)
        ]
        host_sel = None if self.sel is None else note("sel", -1, self.sel)
        host_extras = [
            note("extra", j, a) for j, a in enumerate(extras or ())
        ]
        if arrays:
            views = _unpack_words(np.asarray(_PACK_WORDS(arrays)), arrays)
            for (kind, idx), v in zip(bufs, views):
                if kind == "data":
                    host_data[idx] = v
                elif kind == "valid":
                    host_valid[idx] = v
                elif kind == "extra":
                    host_extras[idx] = v
                else:
                    host_sel = v
        cols = [
            Column(c.type, host_data[j], host_valid[j], c.dictionary)
            for j, c in enumerate(self.columns)
        ]
        out = Batch(cols, self.num_rows, host_sel)
        if extras is None:
            return out
        return out, host_extras

    def compact(self) -> "Batch":
        """Materialize selection: gather surviving rows to the front (host)."""
        if self.sel is None and all(c.capacity == self.num_rows for c in self.columns):
            return self
        b = self.to_host()
        # host-side mask: selection_mask() would rebuild it as a device
        # array and pay another device->host round trip
        mask = np.arange(b.capacity) < b.num_rows
        if b.sel is not None:
            mask &= np.asarray(b.sel)
        idx = np.nonzero(mask)[0]
        cols = []
        for c in b.columns:
            data, valid = c.to_numpy()
            cols.append(
                Column(c.type, data[idx], None if valid[idx].all() else valid[idx], c.dictionary)
            )
        return Batch(cols, len(idx), None)

    def to_pylist(self) -> list[tuple]:
        """Rows as Python tuples (client output/testing)."""
        b = self.compact()
        out_cols = []
        for c in b.columns:
            data, valid = c.to_numpy()
            col = [
                c.type.to_python(data[i], c.dictionary) if valid[i] else None
                for i in range(b.num_rows)
            ]
            out_cols.append(col)
        return [tuple(col[i] for col in out_cols) for i in range(b.num_rows)]

    @staticmethod
    def from_pylist(schema: Sequence[tuple[str, T.SqlType]], rows: Sequence[Sequence[Any]]):
        """Build (names, Batch) from row-major Python data."""
        cols = []
        for j, (_, t) in enumerate(schema):
            cols.append(Column.from_values(t, [r[j] for r in rows]))
        return Batch(cols, len(rows), None)


def _packable(dtype) -> bool:
    return np.dtype(dtype) in (
        np.dtype(np.bool_),
        np.dtype(np.int32),
        np.dtype(np.uint32),
        np.dtype(np.float32),
        np.dtype(np.int64),
        np.dtype(np.uint64),
    )


def _pack_words(arrays):
    """Traced: flatten each array into uint32 word lanes and concatenate."""
    segs = []
    for a in arrays:
        x = jnp.ravel(a)
        dt = np.dtype(a.dtype)
        if dt == np.dtype(np.bool_):
            segs.append(x.astype(jnp.uint32))
        elif dt in (np.dtype(np.int64), np.dtype(np.uint64)):
            segs.append(x.astype(jnp.uint32))  # low word (mod 2^32)
            segs.append((x >> 32).astype(jnp.uint32))  # high word
        else:
            segs.append(jax.lax.bitcast_convert_type(x, jnp.uint32))
    return jnp.concatenate(segs) if segs else jnp.zeros(0, jnp.uint32)


_PACK_WORDS = jax.jit(_pack_words)


def _unpack_words(packed: np.ndarray, arrays) -> list[np.ndarray]:
    """Rebuild host arrays from the packed uint32 word stream."""
    out = []
    off = 0
    for a in arrays:
        dt = np.dtype(a.dtype)
        n = int(np.prod(a.shape, dtype=np.int64))
        if dt == np.dtype(np.bool_):
            out.append(packed[off : off + n].astype(np.bool_).reshape(a.shape))
            off += n
        elif dt in (np.dtype(np.int64), np.dtype(np.uint64)):
            lo = packed[off : off + n].astype(np.uint64)
            hi = packed[off + n : off + 2 * n].astype(np.uint64)
            out.append(((hi << np.uint64(32)) | lo).view(dt).reshape(a.shape))
            off += 2 * n
        else:
            out.append(packed[off : off + n].view(dt).reshape(a.shape))
            off += n
    return out


def concat_batches(batches: Sequence[Batch]) -> Batch:
    """Host-side concatenation (compacting). Used at stage boundaries."""
    if not batches:
        raise ValueError("concat of zero batches")
    batches = [b.compact() for b in batches]
    nonempty = [b for b in batches if b.num_rows > 0]
    batches = nonempty or batches[:1]
    if len(batches) == 1:
        return batches[0]
    width = batches[0].width
    cols = []
    for j in range(width):
        parts = [b.columns[j] for b in batches]
        t = parts[0].type
        dictionary = None
        if T.is_string(t):
            dictionary = parts[0].dictionary or Dictionary([])
            datas = []
            valids = []
            for p in parts:
                data, valid = p.to_numpy()
                if p.dictionary is not None and p.dictionary is not dictionary:
                    dictionary, remap = dictionary.merged(p.dictionary)
                    data = np.where(data >= 0, remap[np.maximum(data, 0)], -1).astype(np.int32)
                datas.append(data)
                valids.append(valid)
            data = np.concatenate(datas)
            valid = np.concatenate(valids)
        else:
            pairs = [p.to_numpy() for p in parts]
            data = np.concatenate([d for d, _ in pairs])
            valid = np.concatenate([v for _, v in pairs])
        cols.append(Column(t, data, None if valid.all() else valid, dictionary))
    return Batch(cols, sum(b.num_rows for b in batches), None)


def pad_batch(batch: Batch, capacity: int) -> Batch:
    """Pad physical rows up to capacity (power-of-two bucketing lives above)."""
    b = batch
    if b.capacity == capacity:
        return b
    if b.capacity > capacity:
        raise ValueError(f"batch capacity {b.capacity} > target {capacity}")
    pad = capacity - b.capacity
    cols = []
    for c in b.columns:
        data = np.asarray(c.data)
        pad_shape = (pad,) + data.shape[1:]  # wide decimals are (n, 2)
        data = np.concatenate([data, np.zeros(pad_shape, dtype=data.dtype)])
        if c.valid is not None:
            valid = np.concatenate([np.asarray(c.valid), np.zeros(pad, dtype=np.bool_)])
        else:
            valid = None
        cols.append(Column(c.type, data, valid, c.dictionary))
    sel = batch.sel
    if sel is not None:
        sel = np.concatenate([np.asarray(sel), np.zeros(pad, dtype=np.bool_)])
    return Batch(cols, b.num_rows, sel)


def bucket_capacity(n: int, minimum: int = 1024) -> int:
    """Round up to a power of two (recompile-avoidance shape bucketing)."""
    cap = minimum
    while cap < n:
        cap <<= 1
    return cap


# --- pytree registration ---------------------------------------------------
# Columns/Batches cross jit boundaries with (type, dictionary) static.


def _column_flatten(c: Column):
    return (c.data, c.valid), (c.type, c.dictionary)


def _column_unflatten(aux, children):
    t, dictionary = aux
    data, valid = children
    return Column(t, data, valid, dictionary)


def _batch_flatten(b: Batch):
    return (b.columns, b.sel), (b.num_rows,)


def _batch_unflatten(aux, children):
    (num_rows,) = aux
    columns, sel = children
    return Batch(list(columns), num_rows, sel)


jax.tree_util.register_pytree_node(Column, _column_flatten, _column_unflatten)
jax.tree_util.register_pytree_node(Batch, _batch_flatten, _batch_unflatten)
