"""Query event listeners.

Reference: ``event/QueryMonitor.java:92,134,210`` builds
created/completed events → ``eventlistener/EventListenerManager.java`` →
pluggable ``EventListener``s (``spi/eventlistener/``,
``Plugin.getEventListenerFactories`` at ``spi/Plugin.java:80``).

Stage/task completion events (``SplitCompletedEvent`` territory in the
reference) are fired by the cluster scheduler once per stage / per task
attempt, carrying the elapsed + retry accounting the observability
registry aggregates.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class QueryCreatedEvent:
    query_id: str
    sql: str
    user: str
    create_time: float


@dataclasses.dataclass
class QueryCompletedEvent:
    query_id: str
    sql: str
    user: str
    create_time: float
    end_time: float
    state: str  # FINISHED | FAILED | CANCELED
    output_rows: int = 0
    peak_memory_bytes: int = 0
    error_message: Optional[str] = None
    wall_seconds: float = 0.0
    # classification matching the /v1/query error block (trino_tpu.errors)
    error_code: Optional[int] = None
    error_type: Optional[str] = None


@dataclasses.dataclass
class StageCompletedEvent:
    query_id: str
    stage_id: int
    state: str  # FINISHED | FAILED
    tasks: int = 0
    attempts: int = 0
    elapsed_ms: float = 0.0
    # sibling task elapsed distribution (straggler/speculation signal)
    task_elapsed_p50_ms: Optional[float] = None
    task_elapsed_p99_ms: Optional[float] = None


@dataclasses.dataclass
class TaskCompletedEvent:
    query_id: str
    stage_id: int
    task_id: str
    worker: str
    state: str  # FINISHED | FAILED | CANCELED | CANCELED_SPECULATIVE | ...
    attempt: int = 1
    elapsed_ms: float = 0.0
    rows: int = 0
    error_message: Optional[str] = None
    # a hedged (duplicate) attempt of a detected straggler
    speculative: bool = False


class EventListener:
    """Subclass and override; all hooks optional (spi/eventlistener)."""

    def query_created(self, event: QueryCreatedEvent) -> None:  # noqa: B027
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:  # noqa: B027
        pass

    def stage_completed(self, event: StageCompletedEvent) -> None:  # noqa: B027
        pass

    def task_completed(self, event: TaskCompletedEvent) -> None:  # noqa: B027
        pass


class EventListenerManager:
    def __init__(self):
        self._listeners: list[EventListener] = []

    def add(self, listener: EventListener) -> None:
        self._listeners.append(listener)

    def fire_created(self, event: QueryCreatedEvent) -> None:
        for l in self._listeners:
            try:
                l.query_created(event)
            except Exception:  # noqa: BLE001 — listeners never fail queries
                pass

    def fire_completed(self, event: QueryCompletedEvent) -> None:
        for l in self._listeners:
            try:
                l.query_completed(event)
            except Exception:  # noqa: BLE001
                pass

    def fire_stage_completed(self, event: StageCompletedEvent) -> None:
        for l in self._listeners:
            try:
                l.stage_completed(event)
            except Exception:  # noqa: BLE001
                pass

    def fire_task_completed(self, event: TaskCompletedEvent) -> None:
        for l in self._listeners:
            try:
                l.task_completed(event)
            except Exception:  # noqa: BLE001
                pass
