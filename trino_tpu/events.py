"""Query event listeners.

Reference: ``event/QueryMonitor.java:92,134,210`` builds
created/completed events → ``eventlistener/EventListenerManager.java`` →
pluggable ``EventListener``s (``spi/eventlistener/``,
``Plugin.getEventListenerFactories`` at ``spi/Plugin.java:80``).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Optional


@dataclasses.dataclass
class QueryCreatedEvent:
    query_id: str
    sql: str
    user: str
    create_time: float


@dataclasses.dataclass
class QueryCompletedEvent:
    query_id: str
    sql: str
    user: str
    create_time: float
    end_time: float
    state: str  # FINISHED | FAILED
    output_rows: int = 0
    peak_memory_bytes: int = 0
    error_message: Optional[str] = None
    wall_seconds: float = 0.0


class EventListener:
    """Subclass and override; all hooks optional (spi/eventlistener)."""

    def query_created(self, event: QueryCreatedEvent) -> None:  # noqa: B027
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:  # noqa: B027
        pass


class EventListenerManager:
    def __init__(self):
        self._listeners: list[EventListener] = []

    def add(self, listener: EventListener) -> None:
        self._listeners.append(listener)

    def fire_created(self, event: QueryCreatedEvent) -> None:
        for l in self._listeners:
            try:
                l.query_created(event)
            except Exception:  # noqa: BLE001 — listeners never fail queries
                pass

    def fire_completed(self, event: QueryCompletedEvent) -> None:
        for l in self._listeners:
            try:
                l.query_completed(event)
            except Exception:  # noqa: BLE001
                pass
