"""Runtime lock-order validator (lockdep), the dynamic complement to
``concurrency.py``'s static pass.

Armed by setting ``TT_LOCKDEP=1`` before the test session starts (the
repo's conftest does this for tier-1; ``TT_LOCKDEP=0`` skips). When
installed, ``threading.Lock``/``threading.RLock`` construction returns a
tracked wrapper that:

- records the per-thread held-set at every acquire and adds an edge
  ``outer → inner`` to a global lock-order graph, keyed by each lock's
  *creation site* (``file:line``) so all instances born at one site —
  e.g. every ``Counter._lock`` — collapse into one node;
- keeps the acquisition stacks that first witnessed each edge, so a
  cycle report shows *both* nestings with full context (cf. Linux
  lockdep's "possible circular locking dependency" splat);
- detects loop-thread lock *waits*: a blocking acquire on a registered
  event-loop thread that is still unsatisfied after a short grace
  period (50ms — long enough to filter scheduler-level contention on
  short critical sections, short enough to catch locks held across
  I/O or sleeps) is recorded with the waiter's stack and the owner's
  acquisition site.

At session teardown :func:`report` returns the cycles (potential
deadlocks — two locks taken in both orders on different threads) and
loop-thread waits; the conftest gate fails the run if any exist.

Reentrant acquires of the *same lock object* (RLock) add no edges, and
self-edges between two instances from one creation site are skipped
(indistinguishable from reentrancy at site granularity).

When NOT installed this module costs nothing: ``threading.Lock`` is the
original builtin (tests assert identity), and the register/unregister
hooks are set-ops on a module-level set.
"""

from __future__ import annotations

import os
import sys
import threading
import traceback
from typing import Any, Optional

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_installed = False
_STATE_LOCK = _REAL_LOCK()  # guards the graph/report state, never tracked

# (outer_site, inner_site) -> (outer_stack, inner_stack) at first witness
_edges: dict[tuple[str, str], tuple[str, str]] = {}
# loop-thread blocking waits: (site, waiter_stack, owner_stack)
_loop_waits: list[tuple[str, str, str]] = []
_LOOP_THREADS: set[int] = set()

_tls = threading.local()

# frames from these files are plumbing, not the interesting creation site
_SKIP_FRAMES = (os.sep + "lockdep.py", os.sep + "threading.py", os.sep + "queue.py")
_OWN_FILE = __file__


_WAIT_GRACE_S = 0.05


def _creation_site() -> str:
    # cheap frame walk (no source-line lookup): lock creation can be hot
    f = sys._getframe(1)
    while f is not None and any(
        s in f.f_code.co_filename for s in _SKIP_FRAMES
    ):
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{f.f_code.co_filename}:{f.f_lineno}"


def _fmt_site(site) -> str:
    if isinstance(site, tuple):
        return f"{site[0]}:{site[1]}"
    return str(site)


def _stack(limit: int = 12) -> str:
    frames = [
        f
        for f in traceback.extract_stack()[:-2]
        if os.sep + "lockdep.py" not in f.filename
    ]
    return "".join(traceback.format_list(frames[-limit:]))


class _TrackedLock:
    """Wrapper over a real Lock/RLock recording order and wait events."""

    def __init__(self, inner: Any, reentrant: bool) -> None:
        self._inner = inner
        self._reentrant = reentrant
        self._site = _creation_site()
        self._owner_site: Any = "<never acquired>"  # (file, line) per acquire
        self._owner_stack: Optional[str] = None  # full, only when edges form

    # --- bookkeeping ------------------------------------------------------

    def _record_edges(self, held: list) -> None:
        new_edges = []
        for other in held:
            if other is self:
                if self._reentrant:
                    break  # reentrant re-acquire: no new ordering fact
                continue
            if other._site == self._site:
                # site-level self-edge: indistinguishable from reentry
                continue
            key = (other._site, self._site)
            if key not in _edges:
                new_edges.append((key, other))
        if new_edges:
            # full stacks are expensive; capture only when a new
            # ordering fact is actually being recorded
            stack = _stack()
            self._owner_stack = stack
            with _STATE_LOCK:
                for key, other in new_edges:
                    if key not in _edges:
                        outer = other._owner_stack or (
                            f"  (acquired at {_fmt_site(other._owner_site)})\n"
                        )
                        _edges[key] = (outer, stack)

    def _acquire_blocked(self, timeout: float) -> bool:
        """Contended blocking acquire (the try-probe already failed)."""
        if threading.get_ident() in _LOOP_THREADS:
            # grace probe: brief contention on a short critical section
            # is not a discipline violation; a wait that outlives the
            # grace window is
            if 0 <= timeout <= _WAIT_GRACE_S:
                return self._inner.acquire(True, timeout)
            if self._inner.acquire(True, _WAIT_GRACE_S):
                return True
            owner = self._owner_stack or (
                f"  (acquired at {_fmt_site(self._owner_site)})\n"
            )
            with _STATE_LOCK:
                _loop_waits.append((self._site, _stack(), owner))
            rem = -1 if timeout < 0 else max(0.0, timeout - _WAIT_GRACE_S)
            return self._inner.acquire(True, rem)
        return self._inner.acquire(True, timeout)

    # --- Lock protocol ----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(False)
        if not got and blocking:
            got = self._acquire_blocked(timeout)
        if got:
            try:
                held = _tls.held
            except AttributeError:
                held = _tls.held = []
            f = sys._getframe(1)
            if f.f_code.co_filename == _OWN_FILE:  # entered via ``with``
                f = f.f_back or f
            self._owner_site = (f.f_code.co_filename, f.f_lineno)
            self._owner_stack = None  # stale full stack is worse than the site
            if held:
                self._record_edges(held)
            held.append(self)
        return got

    def release(self) -> None:
        self._inner.release()
        try:
            held = _tls.held
        except AttributeError:
            return
        if held and held[-1] is self:
            held.pop()
            return
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                return

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<TrackedLock {self._site} over {self._inner!r}>"

    def __getattr__(self, name: str) -> Any:
        # Condition() integration: delegate _is_owned/_release_save/
        # _acquire_restore/_at_fork_reinit (and anything else) to the
        # real lock. RLock wait/notify semantics stay correct; the held
        # tracking is briefly stale while a Condition.wait parks, which
        # cannot create edges (the parked thread acquires nothing).
        return getattr(self._inner, name)


_only_paths: tuple[str, ...] = ()


def _track_here(site: str) -> bool:
    if not _only_paths:
        return True
    return any(site.startswith(p) for p in _only_paths)


def _tracked_lock():
    lock = _TrackedLock(_REAL_LOCK(), reentrant=False)
    if not _track_here(lock._site):
        return lock._inner  # third-party creation site: hand back the real lock
    return lock


def _tracked_rlock():
    lock = _TrackedLock(_REAL_RLOCK(), reentrant=True)
    if not _track_here(lock._site):
        return lock._inner
    return lock


# === lifecycle ==============================================================


def install(only_paths: tuple[str, ...] = ()) -> bool:
    """Swap threading.Lock/RLock for tracked factories. Idempotent.

    ``only_paths``: when non-empty, only locks whose creation site lives
    under one of these path prefixes are tracked; everything else gets a
    plain lock. Conftest passes the repo root so the validator watches
    the runtime's discipline, not jax/stdlib internals.
    """
    global _installed, _only_paths
    if _installed:
        return False
    _only_paths = tuple(only_paths)
    threading.Lock = _tracked_lock  # type: ignore[assignment]
    threading.RLock = _tracked_rlock  # type: ignore[assignment]
    _installed = True
    return True


def uninstall() -> bool:
    global _installed
    if not _installed:
        return False
    threading.Lock = _REAL_LOCK  # type: ignore[assignment]
    threading.RLock = _REAL_RLOCK  # type: ignore[assignment]
    _installed = False
    return True


def installed() -> bool:
    return _installed


def reset() -> None:
    """Clear recorded state (unit tests)."""
    with _STATE_LOCK:
        _edges.clear()
        _loop_waits.clear()


def register_loop_thread(ident: int) -> None:
    _LOOP_THREADS.add(ident)


def unregister_loop_thread(ident: int) -> None:
    _LOOP_THREADS.discard(ident)


# === reporting ==============================================================


def _find_cycles(edges: dict[tuple[str, str], tuple[str, str]]) -> list[list[str]]:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    cycles: list[list[str]] = []
    seen_sets: set[frozenset] = set()
    for start in sorted(graph):
        # DFS looking for a path back to `start`
        stack: list[tuple[str, list[str]]] = [(start, [start])]
        visited: set[str] = set()
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    key = frozenset(path)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        cycles.append(path + [start])
                elif nxt not in visited and nxt not in path:
                    visited.add(nxt)
                    stack.append((nxt, path + [nxt]))
    return cycles


def report() -> list[str]:
    """Human-readable problem list: lock-order cycles and loop waits."""
    with _STATE_LOCK:
        edges = dict(_edges)
        waits = list(_loop_waits)
    out: list[str] = []
    for cycle in _find_cycles(edges):
        lines = [
            "lock-order cycle (potential deadlock): "
            + " -> ".join(cycle)
        ]
        for a, b in zip(cycle, cycle[1:]):
            outer_stack, inner_stack = edges.get((a, b), ("", ""))
            lines.append(f"  edge {a} -> {b}")
            if outer_stack:
                lines.append("    outer held at:\n" + _indent(outer_stack, 6))
            lines.append("    inner acquired at:\n" + _indent(inner_stack, 6))
        out.append("\n".join(lines))
    for site, waiter, owner in waits:
        out.append(
            f"event-loop thread blocked acquiring lock created at {site}\n"
            "  loop thread waiting at:\n" + _indent(waiter, 4)
            + "  lock owner acquired at:\n" + _indent(owner, 4)
        )
    return out


def _indent(text: str, n: int) -> str:
    pad = " " * n
    return "".join(
        pad + line + "\n" for line in text.rstrip("\n").splitlines()
    )


def edge_count() -> int:
    with _STATE_LOCK:
        return len(_edges)
