"""AST lint for JAX tracer-safety in kernel code.

Inside ``jax.jit``-traced code, several perfectly ordinary Python idioms
become correctness or performance bugs:

- ``JIT001`` host round-trip — ``.item()`` forces a device→host sync and
  fails outright on a tracer.
- ``JIT002`` host cast — ``float()``/``int()``/``bool()`` on a ``jnp``
  expression concretizes the tracer (``ConcretizationTypeError`` under
  jit; silent host sync outside it).
- ``JIT003`` traced branch — Python ``if``/``while`` on a ``jnp``
  expression needs a concrete boolean; under jit this is a tracer leak,
  outside jit it blocks on the device.
- ``JIT004`` float-literal widening — ``jnp.array(0.5)`` & friends
  without ``dtype=`` produce float64 when x64 is enabled, silently
  widening downstream kernels and doubling memory traffic.
- ``JIT005`` unordered iteration — iterating a ``set`` to build a
  concat/collective operand order is nondeterministic across processes
  (hash randomization), which deadlocks or mis-shards SPMD collectives.
- ``JIT006`` numpy-on-device — ``np.*`` compute calls inside a
  ``jnp``-using function pull values to the host and break tracing; use
  ``jnp.*`` (or hoist the host work out of the kernel).
- ``JIT007`` inter-fragment host pull — a ``to_host``/``.item()`` sync
  followed by another fragment dispatch in the same function. With
  pipeline fusion the interior fragment boundary lives *inside* one jit
  program, so the pull is a dead device→host round trip (and blocks the
  fused chain); keep the value on device and let the program chain it.

Violations are keyed against a checked-in suppression baseline
(``baseline.json``) so CI fails only on *new* violations. A line comment
``# lint: ignore[JIT00x]`` suppresses a single finding at source level.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import sys
from pathlib import Path
from typing import Iterable, Optional

REPO_ROOT = Path(__file__).resolve().parents[2]
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"

# the whole package; the kernel-heavy dirs (ops/, exec/, parallel/) are
# where the rules bite, but host-side modules get the same scan
DEFAULT_PATHS = ("trino_tpu",)

RULES = {
    "JIT001": "host round-trip: .item() syncs device→host and fails on tracers",
    "JIT002": "host cast: float()/int()/bool() on a jnp expression leaks the tracer",
    "JIT003": "python branch on a traced jnp expression (if/while)",
    "JIT004": "float literal constructor without dtype= widens to float64 under x64",
    "JIT005": "iteration over an unordered set feeds collective/concat order",
    "JIT006": "np.* compute on device values inside a jnp-using function",
    "JIT007": "host pull (to_host/.item()) before a later fragment dispatch: "
    "fusion keeps the boundary in-jit, making the sync dead",
}

# entry points that dispatch a fragment program (or a fused chain of
# them) to the device — a host pull lexically before one of these in the
# same function straddles a fragment boundary fusion can keep on device
_FRAGMENT_DISPATCH = frozenset(
    {
        "run_fragment_program",
        "run_fused_program",
        "run_chain",
        "_run_fragment",
        "_run_fused_unit",
        "_run_fused_spanned",
        "run_fragment_program_batched",
        "run_fused_program_batched",
        "_run_fragment_batched",
        "_run_fused_unit_batched",
    }
)

# functions where a host pull before a later dispatch is the POINT: the
# cross-query batch demux pulls all K members' results in one packed
# D2H after the stacked dispatch, and its retry loop re-dispatches on
# capacity overflow — that pull/dispatch interleaving is the protocol,
# not a dead sync
_JIT007_DEMUX_ALLOWED = frozenset(
    {
        "_demux_batch_to_host",
        "_execute_fragments_batched",
    }
)

# np.* attrs that compute over array *values* (vs constructors/dtype meta,
# which are legitimate host-side prep even in device code)
_NP_COMPUTE = frozenset(
    {
        "sum", "mean", "prod", "cumsum", "cumprod", "dot", "matmul",
        "where", "nonzero", "flatnonzero", "argsort", "sort", "unique",
        "concatenate", "stack", "vstack", "hstack", "split", "take",
        "searchsorted", "bincount", "add", "subtract", "multiply",
        "divide", "minimum", "maximum", "clip", "abs", "sign", "sqrt",
        "exp", "log", "floor", "ceil", "round", "logical_and",
        "logical_or", "logical_not", "isnan", "isin", "equal",
        "not_equal", "less", "greater", "argmax", "argmin",
    }
)

_FLOAT_CONSTRUCTORS = frozenset({"array", "asarray", "full", "arange", "linspace"})

# jnp.* calls that return *static* host values at trace time (dtype
# metadata) — branching on these is trace-safe, not a tracer leak
_JNP_STATIC = frozenset(
    {"issubdtype", "isdtype", "iinfo", "finfo", "result_type",
     "promote_types", "can_cast", "dtype"}
)


@dataclasses.dataclass(frozen=True)
class Violation:
    path: str  # repo-relative when under the repo, else as given
    rule: str
    func: str  # enclosing function qualname, or "<module>"
    lineno: int
    message: str

    @property
    def key(self) -> str:
        return f"{self.path}::{self.rule}::{self.func}"

    def render(self) -> str:
        return f"{self.path}:{self.lineno}: {self.rule} [{self.func}] {self.message}"


def _aliases(tree: ast.Module) -> tuple[set[str], set[str]]:
    """(jnp aliases, np aliases) bound by this module's imports."""
    jnp: set[str] = set()
    np: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = a.asname or a.name.split(".")[0]
                if a.name in ("jax.numpy", "jnp"):
                    jnp.add(a.asname or "jnp")
                elif a.name == "numpy":
                    np.add(name)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "numpy":
                        jnp.add(a.asname or "numpy")
            elif node.module == "jax.numpy":
                jnp.add("__jnp_from_import__")  # from jax.numpy import x — rare
    return jnp, np


def _rooted_at(node: ast.expr, aliases: set[str]) -> bool:
    """True when the attribute chain bottoms out at one of `aliases`."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return isinstance(node, ast.Name) and node.id in aliases


def _mentions(node: ast.AST, aliases: set[str]) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id in aliases for n in ast.walk(node)
    )


def _has_float_literal(node: ast.expr) -> bool:
    return any(
        isinstance(n, ast.Constant) and isinstance(n.value, float)
        for n in ast.walk(node)
    )


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str, source_lines: list[str], jnp: set[str], np: set[str]):
        self.path = path
        self.lines = source_lines
        self.jnp = jnp
        self.np = np
        self.stack: list[str] = []  # enclosing function names
        self.fn_uses_jnp: list[bool] = []
        # per-function-scope JIT007 events: host pulls and fragment
        # dispatches, resolved when the scope closes (a pull only becomes
        # a violation if a dispatch follows it lexically)
        self.fn_pulls: list[list[tuple[int, ast.AST, str]]] = []
        self.fn_dispatches: list[list[int]] = []
        self.out: list[Violation] = []

    # --- helpers ----------------------------------------------------------

    def _func(self) -> str:
        return ".".join(self.stack) if self.stack else "<module>"

    def _suppressed(self, lineno: int, rule: str) -> bool:
        if 1 <= lineno <= len(self.lines):
            line = self.lines[lineno - 1]
            return f"lint: ignore[{rule}]" in line or "lint: ignore-all" in line
        return False

    def _flag(self, node: ast.AST, rule: str, detail: str = "") -> None:
        lineno = getattr(node, "lineno", 0)
        if self._suppressed(lineno, rule):
            return
        msg = RULES[rule] + (f" ({detail})" if detail else "")
        self.out.append(Violation(self.path, rule, self._func(), lineno, msg))

    # --- scope tracking ---------------------------------------------------

    def _visit_fn(self, node) -> None:
        self.stack.append(node.name)
        self.fn_uses_jnp.append(_mentions(node, self.jnp))
        self.fn_pulls.append([])
        self.fn_dispatches.append([])
        self.generic_visit(node)
        # JIT007 resolves at scope close: flag each pull that a fragment
        # dispatch follows (nested defs are their own scope, so the root
        # pull after run_units() in the driver loop stays clean)
        dispatches = self.fn_dispatches.pop()
        pulls = self.fn_pulls.pop()
        if node.name not in _JIT007_DEMUX_ALLOWED:
            for lineno, call, label in pulls:
                if any(d > lineno for d in dispatches):
                    self._flag(call, "JIT007", label)
        self.fn_uses_jnp.pop()
        self.stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    # --- rules ------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        # JIT001: x.item()
        if isinstance(fn, ast.Attribute) and fn.attr == "item" and not node.args:
            self._flag(node, "JIT001")
        # JIT002: float()/int()/bool() over a jnp expression
        if (
            isinstance(fn, ast.Name)
            and fn.id in ("float", "int", "bool")
            and node.args
            and any(_mentions(a, self.jnp) for a in node.args)
        ):
            self._flag(node, "JIT002", f"{fn.id}() on jnp value")
        # JIT004: jnp.array(0.5, ...) without dtype=
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in _FLOAT_CONSTRUCTORS
            and _rooted_at(fn, self.jnp)
            and not any(k.arg == "dtype" for k in node.keywords)
            and any(_has_float_literal(a) for a in node.args)
        ):
            self._flag(node, "JIT004", f"jnp.{fn.attr}")
        # JIT006: np compute inside a jnp-using function
        if (
            self.fn_uses_jnp
            and self.fn_uses_jnp[-1]
            and isinstance(fn, ast.Attribute)
            and fn.attr in _NP_COMPUTE
            and _rooted_at(fn, self.np)
        ):
            self._flag(node, "JIT006", f"np.{fn.attr}")
        # JIT007: record host pulls and fragment dispatches per scope
        if self.fn_pulls:
            name = (
                fn.attr
                if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else None
            )
            if name == "to_host" or (
                isinstance(fn, ast.Attribute)
                and fn.attr == "item"
                and not node.args
            ):
                self.fn_pulls[-1].append((node.lineno, node, f"{name}()"))
            elif name in _FRAGMENT_DISPATCH:
                self.fn_dispatches[-1].append(node.lineno)
        self.generic_visit(node)

    def _check_branch(self, node) -> None:
        # JIT003: the branch condition contains a call rooted at jnp
        # (jnp.any/jnp.all/arithmetic...) — attribute reads alone (dtype,
        # shape metadata) are static and fine
        for sub in ast.walk(node.test):
            if (
                isinstance(sub, ast.Call)
                and _rooted_at(sub.func, self.jnp)
                and not (
                    isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _JNP_STATIC
                )
            ):
                self._flag(node, "JIT003", "condition computes a jnp value")
                break
        self.generic_visit(node)

    visit_If = _check_branch
    visit_While = _check_branch

    def _check_iter(self, node, iter_expr: ast.expr) -> None:
        # JIT005: for x in {…} / set(…) / frozenset(…) / set comprehension
        bad = isinstance(iter_expr, (ast.Set, ast.SetComp)) or (
            isinstance(iter_expr, ast.Call)
            and isinstance(iter_expr.func, ast.Name)
            and iter_expr.func.id in ("set", "frozenset")
        )
        if bad:
            self._flag(node, "JIT005")

    def visit_For(self, node: ast.For) -> None:
        self._check_iter(node, node.iter)
        self.generic_visit(node)

    def _visit_comp(self, node) -> None:
        for gen in node.generators:
            self._check_iter(node, gen.iter)
        self.generic_visit(node)

    visit_ListComp = _visit_comp
    visit_SetComp = _visit_comp
    visit_GeneratorExp = _visit_comp
    visit_DictComp = _visit_comp


def lint_file(path: Path) -> list[Violation]:
    try:
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
    except (SyntaxError, UnicodeDecodeError, OSError) as e:
        return [
            Violation(_rel(path), "JIT000", "<module>", 0, f"unparseable: {e}")
        ]
    jnp, np = _aliases(tree)
    v = _Visitor(_rel(path), source.splitlines(), jnp, np)
    v.visit(tree)
    return v.out


def _rel(path: Path) -> str:
    try:
        return path.resolve().relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return str(path)


def lint_paths(paths: Iterable[str | Path]) -> list[Violation]:
    out: list[Violation] = []
    for p in paths:
        p = Path(p)
        if not p.is_absolute() and not p.exists():
            p = REPO_ROOT / p
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            out.extend(lint_file(f))
    return sorted(out, key=lambda v: (v.path, v.lineno, v.rule))


# === suppression baseline ===================================================


def to_baseline(violations: Iterable[Violation]) -> dict:
    counts: dict[str, int] = {}
    for v in violations:
        counts[v.key] = counts.get(v.key, 0) + 1
    return {"version": 1, "entries": dict(sorted(counts.items()))}


def load_baseline(path: Path = BASELINE_PATH) -> dict:
    if not path.exists():
        return {"version": 1, "entries": {}}
    return json.loads(path.read_text())


def compare_to_baseline(
    violations: list[Violation], baseline: dict
) -> tuple[list[Violation], list[str]]:
    """(new violations beyond baseline, stale baseline keys)."""
    allowed: dict[str, int] = dict(baseline.get("entries", {}))
    seen: dict[str, int] = {}
    new: list[Violation] = []
    for v in violations:
        seen[v.key] = seen.get(v.key, 0) + 1
        if seen[v.key] > allowed.get(v.key, 0):
            new.append(v)
    stale = [k for k, n in allowed.items() if seen.get(k, 0) < n]
    return new, stale


# === CLI ====================================================================


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m trino_tpu.lint",
        description="JAX jit-safety lint (see trino_tpu/lint/jit_safety.py)",
    )
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS))
    ap.add_argument("--baseline", type=Path, default=BASELINE_PATH)
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="report every violation, ignoring the suppression baseline",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline to the current violation set and exit 0",
    )
    args = ap.parse_args(argv)

    violations = lint_paths(args.paths)

    if args.update_baseline:
        fresh = to_baseline(violations)
        if args.baseline.exists():  # keep human-written per-entry notes
            old = json.loads(args.baseline.read_text())
            if "notes" in old:
                fresh["notes"] = old["notes"]
        args.baseline.write_text(json.dumps(fresh, indent=2) + "\n")
        print(f"baseline updated: {len(violations)} suppressed violations "
              f"-> {args.baseline}")
        return 0

    baseline = {"version": 1, "entries": {}} if args.no_baseline else load_baseline(args.baseline)
    new, stale = compare_to_baseline(violations, baseline)
    for v in new:
        print(v.render())
    for k in stale:
        print(f"note: stale baseline entry (violation fixed?): {k}")
    if new:
        print(f"\n{len(new)} new violation(s) "
              f"({len(violations)} total, {len(violations) - len(new)} baselined)")
        return 1
    print(f"clean: 0 new violations ({len(violations)} baselined)")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
