"""Static analysis for the repo (``python -m trino_tpu.lint``).

Two rule families share one harness (baseline, inline suppressions,
CLI):

- ``jit_safety.py`` — JIT### rules: host/device sync and tracer misuse
  inside jitted code.
- ``concurrency.py`` — CONC/LOCK/LOOP/THRD rules: blocking calls and
  callback fires under locks, lock-order inversions, blocking ops
  reachable from the event loop, daemon threads without a shutdown
  path.

``lockdep.py`` is the runtime complement: an opt-in (``TT_LOCKDEP=1``)
lock-order and loop-thread-wait validator armed by conftest for tier-1.

See ``baseline.json`` for the suppression baseline: CI fails only on
violations *new* relative to the baseline, so pre-existing debt is
visible but non-blocking; every entry carries a justification under
``notes``.
"""

from trino_tpu.lint import concurrency, lockdep  # noqa: F401
from trino_tpu.lint.cli import FAMILIES, lint_all, main  # noqa: F401
from trino_tpu.lint.jit_safety import (  # noqa: F401
    DEFAULT_PATHS,
    Violation,
    compare_to_baseline,
    lint_paths,
    load_baseline,
    to_baseline,
)
from trino_tpu.lint.jit_safety import RULES as _JIT_RULES

RULES = {**_JIT_RULES, **concurrency.RULES}
