"""Static analysis for JAX jit-safety (``python -m trino_tpu.lint``).

See ``jit_safety.py`` for the rule catalogue and ``baseline.json`` for the
suppression baseline: CI fails only on violations *new* relative to the
baseline, so pre-existing debt is visible but non-blocking.
"""

from trino_tpu.lint.jit_safety import (  # noqa: F401
    DEFAULT_PATHS,
    RULES,
    Violation,
    compare_to_baseline,
    lint_paths,
    load_baseline,
    main,
    to_baseline,
)
