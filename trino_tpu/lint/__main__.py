import sys

from trino_tpu.lint.cli import main

sys.exit(main())
