import sys

from trino_tpu.lint.jit_safety import main

sys.exit(main())
