"""AST lint for metrics label-cardinality discipline (OBS rules).

Prometheus time series are keyed by the full label set: every distinct
label VALUE mints a new series that lives in the registry (and every
scraper) forever. A label fed from an unbounded domain — a query id, a
fingerprint, a SQL string, a trace id — grows the registry linearly
with traffic until the process (or the Prometheus server) falls over.
The same applies to metric NAMES built from runtime strings.

- ``OBS001`` unbounded label value — a ``counter()``/``gauge()``/
  ``histogram()`` label kwarg whose value is built at runtime from an
  open domain: an f-string, ``%``-format, ``.format()``/``str()`` call,
  or an identifier whose name says it carries per-query identity
  (query id, fingerprint, sql, trace/span id, uri, user...). Closed
  vocabularies pass: string literals, plain variables with innocuous
  names (``state``, ``severity``, ``kind``), and subscripts like
  ``record["state"]``.
- ``OBS002`` dynamic metric name — the metric-name argument is an
  f-string / ``%`` / ``.format()`` expression. Legitimate only for a
  provably closed vocabulary; suppress those sites with
  ``# lint: ignore[OBS002]`` and say why.

Scope heuristic: any call of a method named ``counter``/``gauge``/
``histogram`` whose first argument is a string (literal or built) —
this is the MetricsRegistry surface (obs/metrics.py) everywhere in the
repo. Violations key against the shared lint baseline; an inline
``# lint: ignore[OBS00x]`` comment suppresses a single line.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterable

from trino_tpu.lint.jit_safety import Violation, _rel

RULES = {
    "OBS001": "unbounded metrics label value: per-query identity in a "
    "label mints one Prometheus series per query",
    "OBS002": "dynamically built metric name: runtime strings mint "
    "unbounded metric families",
}

_METRIC_METHODS = frozenset({"counter", "gauge", "histogram"})

# histogram(name, buckets=...) — structural kwargs, not labels
_NON_LABEL_KWARGS = frozenset({"buckets"})

# identifier substrings that say "this value is per-query / unbounded";
# matching is case-insensitive over the full dotted/subscripted source
# of the expression
_IDENTITY_RE = re.compile(
    r"(query_?id|queryid|trace_?id|span_?id|fingerprint|\bsql\b"
    r"|statement|\buri\b|\burl\b|\buser\b|session_?id|task_?id"
    r"|slug|token|message|error_?msg)",
    re.IGNORECASE,
)


def _is_dynamic_string(node: ast.expr) -> bool:
    """Built-at-runtime string: f-string, %-format, .format(), str()."""
    if isinstance(node, ast.JoinedStr):
        return True
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "format":
            return True
        if isinstance(fn, ast.Name) and fn.id in ("str", "repr"):
            return True
    return False


def _expr_source(node: ast.expr) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — lint must not crash on exotic AST
        return ""


def _suspicious_label(node: ast.expr) -> str:
    """Why this label value is unbounded ('' = it is fine)."""
    if _is_dynamic_string(node):
        return "runtime-built string"
    # literals and simple closed-vocabulary reads are fine unless the
    # expression's own identifiers say "per-query identity"
    if isinstance(node, ast.Constant):
        return ""
    src = _expr_source(node)
    if src and _IDENTITY_RE.search(src):
        return f"identity-bearing expression {src!r}"
    return ""


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str, source_lines: list[str]):
        self.path = path
        self.lines = source_lines
        self.stack: list[str] = []
        self.out: list[Violation] = []

    def _func(self) -> str:
        return ".".join(self.stack) if self.stack else "<module>"

    def _suppressed(self, lineno: int, rule: str) -> bool:
        if 1 <= lineno <= len(self.lines):
            line = self.lines[lineno - 1]
            return f"lint: ignore[{rule}]" in line or "lint: ignore-all" in line
        return False

    def _flag(self, node: ast.AST, rule: str, detail: str) -> None:
        lineno = getattr(node, "lineno", 0)
        if self._suppressed(lineno, rule):
            return
        self.out.append(
            Violation(
                self.path, rule, self._func(), lineno,
                RULES[rule] + (f" ({detail})" if detail else ""),
            )
        )

    def visit_FunctionDef(self, node) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in _METRIC_METHODS
            and node.args
        ):
            name_arg = node.args[0]
            name_is_str = isinstance(name_arg, ast.Constant) and isinstance(
                name_arg.value, str
            )
            if name_is_str or _is_dynamic_string(name_arg):
                if _is_dynamic_string(name_arg):
                    self._flag(
                        name_arg, "OBS002",
                        _expr_source(name_arg)[:60],
                    )
                for kw in node.keywords:
                    if kw.arg is None or kw.arg in _NON_LABEL_KWARGS:
                        continue
                    why = _suspicious_label(kw.value)
                    if why:
                        self._flag(
                            kw.value, "OBS001", f"label {kw.arg}={why}"
                        )
        self.generic_visit(node)


def lint_file(path: Path) -> list[Violation]:
    try:
        source = path.read_text()
        tree = ast.parse(source)
    except (OSError, SyntaxError):
        return []
    v = _Visitor(_rel(path), source.splitlines())
    v.visit(tree)
    return v.out


def lint_paths(paths: Iterable[str | Path]) -> list[Violation]:
    from trino_tpu.lint.jit_safety import REPO_ROOT

    out: list[Violation] = []
    for p in paths:
        p = Path(p)
        if not p.is_absolute() and not p.exists():
            p = REPO_ROOT / p
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            out.extend(lint_file(f))
    return sorted(out, key=lambda v: (v.path, v.lineno, v.rule))
