"""AST lint for threading discipline (CONC/LOOP/LOCK/THRD families).

The runtime's concurrency correctness rests on invariants that used to
live only in prose: callbacks fire OUTSIDE locks (PR 13), the event-loop
thread never blocks (PR 16), daemon threads have a shutdown path. This
pass makes them machine-checked. It builds, per module, a lock-scope
model (``with self._lock:`` blocks and explicit ``acquire()``..
``release()`` regions, lock identity by attribute path — ``self._lock``
inside ``Foo`` is the lock ``Foo._lock``) and a name-resolved call
graph, then reports:

- ``LOCK001`` lock-order inversion — two locks acquired in both orders
  anywhere in the static call graph (transitively: holding A and calling
  a function whose closure acquires B counts as A→B).
- ``LOCK002`` callback fired under a lock — a user-supplied callback,
  ``Responder.respond``, or listener invocation reachable while a lock
  is held. The fix is always snapshot-then-fire: collect under the lock,
  invoke after release.
- ``LOOP001`` blocking call in event-loop context — ``time.sleep``,
  ``urllib``/``requests`` I/O, untimed ``Lock.acquire()``, blocking
  ``queue.Queue.get/put``, ``Event``/``Condition.wait``, blocking
  socket ops (``sendall``/``connect``/``create_connection``), and jax
  host pulls (``to_host``/``device_get``/``block_until_ready``)
  reachable from loop-context seeds. Seeds: methods of the reactor
  classes (``EventLoop``/``HttpConnection``/``EventLoopHttpServer``),
  anything scheduled via ``call_soon``/``call_later``/``register``, and
  the handler passed to an ``EventLoopHttpServer(...)`` constructor.
  Thread hand-offs (``Thread(target=...)``, pool ``submit``) break
  reachability — work queued to another thread is off the loop.
- ``THRD001`` daemon thread without a shutdown path — a class that
  starts a daemon thread but contains no stop ``threading.Event()``, no
  queue ``put(None)`` sentinel, and no timer ``.cancel()``.
- ``CONC001`` blocking call while holding a lock — the same blocking
  set as LOOP001 executed inside a lock region (serializes unrelated
  callers behind slow I/O; the PR that added this check fixed
  ``SpoolWriter.finish`` doing network I/O under its finish lock).

Static analysis over dynamic dispatch is necessarily approximate: call
edges resolve ``self.m()`` within the class, bare names within the
module, ``Class.m()`` by class name, and otherwise by method name only
when that name is defined exactly once in the scanned tree. Violations
ride the shared harness — ``# lint: ignore[RULE]`` line suppressions
and the checked-in ``baseline.json`` (every baselined entry carries a
written justification in its ``notes``). The dynamic complement is
``lockdep.py``: a runtime lock-order validator armed under
``TT_LOCKDEP=1`` that catches what static resolution cannot see.
"""

from __future__ import annotations

import ast
import dataclasses
import re
import sys
from pathlib import Path
from typing import Iterable, Optional

from trino_tpu.lint.jit_safety import REPO_ROOT, Violation, _rel

RULES = {
    "CONC000": "unparseable module",
    "CONC001": "blocking call while holding a lock serializes unrelated "
    "callers behind slow work; move it outside the lock region",
    "LOCK001": "lock-order inversion: the same two locks are acquired in "
    "both orders, a potential deadlock",
    "LOCK002": "callback/listener fired while a lock is held; snapshot "
    "under the lock, fire after release",
    "LOOP001": "blocking call reachable from event-loop context; the loop "
    "thread must never block",
    "THRD001": "daemon thread started without a shutdown sentinel or stop "
    "event in the enclosing class",
}

# reactor classes whose methods run on (or marshal onto) the loop thread
_LOOP_CLASSES = frozenset({"EventLoop", "HttpConnection", "EventLoopHttpServer"})

# attribute names whose invocation means "user-supplied callback fires"
_CALLBACK_ATTRS = frozenset({"callback", "respond"})
_CALLBACK_NAME_RE = re.compile(
    r"^(cb|fn|callback|listener|handler)$|(_cb|_callback|_listener|_fn|_hook)$"
)
# receivers that look like a queue.Queue for the get/put blocking checks
_QUEUE_NAME_RE = re.compile(r"(^|_)(q|queue)s?$", re.IGNORECASE)
# lock-ish context managers: with self._lock: / with entry["lock"]:
_LOCKISH_RE = re.compile(r"lock|mutex|cond", re.IGNORECASE)

_JAX_PULLS = frozenset({"to_host", "device_get", "block_until_ready"})
_SOCKET_BLOCKING = frozenset({"sendall", "create_connection", "getaddrinfo"})


@dataclasses.dataclass
class _Event:
    """A point of interest inside one function body."""

    lineno: int
    label: str
    held: tuple[str, ...]  # lock ids held at this point


@dataclasses.dataclass
class _CallSite:
    targets: tuple[str, ...]  # symbolic resolution candidates (L:/M:/C:/U:)
    label: str  # rendered callee, for report paths
    lineno: int
    held: tuple[str, ...]


@dataclasses.dataclass
class _FnInfo:
    path: str
    qualname: str
    lineno: int
    acquires: list[_Event] = dataclasses.field(default_factory=list)
    pairs: list[tuple[str, str, int]] = dataclasses.field(default_factory=list)
    calls: list[_CallSite] = dataclasses.field(default_factory=list)
    fires: list[_Event] = dataclasses.field(default_factory=list)
    blocking: list[_Event] = dataclasses.field(default_factory=list)
    daemon_threads: list[int] = dataclasses.field(default_factory=list)
    cls: Optional[str] = None

    @property
    def key(self) -> str:
        return f"{self.path}::{self.qualname}"


@dataclasses.dataclass
class _Module:
    path: str
    lines: list[str]
    fns: dict[str, _FnInfo]  # qualname -> info
    classes: set[str]
    # classes with a shutdown mechanism: Event() attr, put(None) sentinel,
    # or timer .cancel() anywhere in the class body
    shutdown_ok: set[str]
    seeds: set[str]  # symbolic targets scheduled onto the loop


def _attr_path(node: ast.expr) -> Optional[str]:
    """Dotted path of an attribute chain rooted at a Name ('self._lock')."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last_name(node: ast.expr) -> Optional[str]:
    """Trailing identifier of a receiver expression, for name heuristics."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _is_const(node: Optional[ast.expr], value) -> bool:
    return isinstance(node, ast.Constant) and node.value is value


class _Visitor(ast.NodeVisitor):
    """Phase 1: per-module collection of function summaries and seeds."""

    def __init__(self, path: str, lines: list[str]):
        self.path = path
        self.lines = lines
        self.class_stack: list[str] = []
        self.fn_stack: list[_FnInfo] = []
        self.name_stack: list[str] = []
        # lock ids held at the current point, per function scope (a nested
        # def's body does NOT run under the enclosing with-block)
        self.held_stack: list[list[str]] = []
        self.fns: dict[str, _FnInfo] = {}
        self.classes: set[str] = set()
        self.shutdown_ok: set[str] = set()
        self.seeds: set[str] = set()

    # --- identity helpers -------------------------------------------------

    def _cls(self) -> Optional[str]:
        return self.class_stack[-1] if self.class_stack else None

    def _fn(self) -> Optional[_FnInfo]:
        return self.fn_stack[-1] if self.fn_stack else None

    def _held(self) -> tuple[str, ...]:
        return tuple(self.held_stack[-1]) if self.held_stack else ()

    def _lock_id(self, expr: ast.expr) -> Optional[str]:
        """Stable identity for a lock expression, by attribute path."""
        try:
            text = ast.unparse(expr)
        except Exception:  # pragma: no cover - defensive
            return None
        if not _LOCKISH_RE.search(text):
            return None
        path = _attr_path(expr)
        cls = self._cls()
        if path is not None and path.startswith("self.") and cls:
            return f"{cls}.{path[5:]}"
        if path is not None and "." not in path:
            # bare local/param lock: scope it to the enclosing function
            fn = self._fn()
            scope = fn.qualname if fn else "<module>"
            return f"{self.path}::{scope}.{path}"
        return path or f"{self.path}::<expr>{text}"

    def _callee_targets(self, fn_expr: ast.expr) -> tuple[tuple[str, ...], str]:
        """Symbolic resolution candidates for a call/scheduled target."""
        if isinstance(fn_expr, ast.Lambda):
            # dig one level: call_later(d, lambda: self._poll(x))
            body = fn_expr.body
            if isinstance(body, ast.Call):
                return self._callee_targets(body.func)
            return (), "<lambda>"
        if isinstance(fn_expr, ast.Name):
            return (f"M:{fn_expr.id}",), fn_expr.id
        if isinstance(fn_expr, ast.Attribute):
            recv = fn_expr.value
            m = fn_expr.attr
            if isinstance(recv, ast.Name):
                if recv.id == "self" and self._cls():
                    return (f"L:{self._cls()}.{m}", f"U:{m}"), f"self.{m}"
                if recv.id == "cls" and self._cls():
                    return (f"L:{self._cls()}.{m}", f"U:{m}"), f"cls.{m}"
                # Class.m() or module.m() — try class-method then unique
                return (f"C:{recv.id}.{m}", f"U:{m}"), f"{recv.id}.{m}"
            # obj.attr.m(): unique-method fallback only
            return (f"U:{m}",), f"…{m}"
        return (), "<dynamic>"

    # --- scope tracking ---------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.classes.add(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_fn(self, node) -> None:
        self.name_stack.append(node.name)
        # qualname = Class.outer.inner / outer.inner / name
        qual = ".".join(
            ([self.class_stack[-1]] if self.class_stack else [])
            + self.name_stack
        )
        info = _FnInfo(self.path, qual, node.lineno, cls=self._cls())
        self.fns[qual] = info
        self.fn_stack.append(info)
        self.held_stack.append([])  # fresh: body doesn't run under caller's locks
        for stmt in node.body:
            self.visit(stmt)
        self.held_stack.pop()
        self.fn_stack.pop()
        self.name_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # a lambda body runs later, not under the current lock scope; its
        # events are out of scope for this static pass
        return

    def visit_With(self, node: ast.With) -> None:
        fn = self._fn()
        ids: list[str] = []
        for item in node.items:
            expr = item.context_expr
            # `with self._lock:` — a bare lock/condition context manager
            if isinstance(expr, (ast.Attribute, ast.Name, ast.Subscript)):
                lock_id = self._lock_id(expr)
                if lock_id is not None:
                    ids.append(lock_id)
            else:
                self.visit(expr)
        held = self.held_stack[-1] if self.held_stack else []
        if fn is not None:
            for lock_id in ids:
                for outer in held:
                    if outer != lock_id:
                        fn.pairs.append((outer, lock_id, node.lineno))
                fn.acquires.append(_Event(node.lineno, lock_id, tuple(held)))
        held.extend(ids)
        for stmt in node.body:
            self.visit(stmt)
        for lock_id in reversed(ids):
            for i in range(len(held) - 1, -1, -1):
                if held[i] == lock_id:
                    del held[i]
                    break

    def visit_Assign(self, node: ast.Assign) -> None:
        # `t.daemon = True` marks the thread daemon post-construction
        for t in node.targets:
            if (
                isinstance(t, ast.Attribute)
                and t.attr == "daemon"
                and _is_const(node.value, True)
            ):
                fn = self._fn()
                if fn is not None:
                    fn.daemon_threads.append(node.lineno)
        self.generic_visit(node)

    # --- calls ------------------------------------------------------------

    def _record_blocking(self, node: ast.Call, label: str) -> None:
        fn = self._fn()
        if fn is not None:
            fn.blocking.append(_Event(node.lineno, label, self._held()))

    def _maybe_schedule_seed(self, node: ast.Call, attr: str) -> None:
        """Targets of call_soon/call_later/register become loop seeds."""
        idx = {"call_soon": 0, "call_later": 1, "register": 2}.get(attr)
        if idx is None or len(node.args) <= idx:
            return
        targets, _ = self._callee_targets(node.args[idx])
        self.seeds.update(targets)

    def visit_Call(self, node: ast.Call) -> None:  # noqa: C901 — rule dispatch
        fn_info = self._fn()
        fn = node.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else None
        name = fn.id if isinstance(fn, ast.Name) else None
        recv = fn.value if isinstance(fn, ast.Attribute) else None
        recv_name = _last_name(recv) if recv is not None else None
        held = self._held()
        dotted = _attr_path(fn) or ""

        # --- loop seeds and shutdown markers (module/class level facts)
        if attr in ("call_soon", "call_later", "register"):
            self._maybe_schedule_seed(node, attr)
        if name == "EventLoopHttpServer" or attr == "EventLoopHttpServer":
            for a in node.args:
                targets, _ = self._callee_targets(a)
                self.seeds.update(targets)
        cls = self._cls()
        if cls:
            # shutdown mechanisms: a stop Event, a queue None-sentinel, a
            # timer cancel, or joining the thread (bounded hand-off)
            if (name == "Event" or attr == "Event") and not node.args:
                self.shutdown_ok.add(cls)
            if attr in ("put", "put_nowait") and node.args and _is_const(
                node.args[0], None
            ):
                self.shutdown_ok.add(cls)
            if attr == "cancel" and not node.args:
                self.shutdown_ok.add(cls)
            # thread join (possibly deadline-bounded) — receiver must look
            # like a thread, so str.join/os.path.join don't count
            if (
                attr == "join"
                and len(node.args) <= 1
                and recv_name is not None
                and re.search(r"^t$|thread|_t$|worker", recv_name)
            ):
                self.shutdown_ok.add(cls)

        # --- THRD001: daemon thread construction
        if (name == "Thread" or attr in ("Thread", "Timer")) and _is_const(
            _kw(node, "daemon"), True
        ):
            if fn_info is not None:
                fn_info.daemon_threads.append(node.lineno)

        # --- explicit acquire()/release() regions
        if attr == "acquire" and recv is not None:
            lock_id = self._lock_id(recv)
            if lock_id is not None and fn_info is not None:
                nonblocking = _is_const(_kw(node, "blocking"), False) or (
                    node.args and _is_const(node.args[0], False)
                )
                timed = _kw(node, "timeout") is not None or len(node.args) >= 2
                for outer in held:
                    if outer != lock_id:
                        fn_info.pairs.append((outer, lock_id, node.lineno))
                fn_info.acquires.append(_Event(node.lineno, lock_id, held))
                if not nonblocking and not timed:
                    self._record_blocking(node, "untimed Lock.acquire()")
                if not nonblocking:
                    self.held_stack[-1].append(lock_id)
        elif attr == "release" and recv is not None:
            lock_id = self._lock_id(recv)
            if lock_id is not None and self.held_stack and lock_id in self.held_stack[-1]:
                self.held_stack[-1].remove(lock_id)

        # --- blocking-op catalogue (LOOP001 / CONC001 inputs)
        if dotted.endswith("time.sleep") or dotted == "sleep":
            self._record_blocking(node, "time.sleep")
        elif attr == "urlopen" or dotted.endswith("urllib.request.urlopen"):
            self._record_blocking(node, "urllib urlopen")
        elif dotted.startswith("requests.") and attr in (
            "get", "post", "put", "delete", "request", "head",
        ):
            self._record_blocking(node, f"requests.{attr}")
        elif attr in _SOCKET_BLOCKING:
            self._record_blocking(node, f"socket {attr}")
        elif attr == "connect" and recv_name and "sock" in recv_name.lower():
            self._record_blocking(node, "socket connect")
        elif attr in _JAX_PULLS or name in _JAX_PULLS:
            self._record_blocking(node, f"jax host pull ({attr or name})")
        elif attr == "wait" and recv is not None:
            # Event.wait blocks the calling thread. Condition.wait is the
            # one legitimate wait-under-lock (it releases the lock), so a
            # lockish receiver (self._cond, self._lock-as-Condition) is
            # exempt; a constant-zero timeout is a non-blocking poll.
            arg = node.args[0] if node.args else _kw(node, "timeout")
            zero = isinstance(arg, ast.Constant) and arg.value in (0, 0.0)
            lockish = recv_name is not None and _LOCKISH_RE.search(recv_name)
            if not zero and not lockish:
                self._record_blocking(node, f"{recv_name or '?'}.wait")
        elif (
            attr in ("get", "put")
            and recv_name is not None
            and _QUEUE_NAME_RE.search(recv_name)
        ):
            if attr == "get":
                # Queue.get() / get(True) / get(timeout=...) block; a
                # non-bool first positional is dict-style get(key, default)
                blocking = (
                    not node.args or _is_const(node.args[0], True)
                ) and not _is_const(_kw(node, "block"), False)
            else:
                blocking = not (
                    _is_const(_kw(node, "block"), False)
                    or (len(node.args) > 1 and _is_const(node.args[1], False))
                )
            if blocking:
                self._record_blocking(
                    node, f"Queue.{attr} without block=False"
                )

        # --- LOCK002 inputs: callback fires
        fired = None
        if attr in _CALLBACK_ATTRS:
            fired = f"{recv_name or '?'}.{attr}()"
        elif name is not None and _CALLBACK_NAME_RE.search(name):
            fired = f"{name}()"
        if fired is not None and fn_info is not None:
            fn_info.fires.append(_Event(node.lineno, fired, held))

        # --- call-graph edge (skip pure hand-offs: Thread targets and
        # scheduled callbacks run on another thread / later on the loop)
        if fn_info is not None and attr not in (
            "call_soon", "call_later", "register",
        ) and name != "Thread" and attr != "Thread":
            targets, label = self._callee_targets(fn)
            if targets:
                fn_info.calls.append(
                    _CallSite(targets, label, node.lineno, held)
                )
        # visit the receiver chain (nested calls like get_registry().x())
        # and argument expressions; Lambda bodies stay skipped (deferred)
        if isinstance(fn, ast.Attribute):
            self.visit(fn.value)
        for a in node.args:
            self.visit(a)
        for k in node.keywords:
            self.visit(k.value)


def scan_file(path: Path) -> _Module | Violation:
    try:
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
    except (SyntaxError, UnicodeDecodeError, OSError) as e:
        return Violation(_rel(path), "CONC000", "<module>", 0, f"unparseable: {e}")
    v = _Visitor(_rel(path), source.splitlines())
    v.visit(tree)
    return _Module(
        path=v.path,
        lines=v.lines,
        fns=v.fns,
        classes=v.classes,
        shutdown_ok=v.shutdown_ok,
        seeds=v.seeds,
    )


# === phase 2: whole-package analysis ========================================


class _Index:
    """Resolves symbolic call targets against every scanned module."""

    def __init__(self, modules: list[_Module]):
        self.modules = modules
        self.by_key: dict[str, _FnInfo] = {}
        self.by_qual: dict[str, list[_FnInfo]] = {}
        self.by_method: dict[str, list[_FnInfo]] = {}
        self.classes: set[str] = set()
        for m in modules:
            self.classes |= m.classes
            for info in m.fns.values():
                self.by_key[info.key] = info
                self.by_qual.setdefault(info.qualname, []).append(info)
                tail = info.qualname.rsplit(".", 1)[-1]
                self.by_method.setdefault(tail, []).append(info)

    def resolve(self, site_path: str, target: str) -> list[_FnInfo]:
        kind, _, rest = target.partition(":")
        if kind == "L":  # same-file Class.method
            info = self.by_key.get(f"{site_path}::{rest}")
            if info is not None:
                return [info]
            # fall through to unique-method via the U: candidate
            return []
        if kind == "M":  # same-file function (incl. nested closures)
            out = [
                i
                for i in self.by_qual.get(rest, [])
                if i.path == site_path
            ]
            if out:
                return out
            return [
                i
                for m in self.modules
                if m.path == site_path
                for i in m.fns.values()
                if i.qualname.endswith(f".{rest}")
            ]
        if kind == "C":  # Class.method anywhere, when the class is known
            cls = rest.split(".", 1)[0]
            if cls in self.classes:
                return self.by_qual.get(rest, [])
            return []
        if kind == "U":  # unique method name anywhere
            infos = [
                i for i in self.by_method.get(rest, []) if i.cls is not None
            ]
            return infos if len(infos) == 1 else []
        return []

    def resolve_site(self, site_path: str, targets: Iterable[str]) -> list[_FnInfo]:
        for t in targets:
            out = self.resolve(site_path, t)
            if out:
                return out
        return []


@dataclasses.dataclass
class _Closure:
    """Transitive facts about a function: everything its call tree does."""

    locks: dict[str, tuple[str, int, str]]  # lock_id -> (path, lineno, via)
    fires: dict[str, tuple[str, int, str]]  # label -> (path, lineno, via)
    blocking: dict[str, tuple[str, int, str]]  # label -> (path, lineno, via)


def _closures(index: _Index) -> dict[str, _Closure]:
    """Fixpoint of per-function transitive lock/fire/blocking facts."""
    out: dict[str, _Closure] = {
        k: _Closure(
            locks={
                e.label: (f.path, e.lineno, f.qualname)
                for e in f.acquires
            },
            fires={
                e.label: (f.path, e.lineno, f.qualname) for e in f.fires
            },
            blocking={
                e.label: (f.path, e.lineno, f.qualname)
                for e in f.blocking
            },
        )
        for k, f in index.by_key.items()
    }
    changed = True
    while changed:
        changed = False
        for key, info in index.by_key.items():
            mine = out[key]
            for site in info.calls:
                for callee in index.resolve_site(info.path, site.targets):
                    theirs = out[callee.key]
                    for field in ("locks", "fires", "blocking"):
                        src: dict = getattr(theirs, field)
                        dst: dict = getattr(mine, field)
                        for label, wit in src.items():
                            if label not in dst:
                                dst[label] = wit
                                changed = True
    return out


def _suppressed(module: _Module, lineno: int, rule: str) -> bool:
    if 1 <= lineno <= len(module.lines):
        line = module.lines[lineno - 1]
        return f"lint: ignore[{rule}]" in line or "lint: ignore-all" in line
    return False


def _loop_reachable(index: _Index, modules: list[_Module]) -> dict[str, str]:
    """fn key -> human-readable seed chain, BFS over resolved call edges."""
    from collections import deque

    reached: dict[str, str] = {}
    queue: deque[str] = deque()

    def seed(info: _FnInfo, why: str) -> None:
        if info.key not in reached:
            reached[info.key] = why
            queue.append(info.key)

    for m in modules:
        for info in m.fns.values():
            if info.cls in _LOOP_CLASSES:
                seed(info, f"loop class {info.cls}")
        for target in m.seeds:
            for info in index.resolve(m.path, target):
                seed(info, "scheduled on loop")
    while queue:
        key = queue.popleft()
        info = index.by_key[key]
        for site in info.calls:
            for callee in index.resolve_site(info.path, site.targets):
                if callee.key not in reached:
                    reached[callee.key] = f"{reached[key]} → {info.qualname}"
                    queue.append(callee.key)
    return reached


def analyze(modules: list[_Module]) -> list[Violation]:
    index = _Index(modules)
    closures = _closures(index)
    reached = _loop_reachable(index, modules)
    by_path = {m.path: m for m in modules}
    out: list[Violation] = []

    def flag(
        info: _FnInfo, lineno: int, rule: str, detail: str
    ) -> None:
        module = by_path[info.path]
        if _suppressed(module, lineno, rule):
            return
        out.append(
            Violation(
                info.path, rule, info.qualname, lineno,
                RULES[rule] + (f" ({detail})" if detail else ""),
            )
        )

    # --- LOCK001: collect ordered pairs (intra-fn + via call graph) -------
    pairs: dict[tuple[str, str], tuple[_FnInfo, int, str]] = {}
    for info in index.by_key.values():
        for outer, inner, lineno in info.pairs:
            pairs.setdefault((outer, inner), (info, lineno, "direct"))
        for site in info.calls:
            if not site.held:
                continue
            for callee in index.resolve_site(info.path, site.targets):
                for lock_id, wit in closures[callee.key].locks.items():
                    for outer in site.held:
                        if outer != lock_id:
                            pairs.setdefault(
                                (outer, lock_id),
                                (info, site.lineno, f"via {site.label}"),
                            )
    for (a, b), (info, lineno, how) in sorted(
        pairs.items(), key=lambda kv: (kv[1][0].path, kv[1][1])
    ):
        if (b, a) in pairs and a < b:  # report each inverted pair once
            other = pairs[(b, a)]
            flag(
                info, lineno, "LOCK001",
                f"{b} acquired under {a} here [{how}]; inverse order at "
                f"{other[0].path}:{other[1]}",
            )
            flag(
                other[0], other[1], "LOCK001",
                f"{a} acquired under {b} here [{other[2]}]; inverse order "
                f"at {info.path}:{lineno}",
            )

    # --- LOCK002 / CONC001: events under a held lock ----------------------
    for info in index.by_key.values():
        for e in info.fires:
            if e.held:
                flag(
                    info, e.lineno, "LOCK002",
                    f"{e.label} under {e.held[-1]}",
                )
        for e in info.blocking:
            if e.held:
                flag(
                    info, e.lineno, "CONC001",
                    f"{e.label} under {e.held[-1]}",
                )
        for site in info.calls:
            if not site.held:
                continue
            for callee in index.resolve_site(info.path, site.targets):
                cl = closures[callee.key]
                for label, (_, _, via) in cl.fires.items():
                    flag(
                        info, site.lineno, "LOCK002",
                        f"{site.label}() fires {label} in {via} under "
                        f"{site.held[-1]}",
                    )
                for label, (_, _, via) in cl.blocking.items():
                    flag(
                        info, site.lineno, "CONC001",
                        f"{site.label}() blocks on {label} in {via} under "
                        f"{site.held[-1]}",
                    )

    # --- LOOP001: blocking ops in loop-reachable functions ----------------
    for key, why in reached.items():
        info = index.by_key[key]
        for e in info.blocking:
            flag(
                info, e.lineno, "LOOP001",
                f"{e.label}; loop context: {why}",
            )

    # --- THRD001: daemon threads without a class shutdown path ------------
    shutdown_ok: set[str] = set()
    for m in modules:
        shutdown_ok |= m.shutdown_ok
    for info in index.by_key.values():
        if info.cls is None or info.cls in shutdown_ok:
            continue
        for lineno in info.daemon_threads:
            flag(
                info, lineno, "THRD001",
                f"class {info.cls} has no stop Event/sentinel/cancel",
            )

    return sorted(out, key=lambda v: (v.path, v.lineno, v.rule))


def lint_paths(paths: Iterable[str | Path]) -> list[Violation]:
    modules: list[_Module] = []
    errors: list[Violation] = []
    for p in paths:
        p = Path(p)
        if not p.is_absolute() and not p.exists():
            p = REPO_ROOT / p
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            scanned = scan_file(f)
            if isinstance(scanned, Violation):
                errors.append(scanned)
            else:
                modules.append(scanned)
    return sorted(
        errors + analyze(modules), key=lambda v: (v.path, v.lineno, v.rule)
    )


def main(argv: Optional[list[str]] = None) -> int:  # pragma: no cover
    from trino_tpu.lint.cli import main as cli_main

    return cli_main(["--only", "concurrency"] + list(argv or []))


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main(sys.argv[1:]))
