"""Unified lint driver: jit-safety (JIT*) + concurrency (CONC/LOOP/LOCK/THRD).

Both check families share one suppression baseline (``baseline.json``)
and one CLI; ``--only`` narrows to a single family and ``--stats``
prints per-rule counts of the full (pre-baseline) violation set.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from pathlib import Path
from typing import Optional

from trino_tpu.lint import concurrency, jit_safety, obs_metrics
from trino_tpu.lint.jit_safety import (
    BASELINE_PATH,
    DEFAULT_PATHS,
    Violation,
    compare_to_baseline,
    load_baseline,
    to_baseline,
)

FAMILIES = {
    "jit": jit_safety.lint_paths,
    "concurrency": concurrency.lint_paths,
    "obs": obs_metrics.lint_paths,
}


def lint_all(paths, only: Optional[str] = None) -> list[Violation]:
    out: list[Violation] = []
    for name, fn in FAMILIES.items():
        if only is None or only == name:
            out.extend(fn(paths))
    return sorted(out, key=lambda v: (v.path, v.lineno, v.rule))


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m trino_tpu.lint",
        description="static analysis: JAX jit-safety + concurrency "
        "discipline (see trino_tpu/lint/)",
    )
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS))
    ap.add_argument("--baseline", type=Path, default=BASELINE_PATH)
    ap.add_argument(
        "--only", choices=sorted(FAMILIES),
        help="run a single check family",
    )
    ap.add_argument(
        "--stats", action="store_true",
        help="print per-rule violation counts (before baseline filtering)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="report every violation, ignoring the suppression baseline",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline to the current violation set and exit 0",
    )
    args = ap.parse_args(argv)

    violations = lint_all(args.paths, only=args.only)

    if args.stats:
        counts = Counter(v.rule for v in violations)
        for rule, n in sorted(counts.items()):
            print(f"{rule}: {n}")
        print(f"total: {len(violations)}")

    if args.update_baseline:
        if args.only:
            # the baseline always covers every family — a partial run
            # must not drop the other family's entries
            violations = lint_all(args.paths)
        fresh = to_baseline(violations)
        if args.baseline.exists():  # keep human-written per-entry notes
            old = json.loads(args.baseline.read_text())
            if "notes" in old:
                fresh["notes"] = old["notes"]
        args.baseline.write_text(json.dumps(fresh, indent=2) + "\n")
        print(
            f"baseline updated: {len(violations)} suppressed violations "
            f"-> {args.baseline}"
        )
        return 0

    baseline = (
        {"version": 1, "entries": {}}
        if args.no_baseline
        else load_baseline(args.baseline)
    )
    if args.only:
        # compare only against this family's slice of the baseline
        prefixes = {
            "jit": ("JIT",),
            "concurrency": ("CONC", "LOOP", "LOCK", "THRD"),
            "obs": ("OBS",),
        }
        keep = prefixes[args.only]
        baseline = {
            "version": baseline.get("version", 1),
            "entries": {
                k: n
                for k, n in baseline.get("entries", {}).items()
                if k.split("::")[1].startswith(keep)
            },
        }
    new, stale = compare_to_baseline(violations, baseline)
    for v in new:
        print(v.render())
    for k in stale:
        print(f"note: stale baseline entry (violation fixed?): {k}")
    if new:
        print(
            f"\n{len(new)} new violation(s) "
            f"({len(violations)} total, {len(violations) - len(new)} baselined)"
        )
        return 1
    print(f"clean: 0 new violations ({len(violations)} baselined)")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
