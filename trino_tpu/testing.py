"""Test harness: LocalQueryRunner / DistributedQueryRunner analogs.

Reference: ``core/trino-main/src/main/java/io/trino/testing/LocalQueryRunner.java:221,631``
(single-process full stack) and
``testing/trino-testing/.../DistributedQueryRunner.java:72`` (N workers in
one process — here N mesh shards with real collectives). Both delegate to
:class:`trino_tpu.engine.Engine`, the same core the HTTP server serves.
The correctness oracle is NumPy recomputation over the same generated data
(the reference's H2-oracle pattern, ``H2QueryRunner.java``).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from trino_tpu.config import Session
from trino_tpu.engine import Engine
from trino_tpu.planner import plan as P
from trino_tpu.sql import parse_statement


class LocalQueryRunner:
    """Parse -> analyze/plan -> execute, one process, no RPC."""

    def __init__(self, session: Optional[Session] = None):
        self.session = session or Session()
        self.engine = Engine()

    @property
    def catalogs(self):
        return self.engine.catalogs

    @property
    def memory_pool(self):
        return self.engine.memory_pool

    def plan(self, sql: str) -> P.PlanNode:
        return self.engine.plan(parse_statement(sql), self.session)

    def execute(self, sql: str) -> tuple[list[tuple], list[str]]:
        res = self.engine.execute_statement(sql, self.session)
        return res.rows, res.column_names

    def explain(self, sql: str) -> str:
        return P.plan_text(self.plan(sql))

    def assert_query(self, sql: str, expected: Sequence[tuple], ordered: bool = False):
        rows, _ = self.execute(sql)
        got = rows if ordered else sorted(map(tuple, rows))
        want = list(expected) if ordered else sorted(map(tuple, expected))
        assert got == want, f"query mismatch:\n got: {got[:20]}\nwant: {want[:20]}"


class DistributedQueryRunner(LocalQueryRunner):
    """Multi-shard runner over a device mesh: every query executes SPMD
    with real collectives between shards."""

    def __init__(self, session: Optional[Session] = None, n_devices: Optional[int] = None):
        super().__init__(session)
        from trino_tpu.parallel.mesh import make_mesh

        self.mesh = make_mesh(n_devices)
        self.engine.mesh = self.mesh
        self.session.set("execution_mode", "distributed")
