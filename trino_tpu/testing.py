"""Test harness: LocalQueryRunner / DistributedQueryRunner analogs.

Reference: ``core/trino-main/src/main/java/io/trino/testing/LocalQueryRunner.java:221,631``
(single-process full stack) and
``testing/trino-testing/.../DistributedQueryRunner.java:72`` (N workers in
one process — here N mesh shards with real collectives). Both delegate to
:class:`trino_tpu.engine.Engine`, the same core the HTTP server serves.
The correctness oracle is NumPy recomputation over the same generated data
(the reference's H2-oracle pattern, ``H2QueryRunner.java``).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from trino_tpu.config import Session
from trino_tpu.engine import Engine
from trino_tpu.planner import plan as P
from trino_tpu.sql import parse_statement


class LocalQueryRunner:
    """Parse -> analyze/plan -> execute, one process, no RPC."""

    def __init__(
        self, session: Optional[Session] = None, engine: Optional[Engine] = None
    ):
        self.session = session or Session()
        # sharing an engine across runners shares connector state/caches
        # (the reference's QueryRunner-over-TestingTrinoServer pattern)
        self.engine = engine or Engine()

    @property
    def catalogs(self):
        return self.engine.catalogs

    @property
    def memory_pool(self):
        return self.engine.memory_pool

    def plan(self, sql: str) -> P.PlanNode:
        return self.engine.plan(parse_statement(sql), self.session)

    def execute(self, sql: str) -> tuple[list[tuple], list[str]]:
        res = self.engine.execute_statement(sql, self.session)
        return res.rows, res.column_names

    def explain(self, sql: str) -> str:
        return P.plan_text(self.plan(sql))

    def assert_query(self, sql: str, expected: Sequence[tuple], ordered: bool = False):
        rows, _ = self.execute(sql)
        got = rows if ordered else sorted(map(tuple, rows))
        want = list(expected) if ordered else sorted(map(tuple, expected))
        assert got == want, f"query mismatch:\n got: {got[:20]}\nwant: {want[:20]}"


class DistributedQueryRunner(LocalQueryRunner):
    """Multi-shard runner over a device mesh: every query executes SPMD
    with real collectives between shards."""

    def __init__(self, session: Optional[Session] = None, n_devices: Optional[int] = None):
        super().__init__(session)
        from trino_tpu.parallel.mesh import make_mesh

        self.mesh = make_mesh(n_devices)
        self.engine.mesh = self.mesh
        self.session.set("execution_mode", "distributed")


class MultiProcessQueryRunner:
    """N separate server *processes* — a coordinator and N-1 workers — with
    queries flowing through real HTTP task dispatch and page exchange.

    Reference: ``testing/trino-testing/.../DistributedQueryRunner.java:72``
    (N real TestingTrinoServer instances; here real OS processes, which is
    stricter: nothing can leak through shared memory).
    """

    def __init__(
        self,
        n_workers: int = 2,
        platform: str = "cpu",
        spmd: bool = False,
        cluster_memory_limit_bytes: Optional[int] = None,
        catalogs: Optional[list] = None,
    ):
        import os
        import subprocess
        import time
        import urllib.request

        import secrets as _secrets

        self._procs: list[subprocess.Popen] = []
        self.spmd = spmd
        self.platform = platform
        env = dict(os.environ)
        # one internal credential per PROCESS (not per cluster): rotating
        # it would 401 the parent's calls to an older still-live cluster
        from trino_tpu.server.auth import ENV_VAR as _AUTH_ENV

        if not os.environ.get(_AUTH_ENV):
            os.environ[_AUTH_ENV] = _secrets.token_hex(16)
        env[_AUTH_ENV] = os.environ[_AUTH_ENV]
        env.pop("PALLAS_AXON_POOL_IPS", None)  # workers run CPU-only
        env["JAX_PLATFORMS"] = platform
        # share the parent's persistent compile cache: a cold worker cache
        # makes first-query compiles race the exchange timeouts
        env.setdefault(
            "TRINO_TPU_COMPILE_CACHE",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                ".jax_cache",
            ),
        )

        self._logs: list[list[str]] = []
        self._env = env
        self._cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        popen = self._popen
        await_listening = self._await_listening

        spmd_args: list[list[str]] = []
        if spmd:
            # one jax.distributed group: coordinator = rank 0
            import socket

            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            dist_port = s.getsockname()[1]
            s.close()
            nprocs = n_workers + 1
            spmd_args = [
                [
                    "--spmd-coordinator",
                    f"127.0.0.1:{dist_port}",
                    "--spmd-procs",
                    str(nprocs),
                    "--spmd-rank",
                    str(rank),
                ]
                for rank in range(nprocs)
            ]

        catalog_args: list[str] = []
        for spec in catalogs or []:
            catalog_args += ["--catalog", spec]
        self._catalog_args = catalog_args
        coord_args = ["--role", "coordinator", "--platform", platform]
        coord_args += catalog_args
        if cluster_memory_limit_bytes is not None:
            coord_args += [
                "--cluster-memory-limit-bytes", str(cluster_memory_limit_bytes)
            ]
        coord_proc = popen(coord_args + (spmd_args[0] if spmd else []))
        if spmd:
            # workers must join the jax.distributed group before any process
            # finishes booting; spawn all before reading LISTENING lines.
            # Workers discover the coordinator lazily via --discovery-wait.
            self.coordinator_uri = None
            worker_procs = [
                popen(
                    [
                        "--role",
                        "worker",
                        "--node-id",
                        f"worker-{i}",
                        "--discovery",
                        "@coordinator",
                        "--platform",
                        platform,
                    ]
                    + catalog_args
                    + spmd_args[i + 1]
                )
                for i in range(n_workers)
            ]
            self.coordinator_uri = await_listening(coord_proc)
            self._worker_procs = worker_procs
            self.worker_uris = [await_listening(p) for p in worker_procs]
            # late discovery: tell each worker where the coordinator is
            import json as _json

            from trino_tpu.server import auth as _auth

            for uri in self.worker_uris:
                req = urllib.request.Request(
                    f"{uri}/v1/discovery",
                    data=_json.dumps(
                        {"uri": self.coordinator_uri}
                    ).encode(),
                    method="PUT",
                    headers=_auth.headers(),
                )
                urllib.request.urlopen(req, timeout=10)
        else:
            self.coordinator_uri = await_listening(coord_proc)
            self._worker_procs = [
                popen(self._worker_args(i)) for i in range(n_workers)
            ]
            self.worker_uris = [
                await_listening(p) for p in self._worker_procs
            ]
        # wait for every worker to be announced and healthy
        deadline = time.time() + 60
        import json as _json

        while time.time() < deadline:
            with urllib.request.urlopen(f"{self.coordinator_uri}/v1/node") as r:
                info = _json.loads(r.read().decode())
            if len(info.get("nodes", [])) >= n_workers:
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("workers did not announce in time")

    def _popen(self, args):
        import subprocess
        import sys

        proc = subprocess.Popen(
            [sys.executable, "-m", "trino_tpu.server.main", *args],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=self._env,
            cwd=self._cwd,
        )
        self._procs.append(proc)
        return proc

    def _await_listening(self, proc):
        import threading
        import time

        deadline = time.time() + 180
        while time.time() < deadline:
            line = proc.stdout.readline()
            if line.startswith("LISTENING "):
                # keep draining the pipe: an undrained 64KB pipe buffer
                # blocks the child on its next write and freezes it
                log: list[str] = []
                self._logs.append(log)

                def drain(stream=proc.stdout, log=log):
                    for ln in stream:
                        log.append(ln)

                threading.Thread(target=drain, daemon=True).start()
                return line.split()[1].strip()
            if proc.poll() is not None:
                raise RuntimeError(
                    f"server process exited: {proc.stdout.read()}"
                )
        raise TimeoutError("server did not start in time")

    def _worker_args(self, i: int) -> list[str]:
        return [
            "--role", "worker",
            "--node-id", f"worker-{i}",
            "--discovery", self.coordinator_uri,
            "--platform", self.platform,
        ] + self._catalog_args

    def execute(self, sql: str, session_properties: Optional[dict] = None):
        from trino_tpu.client import ClientSession, StatementClient

        cs = ClientSession(
            properties={"execution_mode": "cluster", **(session_properties or {})}
        )
        client = StatementClient(self.coordinator_uri, sql, cs)
        rows = list(client.rows())
        names = [c.name for c in client.columns] if client.columns else []
        return rows, names

    # --- chaos / lifecycle hooks (non-SPMD clusters only) ----------------

    def kill_worker(self, i: int, timeout: float = 10.0) -> None:
        """SIGKILL worker ``i`` — no drain, no goodbye; simulates node
        death for spool/lineage recovery tests."""
        p = self._worker_procs[i]
        p.kill()
        p.wait(timeout=timeout)

    def drain_worker(self, i: int, timeout: float = 120.0) -> None:
        """Graceful decommission: ``PUT /v1/info/state SHUTTING_DOWN``
        stops admission, finishes running tasks, force-spools retained
        buffers, deregisters, and exits the process."""
        import urllib.request

        from trino_tpu.server import auth as _auth

        req = urllib.request.Request(
            f"{self.worker_uris[i]}/v1/info/state",
            data=b'"SHUTTING_DOWN"',
            method="PUT",
            headers=_auth.headers(),
        )
        urllib.request.urlopen(req, timeout=10)
        self._worker_procs[i].wait(timeout=timeout)

    def restart_worker(self, i: int, timeout: float = 60.0) -> str:
        """Respawn worker ``i`` (same node id, fresh port) and wait until
        the coordinator has re-registered its announce."""
        import json as _json
        import time
        import urllib.request

        proc = self._popen(self._worker_args(i))
        uri = self._await_listening(proc)
        self._worker_procs[i] = proc
        self.worker_uris[i] = uri
        deadline = time.time() + timeout
        while time.time() < deadline:
            with urllib.request.urlopen(
                f"{self.coordinator_uri}/v1/node", timeout=10
            ) as r:
                info = _json.loads(r.read().decode())
            for n in info.get("nodes", []):
                if n.get("nodeId") == f"worker-{i}" and n.get("uri") == uri:
                    return uri
            time.sleep(0.2)
        raise TimeoutError(f"worker-{i} did not re-announce in time")

    def close(self) -> None:
        for p in self._procs:
            p.terminate()
        for p in self._procs:
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                p.kill()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
