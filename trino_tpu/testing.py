"""Test harness: LocalQueryRunner analog.

Reference: ``core/trino-main/src/main/java/io/trino/testing/LocalQueryRunner.java:221,631``
(single-process full stack) and the H2 oracle pattern
(``testing/trino-testing/.../H2QueryRunner.java``) — our oracle is NumPy
recomputation over the same generated data.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from trino_tpu.analyzer import Analyzer
from trino_tpu.columnar import Batch
from trino_tpu.config import Session
from trino_tpu.connectors.api import CatalogManager
from trino_tpu.connectors.blackhole import BlackHoleConnector
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.exec.local import LocalExecutor
from trino_tpu.planner import plan as P
from trino_tpu.sql import parse_statement
from trino_tpu.sql import tree as t


class LocalQueryRunner:
    """Parse -> analyze/plan -> execute, one process, no RPC."""

    def __init__(self, session: Optional[Session] = None):
        self.session = session or Session()
        self.catalogs = CatalogManager()
        self.catalogs.register("tpch", TpchConnector())
        self.catalogs.register("memory", MemoryConnector())
        self.catalogs.register("blackhole", BlackHoleConnector())

    def plan(self, sql: str) -> P.PlanNode:
        stmt = parse_statement(sql)
        analyzer = Analyzer(self.catalogs, self.session)
        plan = analyzer.plan_statement(stmt)
        from trino_tpu.planner.optimizer import optimize

        return optimize(plan, self.session, self.catalogs)

    def execute(self, sql: str) -> tuple[list[tuple], list[str]]:
        stmt = parse_statement(sql)
        if isinstance(stmt, t.SetSession):
            value = stmt.value
            v: Any = value.value if isinstance(value, t.Literal) else None
            self.session.set(stmt.name, v)
            return [], ["result"]
        plan = self._plan_stmt(stmt)
        executor = LocalExecutor(self.catalogs, self.session)
        batch, names = executor.execute(plan)
        return batch.to_pylist(), names

    def _plan_stmt(self, stmt) -> P.PlanNode:
        analyzer = Analyzer(self.catalogs, self.session)
        plan = analyzer.plan_statement(stmt)
        from trino_tpu.planner.optimizer import optimize

        return optimize(plan, self.session, self.catalogs)

    def explain(self, sql: str) -> str:
        return P.plan_text(self.plan(sql))

    def assert_query(self, sql: str, expected: Sequence[tuple], ordered: bool = False):
        rows, _ = self.execute(sql)
        got = rows if ordered else sorted(map(tuple, rows))
        want = list(expected) if ordered else sorted(map(tuple, expected))
        assert got == want, f"query mismatch:\n got: {got[:20]}\nwant: {want[:20]}"


class DistributedQueryRunner(LocalQueryRunner):
    """Multi-shard runner over a device mesh (reference:
    ``testing/trino-testing/.../DistributedQueryRunner.java:72`` — N real
    workers in one process; here N mesh shards in one process, with real
    collectives between them)."""

    def __init__(self, session: Optional[Session] = None, n_devices: Optional[int] = None):
        super().__init__(session)
        from trino_tpu.parallel.mesh import make_mesh

        self.mesh = make_mesh(n_devices)

    def execute(self, sql: str) -> tuple[list[tuple], list[str]]:
        stmt = parse_statement(sql)
        if isinstance(stmt, t.SetSession):
            return super().execute(sql)
        plan = self._plan_stmt(stmt)
        from trino_tpu.parallel.distributed import DistributedExecutor

        executor = DistributedExecutor(self.catalogs, self.session, self.mesh)
        batch, names = executor.execute(plan)
        return batch.to_pylist(), names
