"""Single-host execution of a logical plan (LocalQueryRunner tier).

Reference: ``core/trino-main/src/main/java/io/trino/testing/LocalQueryRunner.java:631``
— full parse->plan->execute in one process, no RPC. Each plan node is
evaluated to a device :class:`Batch` + symbol layout; expressions are bound
to channels and jit-evaluated. Materialized (operator-at-a-time) in v1 —
the distributed executor fuses per-fragment programs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import (
    Batch,
    Column,
    Dictionary,
    bucket_capacity,
    concat_batches,
    pad_batch,
)
from trino_tpu.compiler import ExprCompiler
from trino_tpu.config import Session
from trino_tpu.connectors.api import CatalogManager
from trino_tpu.ir import Call, Constant, InputRef, RowExpr, SpecialForm, Variable, bind_variables
from trino_tpu.ops import join as J
from trino_tpu.ops.aggregation import AggSpec, global_aggregate, group_aggregate
from trino_tpu.ops.sort import SortKey, sort_indices
from trino_tpu.planner import plan as P


class ExecutionError(Exception):
    pass


def rank_codes(dictionary, data):
    """Map dictionary codes to lexicographic ranks; safe on empty
    dictionaries (padding rows over empty tables have no real codes)."""
    if dictionary is None or len(dictionary) == 0:
        return jnp.zeros(data.shape, dtype=jnp.int64)
    r = jnp.asarray(dictionary.ranks())
    return r[jnp.maximum(data, 0)].astype(jnp.int64)


def sum_spec_for(fn: P.AggFunction, data) -> AggSpec:
    """Pick the accumulation kernel for a sum/avg: 128-bit limb
    accumulation when the declared result is a wide DECIMAL or the input
    already carries wide (hi, lo) storage (reference:
    DecimalSumAggregation over UnscaledDecimal128 state)."""
    from trino_tpu.ops.decimal128 import is_wide_data

    if fn.kind in ("sum", "avg"):
        if data is not None and is_wide_data(data):
            return AggSpec("sum128w")
        rt = fn.result_type
        if isinstance(rt, T.DecimalType) and rt.wide:
            return AggSpec("sum128")
    return AggSpec(fn.kind if fn.kind != "count_star" else "count_star")


@dataclasses.dataclass
class Result:
    """A materialized intermediate: batch + symbol layout."""

    batch: Batch
    layout: dict[str, int]  # symbol name -> channel

    def column(self, symbol: P.Symbol) -> Column:
        return self.batch.columns[self.layout[symbol.name]]

    def pair(self, symbol: P.Symbol):
        c = self.column(symbol)
        return c.data, c.valid_mask()

    def opt_pair(self, symbol: P.Symbol):
        """(data, valid-or-None): kernels skip null handling for None."""
        c = self.column(symbol)
        return c.data, c.valid


class LocalExecutor:
    def __init__(
        self,
        catalogs: CatalogManager,
        session: Session,
        memory_ctx=None,
    ):
        self.catalogs = catalogs
        self.session = session
        # collected dynamic-filter stats (DynamicFilterService analog)
        self.dynamic_filters: list = []
        # memory accounting (node -> query -> pool; see trino_tpu.memory)
        self.memory_ctx = memory_ctx
        self._reservations: dict[int, int] = {}
        # per-node execution stats for EXPLAIN ANALYZE (OperatorStats chain)
        self.stats_collector = None
        # per-query ingest accounting (split decode, coalesced H2D, table
        # cache; trino_tpu/ingest.py) — served via /v1/query as ingestStats
        self.ingest_stats: dict = {}
        # engine-owned DeviceTableCache (None outside the engine)
        self.table_cache = None

    def ingest_stats_snapshot(self) -> Optional[dict]:
        return dict(self.ingest_stats) if self.ingest_stats else None

    def _read_splits(self, connector, schema, table, columns, splits):
        """Decode splits through the ingest tier: double-buffered (a
        background thread decodes split k+1 while the caller consumes
        split k), honoring the ``native_decode`` session prop."""
        import contextlib

        from trino_tpu import native
        from trino_tpu.ingest import SplitPrefetcher

        ctx = (
            contextlib.nullcontext()
            if self.session.get("native_decode")
            else native.python_fallback()
        )
        with ctx:
            yield from SplitPrefetcher(
                lambda s: connector.read_split(schema, table, columns, s),
                splits,
                enabled=bool(self.session.get("ingest_prefetch")),
                stats=self.ingest_stats,
            )

    # === entry ==========================================================
    def execute(self, node: P.PlanNode) -> tuple[Batch, list[str]]:
        from trino_tpu.obs.trace import get_tracer

        with get_tracer().span(
            "execute_plan", attrs={"executor": type(self).__name__}
        ):
            if isinstance(node, P.Output):
                res = self._exec(node.source)
                cols = [res.column(s) for s in node.symbols]
                out = Batch(cols, res.batch.num_rows, res.batch.sel).compact()
                return out, node.column_names
            res = self._exec(node)
            return res.batch.compact(), [s.name for s in node.output_symbols]

    @staticmethod
    def _nonempty(res: Result) -> Result:
        """Kernels reject 0-capacity arrays; represent an empty relation as
        one unselected padding row."""
        if res.batch.capacity > 0:
            return res
        from trino_tpu.spill import pad_to_one_unselected

        return Result(pad_to_one_unselected(res.batch), res.layout)

    # === dispatch =======================================================
    def _exec(self, node: P.PlanNode) -> Result:
        method = getattr(self, f"_exec_{type(node).__name__.lower()}", None)
        if method is None:
            raise ExecutionError(f"no executor for {type(node).__name__}")
        if self.stats_collector is not None:
            import time as _time

            from trino_tpu.memory import batch_nbytes

            t0 = _time.perf_counter()
            res = method(node)
            rows = int(res.batch.count_rows())  # device sync: exact timing
            self.stats_collector.record(
                node, _time.perf_counter() - t0, rows, batch_nbytes(res.batch)
            )
        else:
            res = method(node)
        if self.memory_ctx is not None:
            from trino_tpu.memory import batch_nbytes

            nbytes = batch_nbytes(res.batch)
            self.memory_ctx.reserve(nbytes, what=type(node).__name__)
            self._reservations[id(node)] = nbytes
            # children's intermediates are dead once this node materialized
            for s in node.sources:
                self.memory_ctx.free(self._reservations.pop(id(s), 0))
        return res

    # === leaf nodes =====================================================
    def _exec_tablescan(self, node: P.TableScan) -> Result:
        connector = self.catalogs.get(node.catalog)
        splits = connector.get_splits_with_hints(
            node.schema, node.table, 64, node.constraint,
            limit=node.limit, topn=node.topn,
        )
        if not splits:
            return Result(self._empty_batch(node), {s.name: i for i, s in enumerate(node.symbols)})
        import time as _time

        from trino_tpu.obs.trace import get_tracer

        t0 = _time.perf_counter()
        batches = []
        rows_read = 0
        for b in self._read_splits(
            connector, node.schema, node.table, node.column_names, splits
        ):
            batches.append(b)
            rows_read += b.num_rows
            # connector applyLimit hint: stop pulling splits once the
            # pushed row budget is covered (the Limit node still enforces)
            if node.limit is not None and rows_read >= node.limit:
                break
        get_tracer().record(
            "ingest.decode",
            (_time.perf_counter() - t0) * 1000.0,
            attrs={"table": node.table, "splits": len(batches)},
        )
        batch = concat_batches(batches) if len(batches) > 1 else batches[0]
        layout = {s.name: i for i, s in enumerate(node.symbols)}
        return Result(batch, layout)

    def _empty_batch(self, node: P.TableScan) -> Batch:
        cols = [
            Column(
                s.type,
                np.zeros(0, dtype=s.type.storage_dtype),
                None,
                Dictionary([]) if T.is_string(s.type) else None,
            )
            for s in node.symbols
        ]
        return Batch(cols, 0)

    def _exec_values(self, node: P.Values) -> Result:
        n = len(node.rows)
        cols = []
        for j, sym in enumerate(node.symbols):
            t = sym.type
            vals = [row[j] for row in node.rows]
            valid = np.asarray([v is not None for v in vals], dtype=np.bool_)
            if T.is_string(t):
                d, codes = Dictionary.from_strings(
                    [v if v is not None else "" for v in vals]
                )
                codes = np.where(valid, codes, -1).astype(np.int32)
                cols.append(Column(t, codes, None if valid.all() else valid, d))
            else:
                data = np.asarray(
                    [v if v is not None else 0 for v in vals], dtype=t.storage_dtype
                )
                cols.append(Column(t, data, None if valid.all() else valid))
        return Result(
            Batch(cols, n), {s.name: i for i, s in enumerate(node.symbols)}
        )

    # === row-preserving nodes ==========================================
    def _exec_filter(self, node: P.Filter) -> Result:
        from trino_tpu.strings import lower_string_calls

        res = self._exec(node.source)
        expr = self._bind(node.predicate, res.layout)
        cols = list(res.batch.columns)
        from trino_tpu.datetimefmt import lower_datetime_format_calls

        expr = lower_datetime_format_calls(expr, cols)
        expr = lower_string_calls(expr, cols)
        mask = ExprCompiler(
            cols, params=getattr(self, "_params", None)
        ).predicate_mask(expr)
        sel = mask if res.batch.sel is None else (mask & res.batch.sel)
        return Result(
            Batch(res.batch.columns, res.batch.num_rows, sel), res.layout
        )

    def _exec_project(self, node: P.Project) -> Result:
        from trino_tpu.strings import lower_string_calls

        res = self._exec(node.source)
        work_cols = list(res.batch.columns)
        cols: list[Column] = []
        from trino_tpu.datetimefmt import lower_datetime_format_calls

        for sym, expr in node.assignments:
            bound = self._bind(expr, res.layout)
            bound = lower_datetime_format_calls(bound, work_cols)
            bound = lower_string_calls(bound, work_cols)
            ec = ExprCompiler(work_cols, params=getattr(self, "_params", None))
            if isinstance(bound, InputRef):
                cols.append(work_cols[bound.channel])
                continue
            if isinstance(sym.type, (T.ArrayType, T.MapType, T.RowType)):
                if isinstance(bound, Constant):
                    n = res.batch.capacity
                    if bound.value is None:
                        cols.append(
                            Column(
                                sym.type,
                                np.full(n, -1, dtype=np.int32),
                                np.zeros(n, dtype=np.bool_),
                                Dictionary([]),
                            )
                        )
                    else:
                        cols.append(
                            Column(
                                sym.type,
                                np.zeros(n, dtype=np.int32),
                                None,
                                Dictionary([bound.value]),
                            )
                        )
                    continue
                raise ExecutionError(
                    "computed ARRAY/MAP/ROW expressions are not supported yet"
                )
            if T.is_string(sym.type):
                if isinstance(bound, Constant):
                    n = res.batch.capacity
                    if bound.value is None:
                        cols.append(
                            Column(
                                sym.type,
                                np.full(n, -1, dtype=np.int32),
                                np.zeros(n, dtype=np.bool_),
                                Dictionary([]),
                            )
                        )
                    else:
                        cols.append(
                            Column(
                                sym.type,
                                np.zeros(n, dtype=np.int32),
                                None,
                                Dictionary([str(bound.value)]),
                            )
                        )
                    continue
                # general string-valued expression (CASE/COALESCE/...):
                # unify all referenced dictionaries + literals, evaluate
                # as codes in the unified dictionary
                new_cols, union = _unify_strings(bound, work_cols)
                ec2 = ExprCompiler(
                    new_cols,
                    string_dictionary=union,
                    params=getattr(self, "_params", None),
                )
                data, valid = ec2.evaluate(bound)
                cols.append(
                    Column(sym.type, data.astype(np.int32), valid, union)
                )
                continue
            data, valid = ec.evaluate(bound)
            data = data.astype(sym.type.storage_dtype)
            cols.append(Column(sym.type, data, valid))
        layout = {s.name: i for i, (s, _) in enumerate(node.assignments)}
        return Result(Batch(cols, res.batch.num_rows, res.batch.sel), layout)

    def _exec_unnest(self, node: P.Unnest) -> Result:
        """Expand array values into rows (UnnestOperator.java:39). A
        row-count-changing host boundary: arrays are pool tuples, so the
        expansion is np.repeat over row indices + typed element columns."""
        res = self._exec(node.source)
        b = res.batch.compact()
        n = b.num_rows
        per_expr: list[tuple[list, np.ndarray]] = []  # (pool tuples per row)
        for expr in node.array_exprs:
            bound = self._bind(expr, res.layout)
            if isinstance(bound, Constant):
                tuples = [
                    bound.value if bound.value is not None else () for _ in range(n)
                ]
            else:
                work = list(b.columns)
                ec = ExprCompiler(work)
                data, valid = ec.evaluate(bound)
                pool = None
                if isinstance(bound, InputRef):
                    pool = work[bound.channel].dictionary
                if pool is None:
                    raise ExecutionError("UNNEST argument has no array pool")
                data_np = np.asarray(data)
                valid_np = np.asarray(valid)
                tuples = [
                    pool.values[int(data_np[i])] if valid_np[i] else ()
                    for i in range(n)
                ]
            per_expr.append(tuples)
        lengths = np.asarray(
            [
                max((len(tuples[i]) for tuples in per_expr), default=0)
                for i in range(n)
            ],
            dtype=np.int64,
        )
        row_idx = np.repeat(np.arange(n), lengths)
        cols: list[Column] = []
        layout: dict[str, int] = {}
        for s in node.source.output_symbols:
            c = b.columns[res.layout[s.name]]
            data, valid = c.to_numpy()
            cols.append(
                Column(
                    c.type,
                    data[row_idx],
                    None if valid[row_idx].all() else valid[row_idx],
                    c.dictionary,
                )
            )
            layout[s.name] = len(cols) - 1
        for sym, tuples in zip(node.element_symbols, per_expr):
            vals: list = []
            for i in range(n):
                t_ = tuples[i]
                ln = int(lengths[i])
                for j in range(ln):
                    v = t_[j] if j < len(t_) else None
                    if v is not None and isinstance(sym.type, T.DecimalType):
                        # pool holds storage ints; from_values wants logical
                        from decimal import Decimal as _D

                        v = _D(int(v)) / sym.type.unscale
                    elif v is not None and isinstance(sym.type, T.DateType):
                        v = int(v)
                    vals.append(v)
            cols.append(Column.from_values(sym.type, vals))
            layout[sym.name] = len(cols) - 1
        if node.ordinality is not None:
            ords = np.concatenate(
                [np.arange(1, ln + 1, dtype=np.int64) for ln in lengths]
            ) if len(lengths) else np.zeros(0, dtype=np.int64)
            cols.append(Column(T.BIGINT, ords))
            layout[node.ordinality.name] = len(cols) - 1
        return Result(Batch(cols, int(lengths.sum())), layout)

    def _exec_limit(self, node: P.Limit) -> Result:
        res = self._exec(node.source)
        b = res.batch.compact()
        lo = min(node.offset, b.num_rows)
        hi = b.num_rows if node.count is None else min(b.num_rows, lo + node.count)
        cols = []
        for c in b.columns:
            data, valid = c.to_numpy()
            cols.append(
                Column(c.type, data[lo:hi], None if valid[lo:hi].all() else valid[lo:hi], c.dictionary)
            )
        return Result(Batch(cols, hi - lo), res.layout)

    # === sorting ========================================================
    def _sorted_result(self, res: Result, order_by: Sequence[P.Ordering], keep: Optional[int]) -> Result:
        b = res.batch
        key_pairs = []
        keys = []
        ranks = []
        for o in order_by:
            c = res.column(o.symbol)
            key_pairs.append((c.data, c.valid_mask()))
            keys.append(o.sort_key())
            ranks.append(c.dictionary.ranks() if c.dictionary is not None else None)
        sel = b.selection_mask()
        perm = sort_indices(key_pairs, keys, sel, ranks)
        n_valid = int(np.asarray(sel).sum())
        take = n_valid if keep is None else min(keep, n_valid)
        perm_np = np.asarray(perm)[:take]
        cols = []
        for c in b.columns:
            data, valid = c.to_numpy()
            cols.append(
                Column(
                    c.type,
                    data[perm_np],
                    None if valid[perm_np].all() else valid[perm_np],
                    c.dictionary,
                )
            )
        return Result(Batch(cols, take), res.layout)

    def _exec_sort(self, node: P.Sort) -> Result:
        res = self._exec(node.source)
        if self._should_spill_sort(res, node.order_by):
            return self._spill_sort(res, node.order_by, None)
        return self._sorted_result(res, node.order_by, None)

    def _exec_topn(self, node: P.TopN) -> Result:
        res = self._exec(node.source)
        if self._should_spill_sort(res, node.order_by):
            return self._spill_sort(res, node.order_by, node.count)
        return self._sorted_result(res, node.order_by, node.count)

    def _should_spill_sort(self, res: Result, order_by) -> bool:
        if not self.session.get("spill_enabled") or not order_by:
            return False
        if res.batch.capacity <= int(self.session.get("spill_threshold_rows")):
            return False
        first = res.column(order_by[0].symbol)
        # wide-decimal (two-lane) leading keys have no scalar range domain
        return getattr(first.data, "ndim", 1) == 1

    def _spill_sort(self, res: Result, order_by, keep: Optional[int]) -> Result:
        """Bounded-HBM external sort: range-partition by a sampled leading
        key, device-sort each partition, concatenate in range order.

        Reference: ``OrderByOperator``/``TopNOperator`` memory revocation
        (``spiller/FileSingleStreamSpiller.java:55``) — the reference
        spills sorted runs and merge-reads them; the TPU-shaped analog is
        a sample sort, which needs no merge pass because ranges are
        disjoint (rows with EQUAL leading keys land in one partition, so
        secondary keys still order correctly within it)."""
        from trino_tpu.spill import slice_rows

        b = res.batch
        o0 = order_by[0]
        c0 = res.column(o0.symbol)
        data, valid = c0.to_numpy()
        if c0.dictionary is not None:
            ranks = np.asarray(c0.dictionary.ranks())
            data = ranks[np.clip(data, 0, max(len(ranks) - 1, 0))]
        sel = np.asarray(b.selection_mask())
        n_part = max(2, int(self.session.get("spill_partitions")))
        live = sel & valid
        vals = data[live]
        if vals.size == 0:
            return self._sorted_result(res, order_by, keep)
        sample = np.sort(vals[:: max(1, vals.size // 65536)])
        bounds = sample[
            np.linspace(0, sample.size - 1, n_part + 1)[1:-1].astype(np.int64)
        ]
        part = np.searchsorted(np.unique(bounds), data, side="right")
        n_ranges = int(part.max(initial=0)) + 1
        null_rows = np.nonzero(sel & ~valid)[0]
        # bucket visit order = final output order: NULL bucket at the end
        # the ordering spec puts it, value ranges ascending or descending
        range_order = list(
            range(n_ranges) if o0.ascending else range(n_ranges - 1, -1, -1)
        )
        buckets: list = (
            ["null", *range_order] if o0.nulls_first else [*range_order, "null"]
        )
        batches: list[Batch] = []
        total = 0
        for bk in buckets:
            rows = (
                null_rows if bk == "null" else np.nonzero(live & (part == bk))[0]
            )
            if rows.size == 0:
                continue
            sub = Result(slice_rows(b, rows), dict(res.layout))
            piece = self._sorted_result(sub, order_by, keep).batch
            batches.append(piece)
            total += piece.num_rows
            if keep is not None and total >= keep:
                break
        out = concat_batches(batches) if len(batches) > 1 else batches[0]
        if keep is not None and out.num_rows > keep:
            out = slice_rows(out, np.arange(keep))
        return Result(out, dict(res.layout))

    # === aggregation ====================================================
    def _exec_aggregate(self, node: P.Aggregate) -> Result:
        if node.step == "partial" and node.acc_symbols is not None:
            return self._aggregate_partial(node, self._exec(node.source))
        if node.step == "final" and node.acc_symbols is not None:
            return self._aggregate_final(node, self._exec(node.source))
        return self._aggregate_result(node, self._exec(node.source))

    def _aggregate_partial(self, node: P.Aggregate, res: Result) -> Result:
        """PARTIAL step: emit accumulator columns (value, count) per agg —
        the wire representation between fragments (reference:
        AccumulatorStateSerializer). String min/max values travel as
        lexicographic ranks with the dictionary attached to the column."""
        res = self._nonempty(res)
        sel = res.batch.selection_mask()
        agg_inputs, specs, string_aggs = self._prepare_partial_inputs(node, res)
        key_dicts = [res.column(k).dictionary for k in node.group_keys]
        if not node.group_keys:
            raw = global_aggregate(sel, agg_inputs, specs)
            cols, layout = self._acc_columns(node, raw, 1, string_aggs)
            return Result(Batch(cols, 1), layout)
        keys = [res.pair(k) for k in node.group_keys]
        max_groups = 1 << 12
        while True:
            (kd, kv), raw, ng, overflow = group_aggregate(
                keys, sel, agg_inputs, specs, max_groups
            )
            if not bool(overflow):
                break
            max_groups <<= 2
            if max_groups > (1 << 26):
                raise ExecutionError("group-by cardinality too large")
        ng = int(ng)
        cols: list[Column] = []
        layout: dict[str, int] = {}
        for i, k in enumerate(node.group_keys):
            valid = np.asarray(kv[i])[:ng]
            cols.append(
                Column(
                    k.type,
                    np.asarray(kd[i])[:ng].astype(k.type.storage_dtype),
                    None if valid.all() else valid,
                    key_dicts[i],
                )
            )
            layout[k.name] = len(cols) - 1
        acc_cols, acc_layout = self._acc_columns(node, raw, ng, string_aggs)
        for name, i in acc_layout.items():
            layout[name] = len(cols) + i
        cols.extend(acc_cols)
        return Result(Batch(cols, ng), layout)

    def _prepare_partial_inputs(self, node: P.Aggregate, res: Result):
        """Like the single-step input prep but without DISTINCT handling
        (the fragmenter never splits DISTINCT aggregates)."""
        agg_inputs, specs, string_aggs = [], [], []
        for _, fn in node.aggregates:
            if fn.kind == "count_star":
                if fn.filter is not None:
                    fc = res.column(P.Symbol(fn.filter.name, T.BOOLEAN))
                    ones = jnp.ones(res.batch.capacity, dtype=jnp.int64)
                    agg_inputs.append((ones, fc.data & fc.valid_mask()))
                    specs.append(AggSpec("count"))
                    string_aggs.append(None)
                    continue
                agg_inputs.append(None)
                specs.append(AggSpec("count_star"))
                string_aggs.append(None)
                continue
            sym = P.Symbol(fn.argument.name, fn.argument.type)
            c = res.column(sym)
            data, valid = c.data, c.valid_mask()
            if c.dictionary is not None and fn.kind in ("min", "max"):
                data = rank_codes(c.dictionary, data)
                string_aggs.append(c.dictionary)
            else:
                string_aggs.append(None)
            if fn.filter is not None:
                fc = res.column(P.Symbol(fn.filter.name, T.BOOLEAN))
                valid = valid & fc.data & fc.valid_mask()
            agg_inputs.append((data, valid))
            specs.append(sum_spec_for(fn, data))
        return agg_inputs, specs, string_aggs

    def _acc_columns(self, node: P.Aggregate, raw, n, string_aggs):
        cols: list[Column] = []
        layout: dict[str, int] = {}
        for (vsym, csym), (_, fn), r, sdict in zip(
            node.acc_symbols, node.aggregates, raw, string_aggs
        ):
            if fn.kind in ("count", "count_star"):
                data = np.asarray(r).reshape(-1)[:n].astype(np.int64)
                cols.append(Column(T.BIGINT, data))
                layout[vsym.name] = len(cols) - 1
                continue
            val, cnt = r
            val_arr = np.asarray(val)
            cnt = np.asarray(cnt).reshape(-1)[:n].astype(np.int64)
            if val_arr.ndim == 2 and val_arr.shape[1] in (3, 5):
                # limb accumulator -> wide (hi, lo) acc column on the wire
                from trino_tpu.ops import decimal128 as D128

                if val_arr.shape[1] == 3:
                    ints = D128.narrow_sums_to_ints(val_arr[:n])
                else:
                    ints = D128.wide_sums_to_ints(val_arr[:n])
                cols.append(Column(vsym.type, D128.wide_from_ints(ints), None))
                layout[vsym.name] = len(cols) - 1
                cols.append(Column(T.BIGINT, cnt))
                layout[csym.name] = len(cols) - 1
                continue
            if val_arr.ndim == 2 and val_arr.shape[1] == 2:
                # wide min/max extrema: already (hi, lo)
                cols.append(Column(vsym.type, val_arr[:n], None))
                layout[vsym.name] = len(cols) - 1
                cols.append(Column(T.BIGINT, cnt))
                layout[csym.name] = len(cols) - 1
                continue
            val = val_arr.reshape(-1)[:n]
            if sdict is not None:
                # string min/max computed over local ranks — convert the
                # winning rank back to a CODE for the wire: ranks are only
                # meaningful against this node's dictionary, codes travel
                # with it (page serde / concat merge remap codes correctly)
                order = np.argsort(sdict.ranks(), kind="stable")
                if len(order):
                    val = order[np.clip(val, 0, len(order) - 1)].astype(np.int32)
                else:
                    val = np.full(val.shape, -1, dtype=np.int32)
                val = np.where(cnt > 0, val, -1).astype(np.int32)
                cols.append(Column(vsym.type, val, cnt > 0, sdict))
            else:
                cols.append(Column(vsym.type, val, None, None))
            layout[vsym.name] = len(cols) - 1
            cols.append(Column(T.BIGINT, cnt))
            layout[csym.name] = len(cols) - 1
        return cols, layout

    def _aggregate_final(self, node: P.Aggregate, res: Result) -> Result:
        """FINAL step: combine accumulator rows shipped from partials."""
        res = self._nonempty(res)
        sel = res.batch.selection_mask()
        combine_inputs: list = []
        combine_specs: list[AggSpec] = []
        dicts = []
        for (vsym, csym), (_, fn) in zip(node.acc_symbols, node.aggregates):
            vcol = res.column(vsym)
            dicts.append(vcol.dictionary)
            if fn.kind in ("count", "count_star"):
                combine_inputs.append((vcol.data, vcol.valid_mask()))
                combine_specs.append(AggSpec("sum"))
            else:
                ccol = res.column(csym)
                nonempty = ccol.data > 0
                vdata = vcol.data
                if vcol.dictionary is not None and fn.kind in ("min", "max"):
                    # codes -> ranks against the (possibly merged) local
                    # dictionary before order-based combining
                    vdata = rank_codes(vcol.dictionary, vdata)
                    nonempty = nonempty & (vcol.data >= 0)
                combine_inputs.append((vdata, nonempty))
                if fn.kind in ("sum", "avg"):
                    from trino_tpu.ops.decimal128 import is_wide_data

                    combine_specs.append(
                        AggSpec("sum128w" if is_wide_data(vdata) else "sum")
                    )
                else:
                    combine_specs.append(AggSpec(fn.kind))
                combine_inputs.append((ccol.data, ccol.valid_mask()))
                combine_specs.append(AggSpec("sum"))

        def fold(raw):
            out = []
            j = 0
            for _, fn in node.aggregates:
                if fn.kind in ("count", "count_star"):
                    v = raw[j]
                    out.append(v[0] if isinstance(v, tuple) else v)
                    j += 1
                else:
                    v, c = raw[j], raw[j + 1]
                    out.append(
                        (
                            v[0] if isinstance(v, tuple) else v,
                            c[0] if isinstance(c, tuple) else c,
                        )
                    )
                    j += 2
            return out

        if not node.group_keys:
            raw = fold(global_aggregate(sel, combine_inputs, combine_specs))
            cols = self._finalize_aggs(node, raw, 1, None, dicts)
            return Result(
                Batch(cols, 1),
                {s.name: i for i, s in enumerate(node.output_symbols)},
            )
        keys = [res.pair(k) for k in node.group_keys]
        key_dicts = [res.column(k).dictionary for k in node.group_keys]
        max_groups = 1 << 12
        while True:
            (kd, kv), raw, ng, overflow = group_aggregate(
                keys, sel, combine_inputs, combine_specs, max_groups
            )
            if not bool(overflow):
                break
            max_groups <<= 2
            if max_groups > (1 << 26):
                raise ExecutionError("group-by cardinality too large")
        ng = int(ng)
        cols = []
        for i, k in enumerate(node.group_keys):
            valid = np.asarray(kv[i])[:ng]
            cols.append(
                Column(
                    k.type,
                    np.asarray(kd[i])[:ng].astype(k.type.storage_dtype),
                    None if valid.all() else valid,
                    key_dicts[i],
                )
            )
        cols.extend(self._finalize_aggs(node, fold(raw), ng, None, dicts))
        return Result(
            Batch(cols, ng), {s.name: i for i, s in enumerate(node.output_symbols)}
        )

    def _aggregate_with_array_agg(self, node: P.Aggregate, res: Result) -> Result:
        """array_agg collects values into pool-coded arrays host-side
        (groups are small relative to rows; the per-row work stayed on
        device in the feeding operators). Other aggregates in the same
        GROUP BY run through the normal kernels and are stitched back."""
        others = [
            (s, fn) for s, fn in node.aggregates if fn.kind != "array_agg"
        ]
        base = P.Aggregate(node.source, node.group_keys, others, node.step)
        out = self._aggregate_result(base, res)
        ng = out.batch.num_rows

        # host view of the input rows
        sel = np.asarray(res.batch.selection_mask())
        key_vals = []
        for k in node.group_keys:
            c = res.column(k)
            d, v = c.to_numpy()
            key_vals.append((d, v))

        def key_of(i):
            return tuple(
                (int(d[i]), bool(v[i])) for d, v in key_vals
            )

        # group membership in output order
        out_keys = {}
        for gi in range(ng):
            parts = []
            for k in node.group_keys:
                c = out.batch.columns[out.layout[k.name]]
                d, v = c.to_numpy()
                parts.append((int(d[gi]), bool(v[gi])))
            out_keys[tuple(parts)] = gi

        from trino_tpu.columnar import Dictionary as _Dict

        cols = list(out.batch.columns)
        layout = dict(out.layout)
        for sym, fn in node.aggregates:
            if fn.kind != "array_agg":
                continue
            c = res.column(P.Symbol(fn.argument.name, fn.argument.type))
            d, v = c.to_numpy()
            fmask = np.ones(len(d), dtype=bool)
            if fn.filter is not None:
                fc = res.column(P.Symbol(fn.filter.name, T.BOOLEAN))
                fd, fv = fc.to_numpy()
                fmask = fd & fv
            groups: dict = {k: [] for k in out_keys}
            dvals = d.tolist()  # python scalars in one pass, not per-row
            for i in np.nonzero(sel & fmask)[0]:
                k = key_of(i)
                if k not in groups:
                    continue
                if not v[i]:
                    groups[k].append(None)  # array_agg keeps NULLs
                elif c.dictionary is not None:
                    groups[k].append(c.dictionary.decode(int(d[i])))
                else:
                    groups[k].append(dvals[i])
            tuples: list = [()] * max(ng, 1)
            valid_out = np.zeros(max(ng, 1), dtype=bool)
            for k, gi in out_keys.items():
                vals = groups.get(k, [])
                tuples[gi] = tuple(vals)
                valid_out[gi] = bool(vals)
            if not node.group_keys:
                # global: exactly one row; empty input -> NULL array
                vals = groups.get((), [])
                tuples = [tuple(vals)]
                valid_out = np.asarray([bool(vals)])
            pool_index: dict = {}
            pool_vals: list = []
            codes = np.empty(len(tuples), dtype=np.int32)
            for gi, t_ in enumerate(tuples):
                code = pool_index.get(t_)
                if code is None:
                    code = len(pool_vals)
                    pool_index[t_] = code
                    pool_vals.append(t_)
                codes[gi] = code
            codes = np.where(valid_out, codes, -1).astype(np.int32)
            pool = _Dict(pool_vals)
            cols.append(
                Column(
                    sym.type, codes,
                    None if valid_out.all() else valid_out, pool,
                )
            )
            layout[sym.name] = len(cols) - 1
        # reorder to the node's declared output order
        ordered = []
        final_layout = {}
        for s in node.output_symbols:
            ordered.append(cols[layout[s.name]])
            final_layout[s.name] = len(ordered) - 1
        return Result(Batch(ordered, out.batch.num_rows), final_layout)

    def _spill_aggregate(self, node: P.Aggregate, res: Result) -> Result:
        """Partitioned (spill-to-host) group-by: rows hash-partitioned by
        group keys; each partition aggregated on device independently
        (disjoint key sets -> plain concat, no re-merge). Reference:
        HashAggregationOperator revocable-state spill."""
        from trino_tpu.spill import partitioned_run

        n_part = int(self.session.get("spill_partitions"))
        keys = [res.pair(k) for k in node.group_keys]
        kh, _ = J.hash_keys(keys)

        def run(subs, p):
            if subs[0].num_rows == 0:
                return None
            sub = Result(subs[0], dict(res.layout))
            out = self._aggregate_result(node, sub, allow_spill=False)
            return out.batch.compact()

        parts = partitioned_run([(res.batch, np.asarray(kh))], n_part, run)
        layout = {s.name: i for i, s in enumerate(node.output_symbols)}
        if not parts:
            cols = [
                Column(
                    s.type,
                    np.zeros(0, dtype=s.type.storage_dtype),
                    None,
                    res.column(s).dictionary
                    if s.name in res.layout and T.is_string(s.type)
                    else (Dictionary([]) if T.is_string(s.type) else None),
                )
                for s in node.output_symbols
            ]
            return Result(Batch(cols, 0), layout)
        merged = concat_batches(parts) if len(parts) > 1 else parts[0]
        return Result(merged, layout)

    def _aggregate_result(
        self, node: P.Aggregate, res: Result, allow_spill: bool = True
    ) -> Result:
        if any(fn.kind == "array_agg" for _, fn in node.aggregates):
            return self._aggregate_with_array_agg(node, res)
        res = self._nonempty(res)
        if (
            allow_spill
            and node.group_keys
            and self.session.get("spill_enabled")
            and int(res.batch.count_rows())
            > int(self.session.get("spill_threshold_rows"))
        ):
            return self._spill_aggregate(node, res)
        sel = res.batch.selection_mask()
        key_pairs_for_distinct = [res.pair(k) for k in node.group_keys]
        agg_inputs = []
        specs = []
        string_aggs: list[Optional[Dictionary]] = []
        for _, fn in node.aggregates:
            if fn.kind == "count_star":
                if fn.filter is not None:
                    # count(*) FILTER (WHERE f) == count over the f mask
                    fsym = P.Symbol(fn.filter.name, T.BOOLEAN)
                    fc = res.column(fsym)
                    ones = jnp.ones(res.batch.capacity, dtype=jnp.int64)
                    pair = (ones, fc.data & fc.valid_mask())
                    string_aggs.append(None)
                    agg_inputs.append(pair)
                    specs.append(AggSpec("count"))
                    continue
                pair = None
                string_aggs.append(None)
            else:
                assert isinstance(fn.argument, Variable)
                sym = P.Symbol(fn.argument.name, fn.argument.type)
                c = res.column(sym)
                data, valid = c.data, c.valid_mask()
                if c.dictionary is not None and fn.kind in ("min", "max"):
                    # strings: min/max over lexicographic ranks, map back after
                    data = rank_codes(c.dictionary, data)
                    string_aggs.append(c.dictionary)
                else:
                    string_aggs.append(None)
                if fn.filter is not None:
                    fsym = P.Symbol(fn.filter.name, T.BOOLEAN)
                    fc = res.column(fsym)
                    valid = valid & fc.data & fc.valid_mask()
                if fn.distinct and fn.kind in ("count", "sum", "avg"):
                    # DISTINCT: keep only the first occurrence of each
                    # (group keys, value) combination
                    from trino_tpu.ops.aggregation import distinct_first_mask

                    first = distinct_first_mask(
                        key_pairs_for_distinct, (data, valid), sel & valid
                    )
                    valid = valid & first
                pair = (data, valid)
            agg_inputs.append(pair)
            specs.append(sum_spec_for(fn, pair[0] if pair else None))

        if not node.group_keys:
            results = global_aggregate(sel, agg_inputs, specs)
            cols = self._finalize_aggs(node, results, 1, None, string_aggs)
            return Result(
                Batch(cols, 1),
                {s.name: i for i, s in enumerate(node.output_symbols)},
            )

        keys = [res.pair(k) for k in node.group_keys]
        key_dicts = [res.column(k).dictionary for k in node.group_keys]
        max_groups = 1 << 12
        while True:
            (kd, kv), results, ng, overflow = group_aggregate(
                keys, sel, agg_inputs, specs, max_groups
            )
            if not bool(overflow):
                break
            max_groups <<= 2
            if max_groups > (1 << 26):
                raise ExecutionError("group-by cardinality too large")
        ng = int(ng)
        cols = []
        for i, k in enumerate(node.group_keys):
            valid = np.asarray(kv[i])[:ng]
            cols.append(
                Column(
                    k.type,
                    np.asarray(kd[i])[:ng].astype(k.type.storage_dtype),
                    None if valid.all() else valid,
                    key_dicts[i],
                )
            )
        cols.extend(self._finalize_aggs(node, results, ng, None, string_aggs))
        return Result(
            Batch(cols, ng), {s.name: i for i, s in enumerate(node.output_symbols)}
        )

    def _finalize_aggs(self, node, results, n, _unused, string_aggs) -> list[Column]:
        cols = []
        for (sym, fn), raw, sdict in zip(node.aggregates, results, string_aggs):
            t = fn.result_type
            if fn.kind in ("count", "count_star"):
                data = np.asarray(raw).reshape(-1)[:n].astype(np.int64)
                cols.append(Column(t, data))
                continue
            ssum, cnt = raw
            cnt_np = np.asarray(cnt).reshape(-1)[:n]
            valid = cnt_np > 0
            ssum_arr = np.asarray(ssum)
            if ssum_arr.ndim == 2 and ssum_arr.shape[1] in (3, 5):
                # 128-bit limb accumulation: exact host reconstruction
                from trino_tpu.ops import decimal128 as D128

                if ssum_arr.shape[1] == 3:
                    ints = D128.narrow_sums_to_ints(ssum_arr[:n])
                else:
                    ints = D128.wide_sums_to_ints(ssum_arr[:n])
                if fn.kind == "avg":
                    vals = []
                    for s_i, c_i in zip(ints, cnt_np):
                        c_i = max(int(c_i), 1)
                        q, r = divmod(abs(s_i), c_i)
                        q = q + (1 if 2 * r >= c_i else 0)
                        vals.append(q if s_i >= 0 else -q)
                    ints = vals
                wide_t = isinstance(t, T.DecimalType) and t.wide
                if wide_t:
                    data = D128.wide_from_ints(ints)
                else:
                    data = np.asarray(ints, dtype=np.int64)
                cols.append(Column(t, data, None if valid.all() else valid))
                continue
            if fn.kind == "sum":
                data = np.asarray(ssum).reshape(-1)[:n].astype(t.storage_dtype)
                cols.append(Column(t, data, None if valid.all() else valid))
            elif fn.kind == "avg":
                s_np = np.asarray(ssum).reshape(-1)[:n]
                safe = np.maximum(cnt_np, 1)
                if isinstance(t, T.DecimalType):
                    # round half up at result scale
                    data = np.where(
                        s_np >= 0,
                        (s_np + safe // 2) // safe,
                        -((-s_np + safe // 2) // safe),
                    ).astype(np.int64)
                else:
                    data = (s_np / safe).astype(t.storage_dtype)
                cols.append(Column(t, data, None if valid.all() else valid))
            else:  # min / max
                ssum_mm = np.asarray(ssum)
                if ssum_mm.ndim == 2 and ssum_mm.shape[1] == 2:
                    # wide (hi, lo) extrema pass through as wide storage
                    cols.append(
                        Column(t, ssum_mm[:n], None if valid.all() else valid)
                    )
                    continue
                data = ssum_mm.reshape(-1)[:n]
                if sdict is not None:
                    # map ranks back to codes
                    order = np.argsort(sdict.ranks(), kind="stable")
                    if len(order):
                        data = order[np.clip(data, 0, len(order) - 1)].astype(np.int32)
                    else:
                        data = np.full(data.shape, -1, dtype=np.int32)
                    cols.append(
                        Column(t, data, None if valid.all() else valid, sdict)
                    )
                else:
                    cols.append(
                        Column(
                            t,
                            data.astype(t.storage_dtype),
                            None if valid.all() else valid,
                        )
                    )
        return cols

    # === window functions ==============================================
    def _exec_window(self, node: P.Window) -> Result:
        res = self._exec(node.source)
        if (
            self.session.get("spill_enabled")
            and node.partition_by
            and res.batch.capacity
            > int(self.session.get("spill_threshold_rows"))
        ):
            return self._spill_window(node, res)
        return self._window_result(node, res)

    def _spill_window(self, node: P.Window, res: Result) -> Result:
        """Partitioned (spill-to-host) windows: rows hash-partitioned by
        the PARTITION BY keys — window frames never cross partition-key
        boundaries, so per-spill-partition computation is exact; results
        scatter back to the original row positions. Reference:
        WindowOperator memory revocation (the 4th revocable operator)."""
        from trino_tpu.spill import partition_assignment, slice_rows

        b = res.batch
        n_part = int(self.session.get("spill_partitions"))
        keys = [res.pair(s) for s in node.partition_by]
        kh, _ = J.hash_keys(keys)
        sel = np.asarray(b.selection_mask())
        assign = partition_assignment(np.asarray(kh), sel, n_part)
        n_fns = len(node.functions)
        out_data = [None] * n_fns
        out_valid = [np.zeros(b.capacity, dtype=np.bool_) for _ in range(n_fns)]
        out_cols_proto: list[Optional[Column]] = [None] * n_fns
        for p in range(n_part):
            rows = np.nonzero(assign == p)[0]
            if rows.size == 0:
                continue
            sub = Result(slice_rows(b, rows), dict(res.layout))
            sub_out = self._window_result(node, sub)
            base_width = len(b.columns)
            for j in range(n_fns):
                col = sub_out.batch.columns[base_width + j]
                data, valid = col.to_numpy()
                if data.ndim != 1:
                    # 2-D (wide DECIMAL) outputs can't scatter into the
                    # 1-D merge buffer: recompute without spilling
                    return self._window_result(node, res)
                if out_data[j] is None:
                    out_data[j] = np.zeros(b.capacity, dtype=data.dtype)
                    out_cols_proto[j] = col
                elif (
                    col.dictionary is not out_cols_proto[j].dictionary
                    or data.dtype != out_data[j].dtype
                ):
                    # a partition-local dictionary (or dtype drift) would
                    # decode wrong strings through the shared buffer:
                    # fall back to the unspilled path
                    return self._window_result(node, res)
                out_data[j][rows] = data
                out_valid[j][rows] = valid
        cols = list(b.columns)
        layout = dict(res.layout)
        for j, (sym, _wf) in enumerate(node.functions):
            proto = out_cols_proto[j]
            if proto is None:  # no selected rows at all
                data = np.zeros(b.capacity, dtype=sym.type.storage_dtype)
                cols.append(Column(sym.type, data, out_valid[j]))
            else:
                cols.append(
                    Column(sym.type, out_data[j], out_valid[j], proto.dictionary)
                )
            layout[sym.name] = len(cols) - 1
        return Result(Batch(cols, b.num_rows, b.sel), layout)

    def _window_result(self, node: P.Window, res: Result) -> Result:
        from trino_tpu.ops.window import WindowFn, WindowSpecKernel, compute_windows

        b = res.batch
        sel = b.selection_mask()

        part_pairs, part_ranks = [], []
        for s in node.partition_by:
            c = res.column(s)
            part_pairs.append((c.data, c.valid_mask()))
            part_ranks.append(c.dictionary.ranks() if c.dictionary else None)
        order_pairs, order_specs, order_ranks = [], [], []
        for o in node.order_by:
            c = res.column(o.symbol)
            order_pairs.append((c.data, c.valid_mask()))
            order_specs.append(o.sort_key())
            order_ranks.append(c.dictionary.ranks() if c.dictionary else None)

        # frame selection (SQL defaults; ranking fns ignore it)
        preceding = 0
        if not node.order_by:
            kframe = "partition"
        elif node.frame is None:
            kframe = "running_range"
        else:
            ftype, fstart, fend = node.frame
            if fend == "UNBOUNDED FOLLOWING":
                kframe = "partition"
            elif ftype == "ROWS" and fstart.endswith(" PRECEDING") and fstart.split()[0].isdigit():
                kframe = "rows_preceding"
                preceding = int(fstart.split()[0])
            elif ftype == "ROWS":
                kframe = "running_rows"
            else:
                kframe = "running_range"

        fns, args, defaults = [], [], []
        out_dicts: list[Optional[Dictionary]] = []
        minmax_dicts: list[Optional[Dictionary]] = []
        for _, wf in node.functions:
            fns.append(WindowFn(wf.kind, wf.offset, wf.default is not None))
            out_dict = None
            mm_dict = None
            if wf.argument is None:
                args.append(None)
                defaults.append(None)
            else:
                sym = P.Symbol(wf.argument.name, wf.argument.type)
                c = res.column(sym)
                data, valid = c.data, c.valid_mask()
                if getattr(data, "ndim", 1) == 2:
                    # window kernels run in int64 lanes; narrow at runtime
                    # (errors if wide values genuinely exceed 18 digits)
                    from trino_tpu.compiler import _narrow_checked

                    data = _narrow_checked(data, "window over DECIMAL(38)")
                if c.dictionary is not None and wf.kind in ("min", "max"):
                    data = rank_codes(c.dictionary, data)
                    mm_dict = c.dictionary
                elif c.dictionary is not None:
                    out_dict = c.dictionary
                args.append((data, valid))
                d = None
                if wf.default is not None:
                    n = b.capacity
                    if isinstance(wf.default, Constant):
                        if wf.default.value is None:
                            d = (
                                jnp.zeros(n, dtype=data.dtype),
                                jnp.zeros(n, dtype=jnp.bool_),
                            )
                        elif out_dict is not None:
                            code = out_dict.encode(str(wf.default.value))
                            if code < 0:
                                out_dict = Dictionary(
                                    out_dict.values + [str(wf.default.value)]
                                )
                                code = len(out_dict.values) - 1
                            d = (
                                jnp.full(n, code, dtype=data.dtype),
                                jnp.ones(n, dtype=jnp.bool_),
                            )
                        else:
                            d = (
                                jnp.full(n, wf.default.value, dtype=data.dtype),
                                jnp.ones(n, dtype=jnp.bool_),
                            )
                    else:
                        dsym = P.Symbol(wf.default.name, wf.default.type)
                        dc = res.column(dsym)
                        d = (dc.data, dc.valid_mask())
                defaults.append(d)
            out_dicts.append(out_dict)
            minmax_dicts.append(mm_dict)

        results = compute_windows(
            part_pairs, part_ranks, order_pairs, order_specs, order_ranks,
            sel, fns, args, defaults, WindowSpecKernel(kframe, preceding),
        )

        cols = list(b.columns)
        layout = dict(res.layout)
        for (sym, wf), (data, valid), odict, mmdict in zip(
            node.functions, results, out_dicts, minmax_dicts
        ):
            valid_np = np.asarray(valid)
            if mmdict is not None:
                # min/max over strings: ranks back to codes
                order = np.argsort(mmdict.ranks(), kind="stable")
                data = order[np.clip(np.asarray(data), 0, len(order) - 1)].astype(
                    np.int32
                )
                col = Column(sym.type, data, valid_np, mmdict)
            elif odict is not None:
                col = Column(
                    sym.type, np.asarray(data).astype(np.int32), valid_np, odict
                )
            else:
                col = Column(
                    sym.type,
                    np.asarray(data).astype(sym.type.storage_dtype),
                    None if valid_np.all() else valid_np,
                )
            cols.append(col)
            layout[sym.name] = len(cols) - 1
        return Result(Batch(cols, b.num_rows, b.sel), layout)

    def _exec_distinct(self, node: P.Distinct) -> Result:
        res = self._exec(node.source)
        syms = node.output_symbols
        keys = [res.pair(s) for s in syms]
        dicts = [res.column(s).dictionary for s in syms]
        sel = res.batch.selection_mask()
        max_groups = max(1 << 12, bucket_capacity(res.batch.capacity))
        (kd, kv), _, ng, overflow = group_aggregate(keys, sel, [], [], max_groups)
        if bool(overflow):
            raise ExecutionError("distinct cardinality exceeded capacity")
        ng = int(ng)
        cols = []
        for i, s in enumerate(syms):
            valid = np.asarray(kv[i])[:ng]
            cols.append(
                Column(
                    s.type,
                    np.asarray(kd[i])[:ng].astype(s.type.storage_dtype),
                    None if valid.all() else valid,
                    dicts[i],
                )
            )
        return Result(Batch(cols, ng), {s.name: i for i, s in enumerate(syms)})

    # === joins ==========================================================
    def _exec_join(self, node: P.Join) -> Result:
        if node.join_type == "CROSS":
            return self._exec_cross_join(node)
        if node.join_type in ("SEMI", "ANTI"):
            return self._exec_semi_join(node)
        if node.join_type == "RIGHT":
            flipped = P.Join(
                "LEFT",
                node.right,
                node.left,
                [(b, a) for a, b in node.criteria],
                node.filter,
            )
            res = self._exec_join(flipped)
            return res  # layout covers both sides; order fixed by Output
        if node.join_type not in ("INNER", "LEFT", "FULL"):
            raise ExecutionError(f"join type {node.join_type} not supported yet")
        if node.join_type == "FULL" and node.filter is not None:
            raise ExecutionError("FULL OUTER JOIN with a non-equi ON filter")
        right = self._exec(node.right)  # build first: enables dynamic filter
        left_plan = self._apply_dynamic_filters(node, right)
        left = self._exec(left_plan)  # probe
        if left_plan is not node.left and id(left_plan) in self._reservations:
            # rekey the probe reservation so the parent free (which walks
            # node.sources) finds it
            self._reservations[id(node.left)] = self._reservations.pop(id(left_plan))
        if (
            node.criteria
            and node.join_type != "FULL"  # spill drops empty-probe partitions
            and self.session.get("spill_enabled")
            and int(left.batch.count_rows()) + int(right.batch.count_rows())
            > int(self.session.get("spill_threshold_rows"))
        ):
            return self._spill_join(node, left, right)
        return self._join_result(node, left, right)

    def _spill_join(self, node: P.Join, left: Result, right: Result) -> Result:
        """Partitioned (spill-to-host) join: hash-partition both sides so
        HBM holds one partition's working set at a time (reference:
        HashBuilderOperator spill states + GenericPartitioningSpiller)."""
        from trino_tpu.spill import partitioned_run

        n_part = int(self.session.get("spill_partitions"))
        lkeys, rkeys = self._join_keys(left, right, node.criteria)
        ph, _ = J.hash_keys(lkeys)
        bh, _ = J.hash_keys(rkeys)

        def run(subs, p):
            from trino_tpu.spill import pad_to_one_unselected

            if subs[0].num_rows == 0:
                return None  # no probe rows: inner AND left produce nothing
            rb = subs[1] if subs[1].num_rows > 0 else pad_to_one_unselected(subs[1])
            sub_left = Result(subs[0], dict(left.layout))
            sub_right = Result(rb, dict(right.layout))
            out = self._join_result(node, sub_left, sub_right)
            return out.batch.compact()

        parts = partitioned_run(
            [(left.batch, np.asarray(ph)), (right.batch, np.asarray(bh))],
            n_part,
            run,
        )
        layout: dict[str, int] = {}
        for s in node.left.output_symbols:
            layout[s.name] = len(layout)
        for s in node.right.output_symbols:
            layout[s.name] = len(layout)
        if not parts:
            cols = []
            srcs = [(node.left, left), (node.right, right)]
            for src_node, src_res in srcs:
                for s in src_node.output_symbols:
                    c = src_res.column(s)
                    data, valid = c.to_numpy()
                    cols.append(Column(c.type, data[:0], valid[:0], c.dictionary))
            return Result(Batch(cols, 0), layout)
        merged = concat_batches(parts) if len(parts) > 1 else parts[0]
        return Result(merged, layout)

    def _apply_dynamic_filters(self, node: P.Join, build: Result) -> P.PlanNode:
        """Collect build-side key domains and push them into the probe plan
        (reference: DynamicFilterSourceOperator -> DynamicFilterService ->
        probe scans; here synchronous since the build is materialized)."""
        from trino_tpu.dynfilter import collect_and_push

        left_plan = node.left
        if (
            node.join_type != "INNER"
            or not node.criteria
            or not self.session.get("enable_dynamic_filtering")
        ):
            return left_plan
        build_rows = int(build.batch.count_rows())
        if build_rows > int(self.session.get("dynamic_filtering_max_build_rows")):
            return left_plan
        sel = np.asarray(build.batch.selection_mask())
        for lsym, rsym in node.criteria:
            col = build.column(rsym)
            data = np.asarray(col.data)
            valid = np.asarray(col.valid_mask()) & sel
            left_plan = collect_and_push(
                left_plan, lsym, rsym, data, valid, build_rows,
                self.dynamic_filters,
            )
        return left_plan

    def _join_result(self, node: P.Join, left: Result, right: Result) -> Result:
        left = self._nonempty(left)
        right = self._nonempty(right)
        lkeys, rkeys = self._join_keys(left, right, node.criteria)
        bh, bv = J.hash_keys(rkeys)
        ph, pv = J.hash_keys(lkeys)
        sbk, sbi, bcount = J.build_side(bh, bv, right.batch.selection_mask())
        probe_sel = left.batch.selection_mask()
        est = max(1024, left.batch.count_rows() * 2, right.batch.count_rows())
        out_capacity = bucket_capacity(est)
        while True:
            ppos, bpos, osel, total, ovf = J.probe_join(
                sbk, sbi, bcount, ph, pv, probe_sel,
                out_capacity,
                "left" if node.join_type in ("LEFT", "FULL") else "inner",
            )
            if not bool(ovf):
                break
            out_capacity = bucket_capacity(int(total))
        osel = J.verify_equal(lkeys, rkeys, ppos, bpos, osel)
        if node.join_type == "LEFT":
            # verify may drop hash-collision rows; outer padding rows keep
            pass
        ppos_np = np.asarray(ppos)
        bpos_np = np.asarray(bpos)
        osel_np = np.asarray(osel)
        is_outer = bpos_np == J.MISSING
        if node.single_row:
            # scalar subquery: each outer row may match at most one row
            # (reference: EnforceSingleRowNode)
            matched_probe = ppos_np[osel_np & ~is_outer]
            if matched_probe.size and np.bincount(matched_probe).max() > 1:
                raise ExecutionError(
                    "Scalar sub-query has returned multiple rows"
                )
        cols: list[Column] = []
        layout: dict[str, int] = {}
        for s in node.left.output_symbols:
            c = left.column(s)
            data, valid = c.to_numpy()
            cols.append(
                Column(c.type, data[ppos_np], valid[ppos_np], c.dictionary)
            )
            layout[s.name] = len(cols) - 1
        safe_bpos = np.where(is_outer, 0, bpos_np)
        for s in node.right.output_symbols:
            c = right.column(s)
            data, valid = c.to_numpy()
            v = valid[safe_bpos] & ~is_outer
            cols.append(Column(c.type, data[safe_bpos], v, c.dictionary))
            layout[s.name] = len(cols) - 1
        out = Result(
            Batch(cols, out_capacity, osel_np), layout
        )
        if node.join_type == "FULL":
            # append null-extended unmatched build rows (the reference's
            # LookupJoinOperator FULL mode replays unvisited positions,
            # LookupJoinOperator.java:71)
            build_n = right.batch.capacity
            matched = np.zeros(build_n, dtype=bool)
            matched[bpos_np[osel_np & ~is_outer]] = True
            build_sel = np.asarray(right.batch.selection_mask())
            unmatched = np.nonzero(build_sel & ~matched)[0]
            if unmatched.size:
                n_left = len(node.left.output_symbols)
                cols2 = []
                for j, c in enumerate(out.batch.columns):
                    data, valid = c.to_numpy()
                    if j < n_left:  # probe columns: NULL
                        add_shape = (unmatched.size,) + data.shape[1:]
                        add = np.zeros(add_shape, dtype=data.dtype)
                        addv = np.zeros(unmatched.size, dtype=bool)
                    else:  # build columns: gather the unmatched rows
                        rc = right.column(node.right.output_symbols[j - n_left])
                        rd, rv = rc.to_numpy()
                        add, addv = rd[unmatched], rv[unmatched]
                    cols2.append(
                        Column(
                            c.type,
                            np.concatenate([data, add]),
                            np.concatenate([valid, addv]),
                            c.dictionary,
                        )
                    )
                keep = np.concatenate(
                    [osel_np, np.ones(unmatched.size, dtype=bool)]
                )
                return Result(
                    Batch(cols2, out.batch.num_rows + unmatched.size, keep),
                    out.layout,
                )
            return out
        if node.filter is not None:
            from trino_tpu.strings import lower_string_calls

            expr = self._bind(node.filter, out.layout)
            fcols = list(out.batch.columns)
            expr = lower_string_calls(expr, fcols)
            mask = ExprCompiler(
                fcols, params=getattr(self, "_params", None)
            ).predicate_mask(expr)
            mask_np = np.asarray(mask)
            if node.join_type == "LEFT":
                # ON-clause filter applies to MATCHES, not probe rows: a
                # probe row whose matches all fail must still appear once,
                # null-extended (the kernel emitted outer padding only for
                # rows with zero raw matches)
                sel_np = mask_np & osel_np
                keep = sel_np | (osel_np & is_outer)
                probe_n = left.batch.capacity
                raw_match = np.zeros(probe_n, dtype=bool)
                raw_match[ppos_np[osel_np & ~is_outer]] = True
                surviving = np.zeros(probe_n, dtype=bool)
                surviving[ppos_np[sel_np & ~is_outer]] = True
                need_outer = np.nonzero(raw_match & ~surviving)[0]
                if need_outer.size:
                    n_left = len(node.left.output_symbols)
                    cols2 = []
                    for j, c in enumerate(out.batch.columns):
                        data, valid = c.to_numpy()
                        if j < n_left:  # probe columns: gather the rows
                            lc = left.column(node.left.output_symbols[j])
                            ld, lv = lc.to_numpy()
                            add, addv = ld[need_outer], lv[need_outer]
                        else:  # build columns: null-extended
                            add = np.zeros(need_outer.size, dtype=data.dtype)
                            addv = np.zeros(need_outer.size, dtype=bool)
                        cols2.append(
                            Column(
                                c.type,
                                np.concatenate([data, add]),
                                np.concatenate([valid, addv]),
                                c.dictionary,
                            )
                        )
                    keep = np.concatenate(
                        [keep, np.ones(need_outer.size, dtype=bool)]
                    )
                    return Result(
                        Batch(cols2, out.batch.num_rows + need_outer.size, keep),
                        out.layout,
                    )
                return Result(
                    Batch(out.batch.columns, out.batch.num_rows, keep), out.layout
                )
            out = Result(
                Batch(out.batch.columns, out.batch.num_rows, mask_np & osel_np),
                out.layout,
            )
        return out

    def _join_keys(self, left: Result, right: Result, criteria):
        lkeys, rkeys = [], []
        for ls, rs in criteria:
            lc = left.column(ls)
            rc = right.column(rs)
            if getattr(lc.data, "ndim", 1) == 2 or getattr(rc.data, "ndim", 1) == 2:
                # wide DECIMAL join keys: one (hi) + one (lo) int64 key
                # pair per criterion — hashing and equality verification
                # treat the lanes as two ordinary keys
                if isinstance(ls.type, (T.DoubleType, T.RealType)) or isinstance(
                    rs.type, (T.DoubleType, T.RealType)
                ):
                    raise ExecutionError(
                        "join between DECIMAL(38) and floating point"
                    )
                from trino_tpu.ops import decimal128 as D128

                ls_s = ls.type.scale if isinstance(ls.type, T.DecimalType) else 0
                rs_s = rs.type.scale if isinstance(rs.type, T.DecimalType) else 0
                s = max(ls_s, rs_s)

                def lanes(col, scale):
                    if getattr(col.data, "ndim", 1) == 2:
                        hi, lo = col.data[:, 0], col.data[:, 1]
                    else:
                        hi, lo = D128.widen_i64(col.data.astype(jnp.int64))
                    if s > scale:
                        hi, lo = D128.rescale_up_wide(hi, lo, s - scale)
                    return hi, lo

                lhi, llo = lanes(lc, ls_s)
                rhi, rlo = lanes(rc, rs_s)
                lv = lc.valid_mask()
                rv = rc.valid_mask()
                lkeys.append((lhi, lv))
                lkeys.append((llo, lv))
                rkeys.append((rhi, rv))
                rkeys.append((rlo, rv))
                continue
            ld, lv = lc.data, lc.valid_mask()
            rd, rv = rc.data, rc.valid_mask()
            if lc.dictionary is not None or rc.dictionary is not None:
                if lc.dictionary is not rc.dictionary:
                    merged, remap = lc.dictionary.merged(rc.dictionary)
                    remap_j = jnp.asarray(remap)
                    rd = jnp.where(rd >= 0, remap_j[jnp.maximum(rd, 0)], -1)
            l_float = isinstance(ls.type, (T.DoubleType, T.RealType))
            r_float = isinstance(rs.type, (T.DoubleType, T.RealType))
            ls_scale = ls.type.scale if isinstance(ls.type, T.DecimalType) else 0
            rs_scale = rs.type.scale if isinstance(rs.type, T.DecimalType) else 0
            if l_float or r_float:
                # decimal/integer vs double: compare in double space, keyed
                # on the float64 bit pattern (exact per-value equality)
                if not l_float:
                    ld = ld.astype(jnp.float64) / (10**ls_scale)
                if not r_float:
                    rd = rd.astype(jnp.float64) / (10**rs_scale)
                ld = _f64_key(ld)
                rd = _f64_key(rd)
            elif ls_scale != rs_scale:
                # align scales: decimal-vs-decimal and decimal-vs-integer
                # joins must compare equal values equal
                s = max(ls_scale, rs_scale)
                ld = ld.astype(jnp.int64) * (10 ** (s - ls_scale))
                rd = rd.astype(jnp.int64) * (10 ** (s - rs_scale))
            lkeys.append((ld.astype(jnp.int64), lv))
            rkeys.append((rd.astype(jnp.int64), rv))
        return lkeys, rkeys

    def _exec_semi_join(self, node: P.Join) -> Result:
        left = self._nonempty(self._exec(node.left))
        right = self._nonempty(self._exec(node.right))
        if not node.criteria:
            if node.filter is not None:
                raise ExecutionError(
                    "non-equi correlated EXISTS without equality criteria "
                    "is not supported yet"
                )
            # uncorrelated EXISTS: right side non-empty?
            nonempty = right.batch.count_rows() > 0
            mark = nonempty if node.join_type == "SEMI" else not nonempty
            mark_val = np.full(left.batch.capacity, mark, dtype=np.bool_)
            cols = list(left.batch.columns) + [Column(T.BOOLEAN, mark_val)]
            layout = dict(left.layout)
            layout[node.mark_symbol.name] = len(cols) - 1
            return Result(Batch(cols, left.batch.num_rows, left.batch.sel), layout)
        lkeys, rkeys = self._join_keys(left, right, node.criteria)
        bh, bv = J.hash_keys(rkeys)
        ph, pv = J.hash_keys(lkeys)
        sbk, sbi, bcount = J.build_side(bh, bv, right.batch.selection_mask())
        # exact: expand matches, verify, then scatter-mark probe rows
        probe_sel = left.batch.selection_mask()
        out_capacity = bucket_capacity(
            max(1024, left.batch.count_rows() * 2)
        )
        while True:
            ppos, bpos, osel, total, ovf = J.probe_join(
                sbk, sbi, bcount, ph, pv, probe_sel, out_capacity, "inner"
            )
            if not bool(ovf):
                break
            out_capacity = bucket_capacity(int(total))
        osel = J.verify_equal(lkeys, rkeys, ppos, bpos, osel)
        if node.filter is not None:
            # residual correlated condition: evaluate over (probe row,
            # build row) pairs and drop non-qualifying matches
            safe_b = jnp.where(bpos == J.MISSING, 0, bpos)
            fcols: list[Column] = []
            flayout: dict[str, int] = {}
            for s in node.left.output_symbols:
                c = left.column(s)
                data, valid = c.to_numpy()
                p_np = np.asarray(ppos)
                fcols.append(Column(c.type, data[p_np], valid[p_np], c.dictionary))
                flayout[s.name] = len(fcols) - 1
            for s in node.right.output_symbols:
                c = right.column(s)
                data, valid = c.to_numpy()
                b_np = np.asarray(safe_b)
                fcols.append(Column(c.type, data[b_np], valid[b_np], c.dictionary))
                flayout[s.name] = len(fcols) - 1
            from trino_tpu.strings import lower_string_calls

            fexpr = self._bind(node.filter, flayout)
            fexpr = lower_string_calls(fexpr, fcols)
            fmask = ExprCompiler(
                fcols, params=getattr(self, "_params", None)
            ).predicate_mask(fexpr)
            osel = osel & fmask
        matched = (
            jnp.zeros(left.batch.capacity, dtype=jnp.bool_)
            .at[jnp.where(osel, ppos, left.batch.capacity)]
            .set(True, mode="drop")
        )
        # three-valued IN semantics (x IN S / x NOT IN S):
        #   matched            -> TRUE / FALSE
        #   S empty            -> FALSE / TRUE
        #   x NULL, S nonempty -> NULL
        #   no match, S has NULL -> NULL
        bsel_mask = right.batch.selection_mask()
        build_nonempty = bool(np.asarray(bsel_mask).any())
        any_null_build = bool(np.asarray((~bv) & bsel_mask).any())
        pv = jnp.ones(left.batch.capacity, dtype=jnp.bool_)
        for _, kv in lkeys:
            pv = pv & kv
        if not node.null_aware or not build_nonempty:
            # EXISTS semantics: strictly TRUE/FALSE (NULL keys never match)
            valid = jnp.ones(left.batch.capacity, dtype=jnp.bool_)
        else:
            valid = matched | (pv & (not any_null_build))
        value = matched if node.join_type == "SEMI" else ~matched
        mark_col = Column(T.BOOLEAN, value, None if bool(np.asarray(valid).all()) else valid)
        cols = list(left.batch.columns) + [mark_col]
        layout = dict(left.layout)
        layout[node.mark_symbol.name] = len(cols) - 1
        return Result(Batch(cols, left.batch.num_rows, left.batch.sel), layout)

    def _exec_cross_join(self, node: P.Join) -> Result:
        left = self._exec(node.left)
        right = self._exec(node.right)
        lb = left.batch.compact()
        rb = right.batch.compact()
        nl, nr = lb.num_rows, rb.num_rows
        if node.single_row and nr > 1:
            raise ExecutionError("Scalar sub-query has returned multiple rows")
        if node.single_row and nr == 0:
            # scalar over empty subquery yields NULL: pad one all-NULL row
            from trino_tpu.spill import pad_to_one_unselected

            padded = pad_to_one_unselected(rb)
            rb = Batch(
                [
                    Column(c.type, np.asarray(c.data), np.zeros(1, dtype=np.bool_), c.dictionary)
                    for c in padded.columns
                ],
                1,
            )
            nr = 1
        if nl * nr > (1 << 24):
            raise ExecutionError("cross join too large")
        cols: list[Column] = []
        layout: dict[str, int] = {}
        li = np.repeat(np.arange(nl), nr)
        ri = np.tile(np.arange(nr), nl)
        for s in node.left.output_symbols:
            c = lb.columns[left.layout[s.name]]
            data, valid = c.to_numpy()
            cols.append(Column(c.type, data[li], None if valid[li].all() else valid[li], c.dictionary))
            layout[s.name] = len(cols) - 1
        for s in node.right.output_symbols:
            c = rb.columns[right.layout[s.name]]
            data, valid = c.to_numpy()
            cols.append(Column(c.type, data[ri], None if valid[ri].all() else valid[ri], c.dictionary))
            layout[s.name] = len(cols) - 1
        return Result(Batch(cols, nl * nr), layout)

    # === set operations =================================================
    def _exec_groupid(self, node: P.GroupId) -> Result:
        """Replicate input once per grouping set, nulling absent key
        columns; appends the group-id column (GroupIdOperator analog)."""
        res = self._exec(node.source)
        base = res.batch.compact()
        parts: list[Batch] = []
        all_key_names = {s.name for s in node.all_keys}
        for gidx, group in enumerate(node.groups):
            present = {s.name for s in group}
            cols = []
            for s in node.source.output_symbols:
                c = base.columns[res.layout[s.name]]
                if s.name in all_key_names and s.name not in present:
                    data, _valid = c.to_numpy()
                    c = Column(
                        c.type, data, np.zeros(base.num_rows, dtype=np.bool_),
                        c.dictionary,
                    )
                cols.append(c)
            cols.append(
                Column(T.BIGINT, np.full(base.num_rows, gidx, dtype=np.int64))
            )
            parts.append(Batch(cols, base.num_rows))
        merged = concat_batches(parts) if len(parts) > 1 else parts[0]
        layout = {s.name: i for i, s in enumerate(node.source.output_symbols)}
        layout[node.gid.name] = len(node.source.output_symbols)
        return Result(merged, layout)

    def _exec_setop(self, node: P.SetOp) -> Result:
        parts = []
        for inp in node.inputs:
            r = self._exec(inp)
            b = r.batch.compact()
            # reorder columns to this input's output symbol order
            cols = [b.columns[r.layout[s.name]] for s in inp.output_symbols]
            parts.append(Batch(cols, b.num_rows))
        # coerce every input's column types to the setop's output types
        coerced = []
        for p in parts:
            cols = []
            for j, s in enumerate(node.symbols):
                c = p.columns[j]
                if c.type != s.type:
                    data, valid = c.to_numpy()
                    data = _host_cast(data, c.type, s.type)
                    c = Column(s.type, data, None if valid.all() else valid, c.dictionary)
                cols.append(c)
            coerced.append(Batch(cols, p.num_rows))
        if node.op == "UNION":
            merged = concat_batches(coerced)
            res = Result(
                merged, {s.name: i for i, s in enumerate(node.symbols)}
            )
            if node.distinct:
                return self._exec_distinct(P.Distinct(_FixedNode(node.symbols, res)))
            return res
        if node.op in ("INTERSECT", "EXCEPT"):
            # set semantics (reference: ALL variants unsupported in v1 too):
            # dedupe left, then keep rows [not] present in the right side —
            # a distinct + null-aware membership test on all columns
            return self._exec_setop_membership(node, coerced)
        raise ExecutionError(f"{node.op} not supported yet")

    def _exec_setop_membership(self, node: P.SetOp, parts: list[Batch]) -> Result:
        left, right = parts[0], parts[1]
        # host-side: row tuples (NULL-safe via sentinel) — set ops are
        # usually small (DISTINCT results); device path is a later optim
        def keys(b: Batch) -> list[tuple]:
            # one device->host conversion per column, then row tuples
            col_data = []
            for c in b.columns:
                data, valid = c.to_numpy()
                if c.dictionary is not None:
                    values = [
                        c.dictionary.decode(int(code)) if ok else None
                        for code, ok in zip(data.tolist(), valid.tolist())
                    ]
                else:
                    values = [
                        v if ok else None
                        for v, ok in zip(data.tolist(), valid.tolist())
                    ]
                col_data.append(values)
            return list(zip(*col_data)) if col_data else []

        lkeys = keys(left)
        rows: list[int] = []
        if node.distinct:
            rset = set(keys(right))
            seen: set[tuple] = set()
            for i, k in enumerate(lkeys):
                if k in seen:
                    continue
                seen.add(k)
                member = k in rset
                if (node.op == "INTERSECT") == member:
                    rows.append(i)
        else:
            # ALL variants: bag semantics — INTERSECT ALL keeps
            # min(mult_l, mult_r) copies; EXCEPT ALL keeps mult_l - mult_r
            from collections import Counter

            rcount = Counter(keys(right))
            if node.op == "INTERSECT":
                taken: Counter = Counter()
                for i, k in enumerate(lkeys):
                    if taken[k] < rcount.get(k, 0):
                        taken[k] += 1
                        rows.append(i)
            else:  # EXCEPT ALL
                skipped: Counter = Counter()
                for i, k in enumerate(lkeys):
                    if skipped[k] < rcount.get(k, 0):
                        skipped[k] += 1
                    else:
                        rows.append(i)
        idx = np.asarray(rows, dtype=np.int64)
        cols = []
        for c in left.columns:
            data, valid = c.to_numpy()
            cols.append(Column(c.type, data[idx], valid[idx], c.dictionary))
        return Result(
            Batch(cols, len(rows)),
            {s.name: i for i, s in enumerate(node.symbols)},
        )

    def _exec__fixednode(self, node: "_FixedNode") -> Result:
        return node.result

    # === misc ===========================================================
    def _bind(self, expr: RowExpr, layout: dict[str, int]) -> RowExpr:
        return bind_variables(expr, layout)


@dataclasses.dataclass
class _FixedNode(P.PlanNode):
    """Adapter: present an already-computed Result as a plan source."""

    symbols: list[P.Symbol]
    result: Result

    @property
    def output_symbols(self):
        return self.symbols


def _unify_strings(expr: RowExpr, columns: Sequence[Column]):
    """Build a unified dictionary over every string column/literal referenced
    by ``expr``; return (columns with string cols remapped, unified dict)."""
    from trino_tpu.ir import SpecialForm

    channels: list[int] = []
    literals: list[str] = []

    def walk(e: RowExpr):
        if isinstance(e, InputRef) and T.is_string(e.type):
            channels.append(e.channel)
        elif isinstance(e, Constant) and T.is_string(e.type) and e.value is not None:
            literals.append(str(e.value))
        elif isinstance(e, (Call, SpecialForm)):
            for a in e.args:
                walk(a)

    walk(expr)
    union = Dictionary([])
    remaps: dict[int, np.ndarray] = {}
    for ch in dict.fromkeys(channels):
        d = columns[ch].dictionary or Dictionary([])
        union, remap = union.merged(d)
        remaps[ch] = remap
    if literals:
        union, _ = union.merged(Dictionary(list(dict.fromkeys(literals))))
    new_cols = list(columns)
    for ch, remap in remaps.items():
        c = new_cols[ch]
        codes = jnp.asarray(np.asarray(remap, dtype=np.int32))[
            jnp.maximum(c.data, 0)
        ]
        codes = jnp.where(c.data >= 0, codes, -1)
        new_cols[ch] = Column(c.type, codes, c.valid, union)
    return new_cols, union


def _f64_key(x: jnp.ndarray) -> jnp.ndarray:
    """Exact int64 equality key for float64 values (+0/-0 normalized).
    f64->i64 bitcast is unsupported under TPU x64 rewriting, so bitcast to
    two int32 lanes and recombine."""
    x = jnp.where(x == 0.0, 0.0, x.astype(jnp.float64))
    parts = jax.lax.bitcast_convert_type(x, jnp.int32)  # (..., 2)
    lo = parts[..., 0].astype(jnp.int64) & 0xFFFFFFFF
    hi = parts[..., 1].astype(jnp.int64)
    return (hi << 32) | lo


def _host_cast(data: np.ndarray, from_t: T.SqlType, to_t: T.SqlType) -> np.ndarray:
    if isinstance(to_t, T.DecimalType):
        if isinstance(from_t, T.DecimalType):
            return data * 10 ** (to_t.scale - from_t.scale)
        if T.is_integer(from_t):
            return data.astype(np.int64) * to_t.unscale
    if isinstance(to_t, (T.DoubleType, T.RealType)):
        if isinstance(from_t, T.DecimalType):
            return (data / from_t.unscale).astype(to_t.storage_dtype)
        return data.astype(to_t.storage_dtype)
    return data.astype(to_t.storage_dtype)
