"""Execution: physical planning and operator evaluation.

Reference: ``core/trino-main/src/main/java/io/trino/operator/`` (~60 physical
operators, ``Driver.java:270`` hot loop) and
``sql/planner/LocalExecutionPlanner.java:392``. TPU-first: operators are
whole-column device computations; the "driver loop" is the host walking the
plan tree invoking jit-compiled kernels.
"""
