"""Fragment-fused execution: one compiled SPMD program per plan fragment.

Reference: Trino executes each PlanFragment as a pipeline of operators with
per-operator scheduling (``operator/Driver.java:355-392``); its "native
tier" compiles the hot expression chains (``sql/gen/ExpressionCompiler.java``).
The TPU translation goes further (SURVEY §7 "Stage = pjit program"): the
ENTIRE fragment — scan filters, projections, joins, partial/final
aggregation, and the exchange collectives that feed the next fragment —
traces into a single ``jax.jit`` program over the device mesh. No per-node
materialization, no host syncs between operators; XLA fuses the chain and
schedules the collectives (``lax.all_to_all`` / ``all_gather``) inline.

Execution model:
- :func:`fragment_plan` (planner/fragmenter.py) splits the optimized plan
  at remote exchanges.
- :class:`FragmentedExecutor` runs the fragment tree bottom-up. Every
  fragment whose nodes are in the fusable set runs as ONE jitted program;
  queries containing non-fusable shapes (windows, set ops, grouping sets,
  semi/anti joins, DISTINCT aggregates, VALUES) fall back to the
  materialized interpreter (``DistributedExecutor``), which remains the
  semantics reference.
- Capacities (group budgets, join output sizes, exchange buckets) are
  static per compile; kernels report overflow flags and the host retries
  with capacities regrown to the next power-of-two bucket. Compiled
  programs live in an engine-owned store keyed by canonical-plan
  fingerprint (planner/canonicalize.py) and, per program, by the
  capacity signature it was traced at — repeated or literal-variant
  queries skip Python retracing entirely (hoisted literals ride in as
  the ``__params__`` jit input), and the overflow ladder re-hits any
  signature it has seen before. Identical programs additionally skip
  XLA compilation via the persistent on-disk compile cache enabled in
  trino_tpu.__init__.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as PS

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column, Dictionary, bucket_capacity
from trino_tpu.exec.local import ExecutionError, Result, rank_codes, sum_spec_for
from trino_tpu.obs.metrics import get_registry
from trino_tpu.obs.trace import get_tracer
from trino_tpu.ops import join as J
from trino_tpu.ops.aggregation import AggSpec, global_aggregate, group_aggregate
from trino_tpu.ops.sort import sort_indices
from trino_tpu.parallel import exchange as X
from trino_tpu.parallel.distributed import DistributedExecutor, _sharded_probe
from trino_tpu.parallel.mesh import AXIS, shard_batch, smap
from trino_tpu.planner import plan as P
from trino_tpu.planner.fragmenter import (
    FusedFragment,
    PlanFragment,
    SubPlan,
    filtered_broadcast_fids,
    fragment_plan,
    fuse_groups,
    partitioned_join_pairs,
)


class FusedUnsupported(Exception):
    """Raised during tracing when a shape turns out not to be fusable."""


class BatchUnsupported(Exception):
    """Raised when a plan or input shape cannot ride the cross-query
    batched (K-unrolled) dispatch path — streaming-sized scans, spill
    inputs, multi-host meshes, non-fusable plans. Callers fall back to
    sequential per-member execution bit-identically."""


class CapacityRetryExceeded(ExecutionError):
    """Capacity-overflow retry budget exhausted.

    Carries the failing fragment, the final (grown) capacities, and the
    attempt count so operators see *where* growth diverged instead of a
    bare message. ``retryable=False``: capacity growth is a pure function
    of the data, so re-running on another worker (TASK retry) or from
    scratch (QUERY retry) replays the same growth path — the new retry
    policies treat this as fatal.
    """

    retryable = False

    def __init__(
        self,
        site: str,
        fragment_id=None,
        capacities: Optional[dict] = None,
        attempts: int = 0,
    ):
        self.site = site
        self.fragment_id = fragment_id
        self.capacities = dict(capacities or {})
        self.attempts = attempts
        caps_text = (
            ", ".join(f"{k}={v}" for k, v in sorted(self.capacities.items()))
            or "none recorded"
        )
        super().__init__(
            f"{site} capacity retry limit exceeded"
            f" (fragment={fragment_id if fragment_id is not None else '?'},"
            f" attempts={attempts}, final capacities: {caps_text})"
        )


# --- fusability -------------------------------------------------------------

_FUSABLE_NODES = (
    P.TableScan,
    P.RemoteSource,
    P.Filter,
    P.Project,
    P.Aggregate,
    P.Join,
    P.TopN,
    P.Limit,
    P.Sort,
    P.Output,
    P.Values,
)


def _is_wide_type(t) -> bool:
    return isinstance(t, T.DecimalType) and t.wide


def _expr_blocks_fusion(e) -> bool:
    """Modulus/cast touching wide DECIMAL narrows at runtime with a
    data-dependent check — not traceable; those queries interpret.
    (Wide DIVISION traces: ops/decimal128.div128_round.)"""
    from trino_tpu.ir import Call, SpecialForm

    if isinstance(e, Call):
        if e.name == "modulus" and (
            _is_wide_type(e.type) or any(_is_wide_type(a.type) for a in e.args)
        ):
            return True
        if e.name == "cast" and any(_is_wide_type(a.type) for a in e.args):
            st, rt = e.args[0].type, e.type
            traced = (
                isinstance(rt, (T.DoubleType, T.RealType))
                or (
                    isinstance(rt, T.DecimalType)
                    and isinstance(st, T.DecimalType)
                    and (rt.wide and rt.scale >= st.scale
                         or st.scale - rt.scale <= 18)
                )
            )
            if not traced:
                return True
        return any(_expr_blocks_fusion(a) for a in e.args)
    if isinstance(e, SpecialForm):
        return any(_expr_blocks_fusion(a) for a in e.args)
    return False


# XLA failure signatures that a SMALLER program can fix: scoped-vmem
# allocation failures at compile time and HBM exhaustion at run time
# (NOTES_r05 known issue 1: SF1 Q5's 33MB fragment program dies in
# scoped allocation before any overflow flag can fire)
_RESOURCE_ERROR_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "Resource exhausted",
    "resource exhausted",
    "Scoped allocation",
    "scoped allocation",
    "vmem limit",
    "VMEM limit",
    "out of memory",
    "Out of memory",
)


def _is_resource_exhausted(e: BaseException) -> bool:
    """True when an XLA compile/allocation failure should enter the
    capacity-HALVING ladder instead of failing the query."""
    msg = f"{type(e).__name__}: {e}"
    return any(m in msg for m in _RESOURCE_ERROR_MARKERS)


def grow_or_raise(name: str, caps: "_Caps") -> None:
    """Dispatch one fired traced flag: capacity names grow for a retry;
    ``err!<message>`` names are data-dependent runtime ERRORS discovered
    inside a compiled program (e.g. a scalar subquery returning multiple
    rows) and fail the query."""
    if name.startswith("err!"):
        raise ExecutionError(name[4:])
    # spill/hot tiers are deliberately small (the cold bucket absorbs the
    # common case), so when they do overflow, converge in few retries
    caps.grow(name, 4 if name.startswith(("agg", "spill", "hot")) else 2)


def query_fusable(sub: SubPlan) -> bool:
    return all(fragment_fusable(frag) for frag in sub.all_fragments())


def fragment_fusable(frag: PlanFragment) -> bool:
    """True when every node in this one fragment traces into the fused
    program (worker tasks check per-fragment: a window fragment interprets
    while its scan fragments still run fused on device)."""
    for n in P.walk_plan(frag.root):
        if not isinstance(n, _FUSABLE_NODES):
            return False
        if isinstance(n, P.Join):
            if n.join_type in ("SEMI", "ANTI"):
                # membership marks trace (hash lookup + scatter); residual
                # correlated filters still interpret
                if n.filter is not None or any(
                    _is_wide_type(a.type) or _is_wide_type(b.type)
                    for a, b in n.criteria
                ):
                    return False
                continue
            if n.join_type == "CROSS" and n.single_row:
                # uncorrelated scalar subquery: the one-row build
                # broadcasts into every probe row (traced)
                continue
            if (
                n.join_type not in ("INNER", "LEFT")
                or not n.criteria
                or (n.single_row and n.join_type != "LEFT")
                or (n.join_type == "LEFT" and n.filter is not None)
                or any(
                    _is_wide_type(a.type) or _is_wide_type(b.type)
                    for a, b in n.criteria
                )
            ):
                return False
            if n.filter is not None and _expr_blocks_fusion(n.filter):
                return False
        if isinstance(n, P.Aggregate):
            if any(fn.distinct for _, fn in n.aggregates) and n.step != "single":
                return False  # distinct dedup must see all rows at once
            if any(_is_wide_type(k.type) for k in n.group_keys):
                return False  # wide group keys: interpreter path
            for _, fn in n.aggregates:
                if fn.kind not in (
                    "sum", "count", "count_star", "min", "max", "avg"
                ):
                    return False
                # wide sums/min/max/avg all fuse (limb accumulators,
                # two-lane extrema, div128_round for the avg divide)
        if isinstance(n, P.Filter) and _expr_blocks_fusion(n.predicate):
            return False
        if isinstance(n, P.Project) and any(
            _expr_blocks_fusion(e) for _, e in n.assignments
        ):
            return False
    return True


class _Caps:
    """Capacity knobs, grown on overflow (shape-bucketed).

    ``provenance`` records where each value came from (``default`` /
    ``seeded`` from planner stats / ``history`` from the observed-stats
    store / ``+grown`` suffix after an overflow retry / ``+halved`` after
    a RESOURCE_EXHAUSTED shrink) — surfaced in the per-query exchange
    counters so capacity decisions are auditable.

    ``sites`` maps the tracer's runtime capacity names (which embed
    ``id(node)`` and change across processes and dynamic-filter rewrites)
    to restart-stable names like ``agg@3#0`` (kind @ fragment id # plan
    ordinal) — the keying the history store persists under."""

    def __init__(self):
        self.vals: dict[str, int] = {}
        self.provenance: dict[str, str] = {}
        self._seed_floor: dict[str, tuple[int, str]] = {}
        self.sites: dict[str, str] = {}
        # join engine v2: per-site chosen strategy (surfaced as
        # exchangeStats.joinStrategy), grow counts, and the demotion set.
        # A ``densejoin`` site that keeps overflowing after capacity
        # growth has a duplicate-key chain longer than the static probe
        # window — doubling can never place it (same key ⇒ same slot
        # sequence), so the site demotes to the sort strategy and the
        # retrace drops its table entirely (graceful, still compiled).
        self.join_strategies: dict[str, str] = {}
        self.grow_counts: dict[str, int] = {}
        self.demoted: set[str] = set()

    def get(self, name: str, default: int) -> int:
        if name not in self.vals:
            floor = self._seed_floor.pop(name, None)
            if floor is not None and floor[0] > default:
                self.vals[name] = floor[0]
                self.provenance[name] = floor[1]
            else:
                self.vals[name] = default
                self.provenance.setdefault(name, "default")
        return self.vals[name]

    def seed(
        self,
        name: str,
        value: int,
        floor_only: bool = False,
        provenance: str = "seeded",
    ) -> None:
        """Install a stats- or history-derived starting value.
        ``floor_only`` seeds take effect only when above the site's
        built-in default (used for join caps, where shrinking below the
        data-derived default trades a recompile-retry for padding).
        Floors are first-wins: history seeding runs before stats seeding
        and observed truth must not be clobbered by a static estimate."""
        if name in self.vals:
            return
        if floor_only:
            if name not in self._seed_floor:
                self._seed_floor[name] = (value, provenance)
        else:
            self.vals[name] = value
            self.provenance[name] = provenance

    def seeded(self, name: str):
        """(value, provenance) of a site's installed value or pending
        seed floor, or None — lets cost gates consult history before the
        site's first ``get()`` (the floor only installs at get time)."""
        if name in self.vals:
            return self.vals[name], self.provenance.get(name, "default")
        fl = self._seed_floor.get(name)
        return (fl[0], fl[1]) if fl is not None else None

    def grow(self, name: str, factor: int = 2) -> None:
        # quantize growth to power-of-two buckets: stats-seeded odd-sized
        # caps would otherwise walk a per-query ladder of unique shapes,
        # and every distinct capacity signature is a separate traced
        # program in the cross-query store
        self.vals[name] = bucket_capacity(self.vals[name] * factor, minimum=1)
        prev = self.provenance.get(name, "default")
        if not prev.endswith("+grown"):
            self.provenance[name] = prev + "+grown"
        # count under the restart-stable alias: every retrace mints a
        # fresh ``densejoin{id(node)}`` runtime name, so an id-keyed
        # counter would reset each attempt and the ladder would grow
        # until CapacityRetryExceeded instead of ever demoting
        stable = self.sites.get(name, name)
        self.grow_counts[stable] = self.grow_counts.get(stable, 0) + 1
        # second fruitless table growth ⇒ duplicate-chain pathology, not
        # sizing: demote the site to the sort strategy (class docstring)
        if name.startswith("densejoin") and self.grow_counts[stable] >= 2:
            self.demoted.add(stable)

    def shrink_all(self, factor: int = 2, floor: int = 64) -> bool:
        """Inverse ladder for RESOURCE_EXHAUSTED compile/alloc failures:
        the program's static shapes exceed scoped vmem (or HBM) before any
        overflow flag can run, so halve every capacity still above
        ``floor`` and retrace smaller. Returns False when nothing can
        shrink (caller re-raises). Row overflow after a halve re-grows
        through the normal ladder — both walks land on the same
        power-of-two buckets."""
        changed = False
        for nm, v in list(self.vals.items()):
            nv = max(floor, bucket_capacity(max(1, v // factor), minimum=1))
            if nv < v:
                self.vals[nm] = nv
                prev = self.provenance.get(nm, "default")
                if not prev.endswith("+halved"):
                    self.provenance[nm] = prev + "+halved"
                changed = True
        return changed

    def signature(self) -> tuple:
        """Hashable view of the current capacity values — the part of a
        traced program's shape that the plan fingerprint cannot see.
        Demotions ride along: a demoted join site traces a different
        kernel at the same capacities, so it must key a new program."""
        return tuple(sorted(self.vals.items())) + tuple(sorted(self.demoted))


@dataclasses.dataclass
class _Meta:
    """Static metadata captured while tracing a fragment program."""

    layout: Optional[dict[str, int]] = None
    column_meta: Optional[list[tuple[T.SqlType, Optional[Dictionary]]]] = None
    overflow_names: Optional[list[str]] = None
    output_names: Optional[list[str]] = None
    # exchange observability: names of traced counters riding the output,
    # plus statically-known per-execution stats (wire slots, bytes)
    counter_names: Optional[list[str]] = None
    exchange_static: Optional[dict] = None
    # device profiling (obs/profiler.py): XLA cost/memory analysis of the
    # compiled program — rides the program-cache entry so warm hits reuse
    # it without recompiling — and the AOT executable itself (warm hits
    # execute through it; None when profiling was off at trace time, the
    # AOT path failed, or a later call saw different input shapes)
    device_stats: Optional[dict] = None
    aot: Any = None
    # cross-query batching: K > 0 marks a batched program whose outputs
    # are per-member tuples — _retry_traced demuxes them into K Results
    # instead of assembling one (rides the cached (jf, meta) entry, so
    # warm hits demux without retracing)
    batch_size: Optional[int] = None

    def capture(self, res: Result, tracer) -> None:
        self.layout = dict(res.layout)
        self.column_meta = [
            (c.type, c.dictionary) for c in res.batch.columns
        ]
        self.overflow_names = [nm for nm, _ in tracer.overflows]
        self.counter_names = [nm for nm, _ in tracer.counters]
        self.exchange_static = dict(tracer.exchange_static)
        self._tracer = tracer

    def outputs(self, res: Result):
        flags = tuple(f for _, f in self._tracer.overflows)
        counters = tuple(c for _, c in self._tracer.counters)
        aux = tuple(self._tracer.aux_out)
        data = tuple((c.data, c.valid) for c in res.batch.columns)
        return data, res.batch.selection_mask(), flags, counters, aux


class _TracerSummary:
    """Merged view over the per-member tracers of a fused program, duck-
    typed to what ``_Meta.capture``/``_Meta.outputs`` read from a single
    :class:`_FragmentTracer`. Overflow flags and counters concatenate
    (site names are unique per node/fragment id); static exchange stats
    sum; ``aux_out`` carries only the ROOT member's exported hot set —
    interior probes' hot sets are consumed in-trace by their in-unit
    build peer and never leave the program."""

    def __init__(self):
        self.overflows: list = []
        self.counters: list = []
        self.exchange_static: dict = {}
        self.aux_out: tuple = ()

    def absorb(self, tracer) -> None:
        self.overflows.extend(tracer.overflows)
        self.counters.extend(tracer.counters)
        for k, v in tracer.exchange_static.items():
            self.exchange_static[k] = self.exchange_static.get(k, 0) + v


class _BatchSummary:
    """Combined view over the K per-member tracers of a cross-query
    batched program, duck-typed like :class:`_TracerSummary`. The K
    members are copies of ONE program over different parameter slices,
    so their overflow/counter site lists are identical — flags merge
    positionally by element-wise max (a site overflows when ANY member
    overflows; the grown rerun re-executes all members) and counters
    sum, keeping the host-side deferred-flag protocol at one scalar per
    site whatever K. Static exchange stats sum; ``aux_out`` stays empty
    (skew handling is disabled under batching)."""

    def __init__(self):
        self.overflows: list = []
        self.counters: list = []
        self.exchange_static: dict = {}
        self.aux_out: tuple = ()
        self._first = True

    def absorb(self, tracer) -> None:
        if self._first:
            self.overflows = [
                (nm, f.astype(jnp.int32)) for nm, f in tracer.overflows
            ]
            self.counters = list(tracer.counters)
            self._first = False
        else:
            self.overflows = [
                (nm, jnp.maximum(f, g.astype(jnp.int32)))
                for (nm, f), (_, g) in zip(self.overflows, tracer.overflows)
            ]
            self.counters = [
                (nm, c + d)
                for (nm, c), (_, d) in zip(self.counters, tracer.counters)
            ]
        for k, v in tracer.exchange_static.items():
            self.exchange_static[k] = self.exchange_static.get(k, 0) + v


def program_label(program_key) -> str:
    """Stable display label for a program-cache key: fragment identity
    without the per-run root-object id (metrics labels and deviceStats
    keys must not churn across executions of the same cached plan)."""
    if isinstance(program_key, tuple) and len(program_key) >= 2:
        if program_key[0] == "frag":
            return f"frag:{program_key[1]}"
        if program_key[0] == "post":
            return f"post:{program_key[1]}"
        if program_key[0] == "fused":
            return "fused:" + "+".join(str(i) for i in program_key[1])
        if program_key[0] == "bfrag":
            return f"bfrag:{program_key[1]}x{program_key[2]}"
        if program_key[0] == "bfused":
            return (
                "bfused:"
                + "+".join(str(i) for i in program_key[1])
                + f"x{program_key[2]}"
            )
    return repr(program_key)


class FragmentedExecutor(DistributedExecutor):
    """Distributed executor that compiles each fragment into one program.

    ``programs`` (optional) is an engine-owned store that outlives this
    per-query executor: jitted fragment programs and their capture
    metadata are reused across executions of the same cached plan, so a
    warm query skips Python retracing entirely (the reference's operators
    are reused per-driver; ours are compiled programs reused per-plan).
    """

    # overflow flags queued during _execute_fragments (None outside it,
    # e.g. when worker tasks call run_fragment_program directly)
    deferred_flags: Optional[list] = None
    # exchange counters queued alongside: (names, stacked int64, static)
    deferred_counters: Optional[list] = None

    def __init__(
        self,
        *args,
        programs: Optional[dict] = None,
        params: Optional[Sequence] = None,
        history: Optional[dict] = None,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.programs: dict = {} if programs is None else programs
        # this fingerprint's aggregate entry from the query-history store
        # (obs/history.py), or None when history is off / the query is
        # cold: observed final capacities floor the static stats seeds
        self.history = history
        # ordered (value, type) literals hoisted out of a canonicalized
        # plan (planner/canonicalize.py): interpreter paths read the host
        # values via self._params; traced programs receive device scalars
        # through the __params__ jit input
        self._param_list = list(params) if params else []
        self._params = (
            tuple(v for v, _ in self._param_list) or None
        )
        # per-query compile-time telemetry (CacheStatsMBean analog);
        # engine copies this onto StatementResult after execution
        self.compile_stats: dict = {
            "trace_count": 0,
            "compile_ms": 0.0,
            "program_cache_hits": 0,
            "program_cache_misses": 0,
        }
        # per-query operator telemetry accumulated off the op! counter
        # channel: {stable_site: {kind, rows_in, rows_out}}
        self.operator_stats: dict[str, dict] = {}
        # per-query: replicated hot-key tables exported by probe-side
        # exchanges, keyed by producer fragment id (device arrays)
        self._hot_sets: dict[int, tuple] = {}
        # chaos hook (trino_tpu/ft): per-fragment crash injection. None
        # unless the session configures fault probabilities.
        from trino_tpu.ft.injection import FaultInjector

        self.fault_injector = FaultInjector.from_session(self.session)

    def execute(self, node: P.PlanNode) -> tuple[Batch, list[str]]:
        # reuse the fragmented plan across executions of a cached plan:
        # program-cache keys and traced closures reference fragment node
        # identities, so the fragmentation must be stable too
        sub = self.programs.get("__subplan__")
        if sub is None:
            with get_tracer().span("fragment"):
                sub = fragment_plan(node)
            self.programs["__subplan__"] = sub
        if not query_fusable(sub):
            return super().execute(node)
        try:
            return self._execute_fragments(sub)
        except FusedUnsupported:
            return super().execute(node)
        except jax.errors.TracerArrayConversionError:
            # an operator needed host values mid-trace (e.g. datetime
            # formatting over unique values) — interpret instead
            return super().execute(node)

    def _param_arrays(self) -> Optional[tuple]:
        """Hoisted literals as typed device scalars — the ``__params__``
        jit input. Dtypes come from the hoisted Constant's SQL type so a
        parameter-vector value is bit-identical to what ``jnp.full`` would
        have baked."""
        if not self._param_list:
            return None
        return tuple(
            jnp.asarray(v, dtype=t.storage_dtype) for v, t in self._param_list
        )

    def _store_program(self, program_key, sig, jf, meta) -> None:
        """Insert a traced program under (program_key, capacity signature).

        ``("frag", id, apply_exchange, id(root))`` keys (and their
        ``("fused", ids, apply_exchange, root_ids)`` counterparts) embed
        root-node identities because dynamic filtering rebuilds probe
        roots per execution; on a shared cross-query store those per-run
        keys would accumulate (each cached closure pins its root alive,
        keeping ids unique), so storing a new root's program evicts every
        entry for the same fragment(s) traced against a different — now
        unreachable — root.
        """
        if (
            isinstance(program_key, tuple)
            and len(program_key) == 4
            and program_key[0] in ("frag", "fused")
        ):
            prefix, rid = program_key[:3], program_key[3]
            stale = [
                k
                for k in self.programs
                if isinstance(k, tuple)
                and len(k) == 2
                and isinstance(k[0], tuple)
                and len(k[0]) == 4
                and k[0][:3] == prefix
                and k[0][3] != rid
            ]
            for k in stale:
                self.programs.pop(k, None)
        self.programs[(program_key, sig)] = (jf, meta)

    def _all_capacities(self) -> dict:
        """Flattened view of every grown capacity in the program store,
        for CapacityRetryExceeded diagnostics."""
        out: dict[str, int] = {}
        for key, val in self.programs.items():
            if (
                isinstance(key, tuple)
                and key
                and key[0] == "caps"
                and isinstance(val, _Caps)
            ):
                scope = ".".join(str(k) for k in key[1:])
                for nm, v in val.vals.items():
                    out[f"{scope}:{nm}"] = v
        return out

    # === skew / stats-seeding / observability ===========================

    def _skew_roles(self) -> dict[int, dict]:
        """Map producer-fragment id -> skew role for every partitioned
        (hash/hash) equi-join. The fragmenter cuts ``Join.left`` before
        ``Join.right``, so the probe producer always executes first — its
        exchange detects heavy hitters over the probe-side key hashes
        (build sides are typically near-unique, so probe frequencies are
        where Zipf skew is visible) and the build producer salts with the
        resulting hot set. SEMI/ANTI and single-row joins are left on the
        plain two-tier path."""
        roles = self.programs.get("__skewroles__")
        if roles is None:
            roles = {}
            sub = self.programs.get("__subplan__")
            if sub is not None and bool(self.session.get("skew_handling")):
                for frag in sub.all_fragments():
                    for node in P.walk_plan(frag.root):
                        if (
                            isinstance(node, P.Join)
                            and node.join_type in ("INNER", "LEFT")
                            and node.criteria
                            and not node.single_row
                            and isinstance(node.left, P.RemoteSource)
                            and node.left.exchange_type == "hash"
                            and isinstance(node.right, P.RemoteSource)
                            and node.right.exchange_type == "hash"
                        ):
                            roles[node.left.fragment_id] = {"role": "probe"}
                            roles[node.right.fragment_id] = {
                                "role": "build",
                                "peer": node.left.fragment_id,
                            }
            self.programs["__skewroles__"] = roles
        return roles

    def _history_sites(self, frag: PlanFragment) -> dict[str, str]:
        """Runtime capacity-site names → restart-stable names. The tracer
        mints sites as ``agg{id(node)}`` / ``join{id(node)}`` /
        ``semi{id(node)}`` — node ids churn across processes AND across
        dynamic-filter rewrites — so history keys them by kind, fragment
        id, and walk ordinal instead (``agg@3#0``), which is stable for a
        given fingerprint. ``semi`` sites are minted on Join nodes (the
        semi/mark-join exec path), so each Join registers both. Scan and
        filter sites carry no capacities — they exist so the operator
        row counters (the ``op!`` channel) key those nodes by the same
        restart-stable scheme."""
        sites = {
            f"exch{frag.id}": f"exch@{frag.id}",
            f"spill{frag.id}": f"spill@{frag.id}",
            f"hot{frag.id}": f"hot@{frag.id}",
        }
        agg_k = join_k = scan_k = filter_k = 0
        for node in P.walk_plan(frag.root):
            if isinstance(node, P.Aggregate):
                sites[f"agg{id(node)}"] = f"agg@{frag.id}#{agg_k}"
                agg_k += 1
            elif isinstance(node, P.Join):
                sites[f"join{id(node)}"] = f"join@{frag.id}#{join_k}"
                sites[f"semi{id(node)}"] = f"semi@{frag.id}#{join_k}"
                sites[f"densejoin{id(node)}"] = f"densejoin@{frag.id}#{join_k}"
                join_k += 1
            elif isinstance(node, P.TableScan):
                sites[f"opscan{id(node)}"] = f"scan@{frag.id}#{scan_k}"
                scan_k += 1
            elif isinstance(node, P.Filter):
                sites[f"opfilter{id(node)}"] = f"filter@{frag.id}#{filter_k}"
                filter_k += 1
        return sites

    def _seed_history(self, frag: PlanFragment, caps: "_Caps") -> None:
        """History-seeded capacities: final observed shapes from earlier
        runs of this fingerprint floor the static estimates. Runs BEFORE
        ``_seed_caps`` — floors are first-wins, so observed truth beats a
        static guess. Grown sites seed floor-only (same contract as stats
        seeding: never shrink an engineered default); halved sites seed
        exactly — the larger shape failed to compile or allocate, and
        re-deriving that by retries is what history exists to avoid.
        Always registers the runtime→stable site map so the snapshot can
        persist capacities under restart-stable keys."""
        try:
            sites = self._history_sites(frag)
            caps.sites.update(sites)
            hcaps = (self.history or {}).get("capacities") or {}
            if not hcaps:
                return
            seeded = 0
            for runtime, stable in sites.items():
                ent = hcaps.get(stable)
                if not isinstance(ent, dict):
                    continue
                if runtime in caps.vals or runtime in caps._seed_floor:
                    continue
                val = bucket_capacity(int(ent.get("value", 0)), minimum=1)
                if val <= 0:
                    continue
                prov = str(ent.get("provenance", ""))
                caps.seed(
                    runtime,
                    val,
                    floor_only="+halved" not in prov,
                    provenance="history",
                )
                seeded += 1
            if seeded:
                from trino_tpu.obs.metrics import get_registry

                get_registry().counter(
                    "trino_tpu_history_seeds_total"
                ).inc(seeded)
        except Exception:  # noqa: BLE001 — seeding is best-effort
            pass

    def _seed_caps(self, frag: PlanFragment, caps: "_Caps") -> None:
        """Stats-seeded capacity defaults: planner NDV/row-count estimates
        pick realistic starting buckets per agg/join/exchange site, so
        cold runs skip the overflow-retry-recompile ladder. Site names use
        the (possibly dynamic-filter-rewritten) node ids of THIS trace, so
        stats are computed over the rewritten root; upstream fragment
        cardinalities come from the once-per-plan subplan stats."""
        if not bool(self.session.get("stats_capacity_seeding")):
            return
        try:
            from trino_tpu.planner import stats as PStats

            sub = self.programs.get("__subplan__")
            root_stats = self.programs.get("__fragstats__")
            if root_stats is None and sub is not None:
                root_stats = PStats.fragment_output_stats(sub, self.catalogs)
                self.programs["__fragstats__"] = root_stats
            calc = PStats.FragmentStatsCalculator(
                self.catalogs, root_stats or {}
            )
            n = max(int(self.mesh.devices.size), 1)
            for node in P.walk_plan(frag.root):
                if isinstance(node, P.Aggregate) and node.group_keys:
                    est = calc.stats(node).row_count
                    if est and est > 0:
                        groups = est / n if node.step == "final" else est
                        caps.seed(
                            f"agg{id(node)}",
                            min(
                                1 << 16,
                                bucket_capacity(
                                    max(256, int(4 * groups)), minimum=256
                                ),
                            ),
                            floor_only=True,
                        )
                elif (
                    isinstance(node, P.Join)
                    and node.criteria
                    and node.join_type in ("INNER", "LEFT")
                    and not node.single_row
                ):
                    est = calc.stats(node).row_count
                    if est and est > 0:
                        caps.seed(
                            f"join{id(node)}",
                            min(
                                1 << 20,
                                bucket_capacity(
                                    max(1024, int(4 * est) // n),
                                    minimum=1024,
                                ),
                            ),
                            floor_only=True,
                        )
            if frag.output_exchange == "hash":
                est = calc.stats(frag.root).row_count
                if est and est > 0:
                    # floor_only everywhere: stats may pre-grow a site the
                    # retry ladder would otherwise have to discover, but
                    # never shrink an engineered default — estimates miss
                    # per-shard amplification (partial-agg outputs exceed
                    # the fragment's global row count) and a low seed
                    # re-creates the overflow-retry-recompile ladder.
                    # Salted exchanges route the heavy mass off the cold
                    # path, so their cold seed is half the plain one
                    # (mirrors the salted default in apply_output_exchange)
                    mult = 1 if frag.id in self._skew_roles() else 2
                    caps.seed(
                        f"exch{frag.id}",
                        bucket_capacity(
                            max(64, int(mult * est) // (n * n)), minimum=64
                        ),
                        floor_only=True,
                    )
        except Exception:  # noqa: BLE001 — seeding is best-effort
            pass

    def _accumulate_exchange(self, names, vals, static) -> None:
        st = self.exchange_stats
        for k, v in (static or {}).items():
            st[k] = st.get(k, 0) + v
        for nm, v in zip(names or (), vals):
            if nm.startswith("sent"):
                st["shuffle_rows"] += int(v)
            elif nm.startswith("salted"):
                st["salted_rows"] += int(v)
            elif nm.startswith("hotkeys"):
                st["hot_keys"] += int(v)
            elif nm.startswith("op!"):
                # operator row counters: op!{kind}!{in|out}!{stable_site},
                # minted with the restart-stable site resolved at trace
                # time (deferred entries don't carry the _Caps site map)
                _, kind, io, site = nm.split("!", 3)
                ent = self.operator_stats.get(site)
                if ent is None:
                    ent = self.operator_stats[site] = {
                        "kind": kind,
                        "rows_in": 0,
                        "rows_out": 0,
                    }
                ent["rows_in" if io == "in" else "rows_out"] += int(v)

    def exchange_stats_snapshot(self) -> dict:
        """Finalized per-query exchange counters (engine attaches this to
        the statement result; /v1/query serves it as ``exchangeStats``)."""
        st = dict(self.exchange_stats)
        st["padding_ratio"] = round(
            st.get("padded_shuffle_rows", 0) / max(1, st.get("shuffle_rows", 0)),
            4,
        )
        caps: dict[str, dict] = {}
        join_strategy: dict[str, str] = {}
        history_seeds = 0
        for key, val in self.programs.items():
            if (
                isinstance(key, tuple)
                and key
                and key[0] == "caps"
                and isinstance(val, _Caps)
            ):
                scope = ".".join(str(k) for k in key[1:])
                for nm, v in val.vals.items():
                    prov = val.provenance.get(nm, "default")
                    caps[f"{scope}:{nm}"] = {
                        "value": v,
                        "provenance": prov,
                        # restart-stable name — what the history store
                        # keys this site by across processes
                        "site": val.sites.get(nm, nm),
                    }
                    if prov.startswith("history"):
                        history_seeds += 1
                for nm, strat in val.join_strategies.items():
                    join_strategy[val.sites.get(nm, nm)] = strat
        st["capacities"] = caps
        # capacity sites whose value came from the observed-history store
        # (surfaced as queryStats.historySeeds on /v1/query)
        st["history_seeds"] = history_seeds
        # join engine v2: chosen kernel per join site (sort / dense /
        # matmul, including demotions observed during the retry ladder)
        st["joinStrategy"] = join_strategy
        if self.operator_stats:
            # per-operator row flow keyed by restart-stable site; batched
            # dispatches sum across stacked members (one program, K
            # queries), which the rollups document as combined flow
            st["operators"] = {
                site: dict(ent) for site, ent in self.operator_stats.items()
            }
        return st

    def ingest_stats_snapshot(self):
        """Per-query ingest counters plus the engine-wide device table
        cache state (entries/bytes/evictions), so /v1/query shows both
        what this query paid and what is HBM-resident for the next one."""
        snap = super().ingest_stats_snapshot()
        if snap is not None and self.table_cache is not None:
            snap["tableCache"] = self.table_cache.snapshot()
        return snap

    # === fragment scheduling ============================================

    def _execute_fragments(self, sub: SubPlan) -> tuple[Batch, list[str]]:
        import time as _time

        results: dict[int, Result] = {}
        names_holder: dict[int, list[str]] = {}
        units = self._fusion_units(sub)

        def run_units():
            for unit in units:
                fused = isinstance(unit, FusedFragment)
                if self.fault_injector is not None:
                    # fragment-level injection sites: deterministic per
                    # (seed, fragment id). A fused unit keeps one site per
                    # MEMBER so chaos schedules are identical with fusion
                    # on or off; in a worker's fused path the crash
                    # surfaces as a task failure (fused_strict) or a
                    # visible interpreter fallback
                    for fid in (
                        unit.fragment_ids if fused else (unit.id,)
                    ):
                        self.fault_injector.maybe_crash_task(f"frag:{fid}")
                if fused:
                    results[unit.id] = self._run_fused_unit(
                        unit, results, names_holder
                    )
                else:
                    results[unit.id] = self._run_fragment(
                        unit, results, names_holder
                    )

        # Optimistic overflow protocol: fragments enqueue their overflow
        # flags (device scalars) in `deferred_flags` instead of pulling
        # each one — a device->host pull costs a full runtime round trip,
        # so the whole query checks ALL flags in ONE transfer, and only
        # the (rare) overflow grows capacities and reruns.
        attempts = 0
        while True:
            attempts += 1
            if attempts > 12:
                raise CapacityRetryExceeded(
                    "fragmented-query",
                    fragment_id=sub.fragment.id,
                    capacities=self._all_capacities(),
                    attempts=attempts - 1,
                )
            self.deferred_flags = []
            self.deferred_counters = []
            results.clear()
            names_holder.clear()
            self._hot_sets.clear()
            run_units()
            root = results[sub.fragment.id]
            if jax.process_count() > 1:
                # multi-host: replicate the (small) root result so every
                # process holds it fully before host materialization
                from trino_tpu.parallel.mesh import replicated

                rep = jax.jit(
                    lambda b: b, out_shardings=replicated(self.mesh)
                )(root.batch)
                root = Result(rep, root.layout)
            deferred = self.deferred_flags
            dcounters = self.deferred_counters
            self.deferred_flags = None
            self.deferred_counters = None
            # the overflow flags (and exchange counters) ride the SAME
            # packed pull as the root batch (optimistic: the output of an
            # overflowed run is discarded and the query reruns with grown
            # budgets; counters only accumulate on the surviving attempt)
            extras = [
                jnp.ravel(f.astype(jnp.int32)) for _, _, f, _ in deferred
            ] + [jnp.ravel(c) for _, c, _ in dcounters if c is not None]
            t_pull = _time.perf_counter()
            host_root, extra_vals = root.batch.to_host(extras=extras)
            pull_ms = (_time.perf_counter() - t_pull) * 1000.0
            get_tracer().record(
                "device_pull", pull_ms,
                attrs={"extras": len(extras), "attempt": attempts},
            )
            get_registry().histogram("trino_tpu_device_pull_ms").observe(
                pull_ms
            )
            flag_vals = extra_vals[: len(deferred)]
            counter_vals = list(extra_vals[len(deferred):])
            overflowed = False
            for (key, names, _, caps), seg in zip(deferred, flag_vals):
                seg = np.atleast_1d(np.asarray(seg))
                for nm, fl in zip(names, seg):
                    if fl:
                        overflowed = True
                        grow_or_raise(nm, caps)
                # the overflowed program stays in the store: its key
                # carries the capacity signature it was traced at, so the
                # grown rerun traces fresh while a later same-sized query
                # (or regrow ladder revisit) still reuses it
            if not overflowed:
                for names, stacked, static in dcounters:
                    vals = (
                        np.atleast_1d(np.asarray(counter_vals.pop(0)))
                        if stacked is not None
                        else ()
                    )
                    self._accumulate_exchange(names, vals, static)
                root = Result(host_root, root.layout)
                break
            self.exchange_stats["overflow_retries"] += 1
        out = root.batch.compact()
        names = names_holder.get(sub.fragment.id) or [
            s.name for s in sub.fragment.root.output_symbols
        ]
        return out, names

    def _df_build_lookup(self, results: dict[int, Result]):
        """Dynamic-filter domain accessor over completed fragment results
        (None for fragments that haven't materialized — e.g. fused-unit
        interiors — or for cross-host sharded intermediates)."""

        def build_lookup(fid):
            res = results.get(fid)
            if res is None:
                return None
            if jax.process_count() > 1:
                # intermediate fragment results are sharded across hosts;
                # host-side domains would need a collective — skip
                return None
            sel = np.asarray(res.batch.selection_mask())

            def get_column(name):
                idx = res.layout.get(name)
                if idx is None:
                    return None
                c = res.batch.columns[idx]
                return c.data, np.asarray(c.valid_mask()) & sel

            return get_column, int(sel.sum())

        return build_lookup

    # === whole-pipeline fusion ==========================================

    def _fusion_units(self, sub: SubPlan) -> list:
        """Bottom-up execution units: :class:`FusedFragment` groups where
        pipeline fusion applies, plain fragments elsewhere. Cached per
        plan entry — the grouping references fragment identities, so like
        the subplan itself it must be stable across executions."""
        units = self.programs.get("__fusedunits__")
        if units is None:
            if bool(self.session.get("pipeline_fusion")):
                blocked = set(self._fusion_blocked(sub))
                if bool(self.session.get("enable_dynamic_filtering")):
                    # a selective broadcast build must stay a fragment
                    # boundary: worker-side dynamic filtering prunes the
                    # probe from the MATERIALIZED build, which a fused
                    # interior member never produces
                    blocked |= filtered_broadcast_fids(sub)
                units = fuse_groups(
                    sub,
                    fusable=fragment_fusable,
                    max_fragments=max(
                        1, int(self.session.get("fusion_max_fragments"))
                    ),
                    blocked=frozenset(blocked),
                    skew_pairs=(
                        partitioned_join_pairs(sub)
                        if bool(self.session.get("skew_handling"))
                        else ()
                    ),
                    # star joins: absorb broadcast dim builds so a fact
                    # chain probes every dim in ONE program (the traced
                    # broadcast link replicates in-trace)
                    broadcast_links=bool(self.session.get("dense_join")),
                )
            else:
                units = []

                def visit(sp: SubPlan):
                    for child in sp.children:
                        visit(child)
                    units.append(sp.fragment)

                visit(sub)
            self.programs["__fusedunits__"] = units
        return units

    def _graceful_overflow(self) -> bool:
        """True when the dense join tier's graceful overflow is active:
        a spill-sized join input can stay on the compiled path because a
        build-table overflow re-hashes at doubled capacity inside the
        retry ladder (``densejoin@…`` sites) instead of needing the
        interpreter's partitioned spill — so the spill threshold stops
        barring fragments from fusion and from the compiled path."""
        return bool(self.session.get("dense_join")) and str(
            self.session.get("join_strategy") or "auto"
        ).lower() != "sort"

    def _fusion_blocked(self, sub: SubPlan) -> set:
        """Fragment ids that must stay on the per-fragment path: scans
        big enough for the streaming chunk loop (bounded memory beats one
        materialized program) or for the interpreter's spill fallback.
        Estimate-based, mirroring the per-fragment gates; tables without
        estimates are discovered at materialization time and fall back
        via FusedUnsupported instead."""
        from trino_tpu.exec.streaming import streamable_chain

        blocked: set[int] = set()
        stream_threshold = int(
            self.session.get("stream_scan_threshold_rows")
        )
        spill_threshold = (
            int(self.session.get("spill_threshold_rows"))
            if self.session.get("spill_enabled")
            and not self._graceful_overflow()
            else None
        )
        for frag in sub.all_fragments():
            chain = streamable_chain(frag.root)
            stream_scan = chain[1] if chain is not None else None
            for n in P.walk_plan(frag.root):
                if not isinstance(n, P.TableScan):
                    continue
                try:
                    est = self.catalogs.get(n.catalog).estimate_rows(
                        n.schema, n.table
                    )
                except Exception:  # noqa: BLE001 — treat as unknown
                    est = None
                if est is None:
                    continue
                if n is stream_scan and est > stream_threshold:
                    blocked.add(frag.id)
                if spill_threshold is not None and est > spill_threshold:
                    blocked.add(frag.id)
        return blocked

    def _run_fused_unit(
        self,
        unit: FusedFragment,
        results: dict[int, Result],
        names_holder: dict[int, list[str]],
    ) -> Result:
        span = get_tracer().start_span(
            "fused_execute",
            attrs={"stage": unit.id, "fragments": len(unit.fragments)},
        )
        try:
            with span:
                return self._run_fused_spanned(
                    unit, results, names_holder, span
                )
        except (FusedUnsupported, CapacityRetryExceeded):
            # bit-identical fallback: run the members as the ordinary
            # per-fragment dispatches the grouping pass replaced (a member
            # that is itself ineligible — e.g. a spill-sized input found
            # only at materialization — then escalates to the interpreter
            # exactly as before)
            for frag in unit.fragments:
                results[frag.id] = self._run_fragment(
                    frag, results, names_holder
                )
            return results[unit.id]

    def _run_fused_spanned(
        self,
        unit: FusedFragment,
        results: dict[int, Result],
        names_holder: dict[int, list[str]],
        span,
    ) -> Result:
        import time as _time

        t0 = _time.perf_counter()
        from trino_tpu.dynfilter import fragment_dynamic_filters

        member_ids = set(unit.fragment_ids)
        # dynamic filtering sees only OUTSIDE-unit build results (interior
        # producers haven't run — they exist solely inside the trace);
        # lookups for them return None, which the rewrite treats as
        # "domain unavailable", a pure pruning loss, never a wrong result
        lookup = self._df_build_lookup(results)
        members = []
        for frag in unit.fragments:
            root = fragment_dynamic_filters(
                frag.root, lookup, self.session, self.dynamic_filters
            )
            members.append(dataclasses.replace(frag, root=root))

        inputs: dict[str, Any] = {}
        input_layouts: dict[str, dict[str, int]] = {}
        spill_threshold = (
            int(self.session.get("spill_threshold_rows"))
            if self.session.get("spill_enabled")
            and not self._graceful_overflow()
            else None
        )
        for frag in members:
            for n in P.walk_plan(frag.root):
                if isinstance(n, P.TableScan):
                    res = self._exec_tablescan(n)
                    if (
                        spill_threshold is not None
                        and res.batch.capacity > spill_threshold
                    ):
                        raise FusedUnsupported("spill-sized input")
                    inputs[f"scan{id(n)}"] = res.batch
                    input_layouts[f"scan{id(n)}"] = res.layout
                elif (
                    isinstance(n, P.RemoteSource)
                    and n.fragment_id not in member_ids
                ):
                    r = results[n.fragment_id]
                    inputs[f"remote{n.fragment_id}"] = r.batch
                    input_layouts[f"remote{n.fragment_id}"] = r.layout
                elif isinstance(n, P.Output):
                    names_holder[frag.id] = list(n.column_names)
        # the unit ROOT's own output exchange may pair with an
        # outside-unit peer (the grouping pass keeps in-unit pairs whole,
        # so only the root can face an external probe/build mate)
        skew = None
        role = self._skew_roles().get(unit.id)
        if role is not None:
            if role["role"] == "probe":
                skew = {
                    "detect": (
                        max(1, int(self.session.get("skew_hot_k"))),
                        float(self.session.get("skew_hot_threshold_frac")),
                    )
                }
            else:
                hs = self._hot_sets.get(role["peer"])
                if hs is not None:
                    skew = {"salt": True}
                    inputs["__hotset__"] = (hs[0], hs[1])
        sink = {} if self.stats_collector is not None else None
        out = self.run_fused_program(
            members, inputs, input_layouts, stats_sink=sink, defer=True,
            skew=skew,
        )
        aux = getattr(self, "_last_aux", ())
        if aux:
            self._hot_sets[unit.id] = aux
        span.set("mode", "fused-pipeline")
        if sink:
            span.set("attempts", sink.get("attempts", 1))
        get_registry().counter("trino_tpu_fused_programs_total").inc()
        if self.stats_collector is not None:
            self.stats_collector.record_fragment(
                unit.id,
                {
                    "mode": "fused-pipeline",
                    "fragments": list(unit.fragment_ids),
                    "wall_s": _time.perf_counter() - t0,
                    "attempts": (sink or {}).get("attempts", 1),
                    "input_rows": (sink or {}).get("input_rows", 0),
                    "output_rows": int(
                        np.asarray(out.batch.selection_mask()).sum()
                    ),
                },
            )
        return out

    def _run_fragment(
        self,
        frag: PlanFragment,
        results: dict[int, Result],
        names_holder: dict[int, list[str]],
    ) -> Result:
        # span per fragment execution; program_compile / exchange spans
        # emitted inside parent to it via the ambient stack
        span = get_tracer().start_span(
            "fragment_execute", attrs={"stage": frag.id}
        )
        with span:
            return self._run_fragment_spanned(
                frag, results, names_holder, span
            )

    def _run_fragment_spanned(
        self,
        frag: PlanFragment,
        results: dict[int, Result],
        names_holder: dict[int, list[str]],
        span,
    ) -> Result:
        import time as _time

        t0 = _time.perf_counter()
        streamed = self._try_streaming(frag, names_holder, results)
        if streamed is not None:
            span.set("mode", "streamed")
            if self.stats_collector is not None:
                self.stats_collector.record_fragment(
                    frag.id,
                    {
                        "mode": "streamed",
                        "wall_s": _time.perf_counter() - t0,
                        "output_rows": int(
                            np.asarray(streamed.batch.selection_mask()).sum()
                        ),
                    },
                )
            return streamed
        # dynamic filtering: completed build fragments prune this
        # fragment's probe scans before any input materializes
        from trino_tpu.dynfilter import fragment_dynamic_filters

        root = fragment_dynamic_filters(
            frag.root,
            self._df_build_lookup(results),
            self.session,
            self.dynamic_filters,
        )
        frag = dataclasses.replace(frag, root=root)

        inputs: dict[str, Batch] = {}
        input_layouts: dict[str, dict[str, int]] = {}
        spill_threshold = (
            int(self.session.get("spill_threshold_rows"))
            if self.session.get("spill_enabled")
            and not self._graceful_overflow()
            else None
        )
        for n in P.walk_plan(frag.root):
            if isinstance(n, P.TableScan):
                res = self._exec_tablescan(n)  # sharded host->device read
                if spill_threshold is not None and res.batch.capacity > spill_threshold:
                    # working set beyond the spill threshold: defer to the
                    # interpreter, which has the partitioned-spill path
                    raise FusedUnsupported("spill-sized input")
                inputs[f"scan{id(n)}"] = res.batch
                input_layouts[f"scan{id(n)}"] = res.layout
            elif isinstance(n, P.RemoteSource):
                res = results[n.fragment_id]
                inputs[f"remote{n.fragment_id}"] = res.batch
                input_layouts[f"remote{n.fragment_id}"] = res.layout
            elif isinstance(n, P.Output):
                names_holder[frag.id] = list(n.column_names)
        # skew handling: the probe-side producer of a partitioned join
        # detects heavy hitters inside its exchange program and exports
        # the hot-key tables; the build-side producer (which runs after
        # it) receives them as a traced input and salts its exchange
        skew = None
        role = self._skew_roles().get(frag.id)
        if role is not None:
            if role["role"] == "probe":
                skew = {
                    "detect": (
                        max(1, int(self.session.get("skew_hot_k"))),
                        float(self.session.get("skew_hot_threshold_frac")),
                    )
                }
            else:
                hs = self._hot_sets.get(role["peer"])
                if hs is not None:
                    skew = {"salt": True}
                    inputs["__hotset__"] = (hs[0], hs[1])
        sink = {} if self.stats_collector is not None else None
        out = self.run_fragment_program(
            frag, inputs, input_layouts, stats_sink=sink, defer=True,
            skew=skew,
        )
        aux = getattr(self, "_last_aux", ())
        if aux:
            self._hot_sets[frag.id] = aux
        span.set("mode", "fused")
        if sink:
            span.set("attempts", sink.get("attempts", 1))
        if self.stats_collector is not None:
            self.stats_collector.record_fragment(
                frag.id,
                {
                    "mode": "fused",
                    "wall_s": _time.perf_counter() - t0,
                    "attempts": sink.get("attempts", 1),
                    "input_rows": sink.get("input_rows", 0),
                    "output_rows": int(
                        np.asarray(out.batch.selection_mask()).sum()
                    ),
                },
            )
        return out

    def _try_streaming(
        self,
        frag: PlanFragment,
        names_holder: dict[int, list[str]],
        results: Optional[dict] = None,
    ) -> Optional[Result]:
        """Scan→agg(→join) fragments over large tables run as a bounded
        chunk loop (exec/streaming.py) instead of materializing the
        probe table; join build sides materialize once up front."""
        from trino_tpu.exec.streaming import (
            StreamingAggregator,
            StreamOverflow,
            streamable_chain,
        )

        chain = streamable_chain(frag.root)
        if chain is None:
            return None
        agg, scan, build_roots = chain
        connector = self.catalogs.get(scan.catalog)
        est = connector.estimate_rows(scan.schema, scan.table)
        if est is None or est <= int(
            self.session.get("stream_scan_threshold_rows")
        ):
            return None
        # build-side inputs: scans materialize now (bounded by the spill
        # threshold — bigger builds go to the interpreter's spill path),
        # remote sources come from completed upstream fragments
        build_inputs: dict[str, Batch] = {}
        build_layouts: dict[str, dict[str, int]] = {}
        build_bound = int(self.session.get("spill_threshold_rows"))
        for root in build_roots:
            for n in P.walk_plan(root):
                if isinstance(n, P.TableScan):
                    bconn = self.catalogs.get(n.catalog)
                    best = bconn.estimate_rows(n.schema, n.table)
                    if best is not None and best > build_bound:
                        return None
                    bres = self._exec_tablescan(n)
                    build_inputs[f"scan{id(n)}"] = bres.batch
                    build_layouts[f"scan{id(n)}"] = bres.layout
                elif isinstance(n, P.RemoteSource):
                    upstream = (results or {}).get(n.fragment_id)
                    if upstream is None:
                        return None
                    build_inputs[f"remote{n.fragment_id}"] = upstream.batch
                    build_layouts[f"remote{n.fragment_id}"] = upstream.layout
        caps = self.programs.setdefault(("caps", "stream", frag.id), _Caps())
        self._seed_history(frag, caps)
        attempts = 0
        while True:
            attempts += 1
            if attempts > 12:
                raise CapacityRetryExceeded(
                    "streaming",
                    fragment_id=frag.id,
                    capacities=caps.vals,
                    attempts=attempts - 1,
                )
            try:
                res = StreamingAggregator(
                    self, frag, agg, scan, caps,
                    build_roots=build_roots,
                    build_inputs=build_inputs,
                    build_layouts=build_layouts,
                ).run()
                break
            except StreamOverflow as e:
                for nm in e.names:
                    grow_or_raise(nm, caps)
        if isinstance(frag.root, P.Output):
            names_holder[frag.id] = list(frag.root.column_names)
            cols = [res.column(s) for s in frag.root.symbols]
            res = Result(
                Batch(cols, res.batch.capacity, res.batch.sel),
                {s.name: i for i, s in enumerate(frag.root.symbols)},
            )
        if frag.output_exchange in (None, "single"):
            return res
        # apply the fragment's output exchange as its own small program

        def build_post(meta: _Meta):
            def post(batch):
                tracer = _FragmentTracer(self, {}, {}, caps)
                out = tracer.apply_output_exchange(
                    frag, Result(batch, res.layout)
                )
                tracer.exchange_static["dispatchRoundTrips"] = 1
                meta.capture(out, tracer)
                return meta.outputs(out)

            return post

        return self._retry_traced(
            caps, build_post, (res.batch,), program_key=("post", frag.id),
            defer=True,
        )

    def _retry_traced(
        self,
        caps: "_Caps",
        build_fn,
        args: tuple,
        stats_sink: Optional[dict] = None,
        input_rows: int = 0,
        program_key=None,
        defer: bool = False,
    ) -> Result:
        """Run a traced program under the capacity-overflow retry protocol
        and materialize its Result. ``build_fn(meta)`` returns the function
        to jit; it must call ``meta.capture`` and return ``meta.outputs``.

        ``program_key`` (optional) reuses the jitted program + meta from
        ``self.programs`` across queries on the same cached plan. Entries
        are stored under ``(program_key, caps.signature())`` — the
        capacity signature the program was traced at — so the overflow
        ladder never serves a stale-capacity program AND any signature
        seen before (by this query's regrow ladder or an earlier query on
        the shared store) is reused instead of retraced.

        With ``defer=True`` (fragments inside ``_execute_fragments``) the
        overflow flags are NOT pulled here: they are queued as device
        scalars on ``self.deferred_flags`` and the whole query checks them
        in one transfer; the outer loop grows ``caps`` and reruns.
        """
        import time as _time

        self._last_aux = ()
        attempts = 0
        while True:
            attempts += 1
            if attempts > 12:
                raise CapacityRetryExceeded(
                    "traced-program",
                    fragment_id=(
                        # keys are ("frag", frag.id, ...) / ("post", frag.id)
                        program_key[1]
                        if isinstance(program_key, tuple)
                        and len(program_key) >= 2
                        else None
                    ),
                    capacities=caps.vals,
                    attempts=attempts - 1,
                )
            cached = (
                self.programs.get((program_key, caps.signature()))
                if program_key is not None
                else None
            )
            traced_now = cached is None
            store_stats = (
                self.programs.setdefault(
                    "__stats__",
                    {"hits": 0, "misses": 0, "trace_count": 0,
                     "compile_ms": 0.0},
                )
                if program_key is not None
                else None
            )
            if cached is not None:
                jf, meta = cached
                self.compile_stats["program_cache_hits"] += 1
                store_stats["hits"] += 1
            else:
                meta = _Meta()
                jf = jax.jit(build_fn(meta))
                if program_key is not None:
                    self.compile_stats["program_cache_misses"] += 1
                    store_stats["misses"] += 1
            t0 = _time.perf_counter()
            outs = None
            if self._device_profiling:
                # AOT-compile the SAME jitted function and execute through
                # the resulting executable: identical program (bit-identical
                # results, no double compile), but the Compiled object
                # additionally exposes XLA's cost/memory analysis
                if traced_now:
                    try:
                        compiled = jf.lower(*args).compile()
                        meta.aot = compiled
                        from trino_tpu.obs.profiler import (
                            capture_device_stats,
                        )

                        meta.device_stats = capture_device_stats(compiled)
                    except Exception:  # noqa: BLE001 — degrade to plain jit
                        meta.aot = None
                if meta.aot is not None:
                    try:
                        outs = meta.aot(*args)
                    except Exception:  # noqa: BLE001 — e.g. new input
                        # shapes on a warm hit: jf(*args) below retraces
                        # transparently, exactly as the unprofiled path does
                        meta.aot = None
                        outs = None
            if outs is None:
                try:
                    outs = jf(*args)
                except Exception as e:  # noqa: BLE001 — inspect and rethrow
                    if not _is_resource_exhausted(e) or not caps.shrink_all():
                        raise
                    # the program failed to COMPILE (scoped-vmem / HBM
                    # exhaustion) before any overflow flag could run:
                    # enter the same retry ladder as row overflow,
                    # inverted — halve every capacity and retrace smaller
                    self.exchange_stats["compile_halvings"] = (
                        self.exchange_stats.get("compile_halvings", 0) + 1
                    )
                    get_registry().counter(
                        "trino_tpu_compile_halvings_total"
                    ).inc()
                    get_tracer().record(
                        "compile_halving", 0.0,
                        attrs={
                            "key": repr(program_key) if program_key else None,
                            "attempt": attempts,
                        },
                    )
                    continue
            data, sel, flags, counters, aux = outs
            compile_ms = 0.0
            if traced_now:
                # trace + lower + (XLA or disk-cache) compile happen
                # synchronously inside the first call; execution itself
                # dispatches async, so this wall time ≈ compile cost
                compile_ms = (_time.perf_counter() - t0) * 1000.0
                self.compile_stats["trace_count"] += 1
                self.compile_stats["compile_ms"] += compile_ms
                if store_stats is not None:
                    store_stats["trace_count"] += 1
                    store_stats["compile_ms"] = round(
                        store_stats["compile_ms"] + compile_ms, 3
                    )
                get_tracer().record(
                    "program_compile", compile_ms,
                    attrs={
                        "key": repr(program_key) if program_key else None,
                        "attempt": attempts,
                    },
                )
                get_registry().histogram(
                    "trino_tpu_program_compile_ms"
                ).observe(compile_ms)
            if self._device_profiling and program_key is not None:
                self._record_device_stats(
                    program_label(program_key), meta.device_stats, compile_ms
                )
            self._last_aux = aux
            if defer and getattr(self, "deferred_flags", None) is not None:
                if flags:
                    stacked = jnp.stack([jnp.reshape(f, ()) for f in flags])
                    self.deferred_flags.append(
                        (program_key, list(meta.overflow_names), stacked, caps)
                    )
                if (counters or meta.exchange_static) and getattr(
                    self, "deferred_counters", None
                ) is not None:
                    cstack = (
                        jnp.stack([jnp.reshape(c, ()) for c in counters])
                        if counters
                        else None
                    )
                    self.deferred_counters.append(
                        (
                            list(meta.counter_names),
                            cstack,
                            dict(meta.exchange_static),
                        )
                    )
                if program_key is not None and traced_now:
                    # keyed by the POST-trace signature: tracing filled in
                    # any capacities this program consults via caps.get
                    self._store_program(program_key, caps.signature(), jf, meta)
                if stats_sink is not None:
                    stats_sink.setdefault("attempts", 0)
                    stats_sink["attempts"] += 1
                    stats_sink["last_wall_s"] = _time.perf_counter() - t0
                    stats_sink["input_rows"] = input_rows
                break
            # ONE device->host pull for all overflow flags: each separate
            # scalar transfer pays the full runtime round-trip latency
            if flags:
                stacked = jnp.stack([jnp.reshape(f, ()) for f in flags])
                flags_np = [bool(x) for x in np.asarray(stacked)]
            else:
                flags_np = []
            if stats_sink is not None:
                jax.block_until_ready(sel)
                stats_sink.setdefault("attempts", 0)
                stats_sink["attempts"] += 1
                stats_sink["last_wall_s"] = _time.perf_counter() - t0
                stats_sink["input_rows"] = input_rows
            if not any(flags_np):
                if program_key is not None and traced_now:
                    self._store_program(program_key, caps.signature(), jf, meta)
                if counters or meta.exchange_static:
                    vals = (
                        np.atleast_1d(
                            np.asarray(
                                jnp.stack(
                                    [jnp.reshape(c, ()) for c in counters]
                                )
                            )
                        )
                        if counters
                        else ()
                    )
                    self._accumulate_exchange(
                        meta.counter_names, vals, meta.exchange_static
                    )
                break
            self.exchange_stats["overflow_retries"] += 1
            for nm, f in zip(meta.overflow_names, flags_np):
                if f:
                    grow_or_raise(nm, caps)
        if meta.batch_size:
            # batched program: data/sel are tuples over the K members —
            # demux into one Result per member (all members share the
            # column meta and layout captured at trace time, since they
            # are copies of one program)
            out = []
            for mdata, msel in zip(data, sel):
                cols = [
                    Column(t, d, v, dictionary)
                    for (d, v), (t, dictionary) in zip(
                        mdata, meta.column_meta
                    )
                ]
                cap = cols[0].data.shape[0] if cols else int(msel.shape[0])
                out.append(Result(Batch(cols, cap, msel), meta.layout))
            return out
        cols = [
            Column(t, d, v, dictionary)
            for (d, v), (t, dictionary) in zip(data, meta.column_meta)
        ]
        # zero-column fragments (count(*) over pruned scans) still carry
        # row liveness in sel
        cap = cols[0].data.shape[0] if cols else int(sel.shape[0])
        return Result(Batch(cols, cap, sel), meta.layout)

    def run_fragment_program(
        self,
        frag: PlanFragment,
        inputs: dict[str, Batch],
        input_layouts: dict[str, dict[str, int]],
        apply_exchange: bool = True,
        stats_sink: Optional[dict] = None,
        defer: bool = False,
        skew: Optional[dict] = None,
    ) -> Result:
        """Compile + run one fragment as a single jitted SPMD program.

        ``inputs`` maps ``scan{id(node)}`` / ``remote{fragment_id}`` keys to
        device batches. With ``apply_exchange=False`` the fragment's output
        exchange is skipped — callers that ship pages across processes
        (worker tasks) partition on the host instead. ``stats_sink``
        receives per-fragment compile/run timings when provided. ``skew``
        configures the output exchange's skew handling (see
        ``_FragmentTracer.apply_output_exchange``); the hot-key tables
        themselves travel as the ``__hotset__`` input so cached programs
        never bake a stale hot set in as constants.
        """
        caps = self.programs.setdefault(("caps", frag.id), _Caps())
        self._seed_history(frag, caps)
        self._seed_caps(frag, caps)
        pvec = self._param_arrays()
        if pvec is not None:
            # hoisted literals ride as device-scalar jit inputs: literal
            # variants of the same canonical plan reuse the traced program
            inputs = dict(inputs)
            inputs["__params__"] = pvec

        def build(meta: _Meta):
            def fn(inp: dict[str, Batch]):
                tracer = _FragmentTracer(
                    self, inp, input_layouts, caps, skew=skew
                )
                res = tracer._exec(frag.root)
                if apply_exchange:
                    res = tracer.apply_output_exchange(frag, res)
                # every execution of this program is one dispatch
                # round-trip; the static rides the counter protocol so
                # only the surviving (non-overflowed) attempt counts
                tracer.exchange_static["dispatchRoundTrips"] = 1
                meta.capture(res, tracer)
                return meta.outputs(res)

            return fn

        return self._retry_traced(
            caps,
            build,
            (inputs,),
            stats_sink=stats_sink,
            input_rows=sum(
                b.capacity for b in inputs.values() if isinstance(b, Batch)
            ),
            # the rewritten root's identity is part of the key: dynamic
            # filtering rebuilds fragment nodes per attempt, and a program
            # traced against old node ids must not serve new inputs (the
            # cached closure pins the old root alive, so its id is unique)
            program_key=("frag", frag.id, apply_exchange, id(frag.root)),
            defer=defer,
        )

    def run_fused_program(
        self,
        frags: Sequence[PlanFragment],
        inputs: dict[str, Any],
        input_layouts: dict[str, dict[str, int]],
        apply_exchange: bool = True,
        stats_sink: Optional[dict] = None,
        defer: bool = False,
        skew: Optional[dict] = None,
    ) -> Result:
        """Compile + run a CHAIN of exchange-connected fragments as ONE
        jitted SPMD program — the whole-pipeline fusion path.

        ``frags`` is in bottom-up execution order (producers first, the
        consumer root LAST). ``inputs`` holds only EXTERNAL feeds: table
        scans of every member plus ``remote{fid}`` batches from producers
        outside the unit; interior exchange links never leave the device —
        each producer's output exchange lowers to in-program collectives
        (``skewed_repartition``'s all_to_all/all_gather) and feeds the
        consumer's RemoteSource as a traced value. ``skew`` configures the
        ROOT member's output exchange; in-unit partitioned-join pairs
        detect and salt entirely in-trace, hot-set tables passing from the
        probe member's exchange to the build member's without ever
        becoming a jit input. One program = one dispatch round-trip,
        whatever the member count.
        """
        frags = list(frags)
        fids = tuple(f.id for f in frags)
        member_ids = set(fids)
        caps = self.programs.setdefault(("caps", "fused", fids), _Caps())
        for f in frags:
            self._seed_history(f, caps)
            self._seed_caps(f, caps)
        pvec = self._param_arrays()
        if pvec is not None:
            inputs = dict(inputs)
            inputs["__params__"] = pvec
        # in-unit skew roles (host-side, static): the grouping pass
        # absorbs partitioned-join pairs atomically, so an interior
        # member's peer is always a member too; only the root can face an
        # external mate (handled by the caller through ``skew``)
        roles = self._skew_roles()
        member_skew: dict[int, dict] = {}
        for fid in fids[:-1]:
            role = roles.get(fid)
            if role is None:
                continue
            if role["role"] == "probe":
                member_skew[fid] = {
                    "detect": (
                        max(1, int(self.session.get("skew_hot_k"))),
                        float(self.session.get("skew_hot_threshold_frac")),
                    )
                }
            elif role["peer"] in member_ids:
                member_skew[fid] = {"salt": True, "peer": role["peer"]}

        def build(meta: _Meta):
            def fn(inp: dict[str, Any]):
                avail = dict(inp)
                layouts = dict(input_layouts)
                combined = _TracerSummary()
                hot_sets: dict[int, tuple] = {}
                res = None
                tracer = None
                for frag in frags:
                    last = frag is frags[-1]
                    mskew = member_skew.get(frag.id)
                    if mskew is not None and mskew.get("salt"):
                        hs = hot_sets.get(mskew["peer"])
                        if hs is None:
                            mskew = None
                        else:
                            # in-trace hot-set handoff: the probe member
                            # ran earlier in this same trace (fragmenter
                            # cuts Join.left first, so bottom-up order
                            # puts the probe before its build mate). The
                            # handoff key is peer-scoped: the plain
                            # "__hotset__" slot belongs to the CALLER
                            # (the root may salt against an external
                            # probe), and a unit can hold several pairs
                            key = f"__hotset__{mskew['peer']}"
                            avail = dict(avail)
                            avail[key] = (hs[0], hs[1])
                            mskew = {"salt": True, "hotset_key": key}
                    if last:
                        mskew = skew
                    tracer = _FragmentTracer(
                        self, avail, layouts, caps, skew=mskew
                    )
                    res = tracer._exec(frag.root)
                    if not last or apply_exchange:
                        res = tracer.apply_output_exchange(frag, res)
                    combined.absorb(tracer)
                    if tracer.aux_out:
                        hot_sets[frag.id] = tracer.aux_out
                    if not last:
                        avail = dict(avail)
                        layouts = dict(layouts)
                        avail[f"remote{frag.id}"] = res.batch
                        layouts[f"remote{frag.id}"] = res.layout
                # one program = one dispatch, whatever the member count;
                # fusedFragments rides the same surviving-attempt protocol
                combined.exchange_static["dispatchRoundTrips"] = 1
                combined.exchange_static["fusedFragments"] = len(frags)
                # only the ROOT's hot set leaves the program (interior
                # probes' tables were consumed in-trace above)
                combined.aux_out = tracer.aux_out
                meta.capture(res, combined)
                return meta.outputs(res)

            return fn

        return self._retry_traced(
            caps,
            build,
            (inputs,),
            stats_sink=stats_sink,
            input_rows=sum(
                b.capacity for b in inputs.values() if isinstance(b, Batch)
            ),
            # root identities of every member key the entry, for the same
            # dynamic-filter staleness reason as the per-fragment path
            program_key=(
                "fused",
                fids,
                apply_exchange,
                tuple(id(f.root) for f in frags),
            ),
            defer=defer,
        )

    # === cross-query batched dispatch ===================================

    def execute_batched(
        self, node: P.PlanNode, param_sets: Sequence[Sequence]
    ) -> tuple[list[Batch], list[str]]:
        """Execute K literal-variant queries as ONE stacked dispatch.

        ``param_sets`` holds one hoisted-literal vector per query, all
        canonicalizing to the plan this executor was built for. The K
        member executions unroll inside a single ``jax.jit`` trace —
        identical ops over different ``__params__`` slices — so every
        member's result is bit-identical to its sequential run while the
        whole batch pays one dispatch round-trip, one program-cache
        lookup, and one device->host pull. Returns (batches, names):
        one compacted host Batch per member, in submission order.

        Dynamic filtering and skew salting are disabled on this path
        (both rebuild per-execution state that would couple members or
        churn program keys); the losses are pruning/padding only, never
        results. Raises :class:`BatchUnsupported` for shapes the path
        cannot carry — non-fusable plans, streaming/spill-sized scans,
        multi-host meshes — and callers fall back to sequential
        per-member execution.
        """
        if jax.process_count() > 1:
            raise BatchUnsupported("multi-host mesh")
        if self.stats_collector is not None:
            raise BatchUnsupported("stats collector attached")
        if not self._param_list:
            raise BatchUnsupported("no hoisted parameters")
        sub = self.programs.get("__subplan__")
        if sub is None:
            with get_tracer().span("fragment"):
                sub = fragment_plan(node)
            self.programs["__subplan__"] = sub
        if not query_fusable(sub):
            raise BatchUnsupported("plan not fusable")
        if self._fusion_blocked(sub):
            raise BatchUnsupported("streaming/spill-sized scan")
        try:
            return self._execute_fragments_batched(sub, list(param_sets))
        except FusedUnsupported as e:
            raise BatchUnsupported(str(e)) from e
        except jax.errors.TracerArrayConversionError as e:
            raise BatchUnsupported("host values needed mid-trace") from e

    def _execute_fragments_batched(
        self, sub: SubPlan, param_sets: list
    ) -> tuple[list[Batch], list[str]]:
        import time as _time

        kreq = len(param_sets)
        # bucket K to a power of two, padding with copies of member 0
        # (only the first kreq results are returned): every distinct K is
        # a separately traced program, so quantizing batch sizes keeps
        # the program store small exactly like the capacity buckets do
        K = 1
        while K < kreq:
            K *= 2
        padded = list(param_sets) + [param_sets[0]] * (K - kreq)
        pstack = tuple(
            jnp.asarray([ps[i] for ps in padded], dtype=t.storage_dtype)
            for i, (_, t) in enumerate(self._param_list)
        )
        results: dict[int, list[Result]] = {}
        names_holder: dict[int, list[str]] = {}
        units = self._fusion_units(sub)

        def run_units():
            for unit in units:
                if isinstance(unit, FusedFragment):
                    results[unit.id] = self._run_fused_unit_batched(
                        unit, K, pstack, results, names_holder
                    )
                else:
                    results[unit.id] = self._run_fragment_batched(
                        unit, K, pstack, results, names_holder
                    )

        # same optimistic deferred-flag protocol as _execute_fragments:
        # flags are already max-merged across members in-trace, so the
        # host still checks one scalar per site in one transfer
        attempts = 0
        while True:
            attempts += 1
            if attempts > 12:
                raise CapacityRetryExceeded(
                    "batched-query",
                    fragment_id=sub.fragment.id,
                    capacities=self._all_capacities(),
                    attempts=attempts - 1,
                )
            self.deferred_flags = []
            self.deferred_counters = []
            results.clear()
            names_holder.clear()
            run_units()
            roots = results[sub.fragment.id]
            deferred = self.deferred_flags
            dcounters = self.deferred_counters
            self.deferred_flags = None
            self.deferred_counters = None
            extras = [
                jnp.ravel(f.astype(jnp.int32)) for _, _, f, _ in deferred
            ] + [jnp.ravel(c) for _, c, _ in dcounters if c is not None]
            t_pull = _time.perf_counter()
            host_batches, extra_vals = self._demux_batch_to_host(
                roots, extras
            )
            pull_ms = (_time.perf_counter() - t_pull) * 1000.0
            get_tracer().record(
                "device_pull", pull_ms,
                attrs={
                    "extras": len(extras),
                    "attempt": attempts,
                    "batch": K,
                },
            )
            get_registry().histogram("trino_tpu_device_pull_ms").observe(
                pull_ms
            )
            flag_vals = extra_vals[: len(deferred)]
            counter_vals = list(extra_vals[len(deferred):])
            overflowed = False
            for (key, names, _, caps), seg in zip(deferred, flag_vals):
                seg = np.atleast_1d(np.asarray(seg))
                for nm, fl in zip(names, seg):
                    if fl:
                        overflowed = True
                        grow_or_raise(nm, caps)
            if not overflowed:
                for names, stacked, static in dcounters:
                    vals = (
                        np.atleast_1d(np.asarray(counter_vals.pop(0)))
                        if stacked is not None
                        else ()
                    )
                    self._accumulate_exchange(names, vals, static)
                break
            self.exchange_stats["overflow_retries"] += 1
        self.exchange_stats["batchedQueries"] = kreq
        outs = [b.compact() for b in host_batches[:kreq]]
        names = names_holder.get(sub.fragment.id) or [
            s.name for s in sub.fragment.root.output_symbols
        ]
        return outs, names

    def _demux_batch_to_host(self, roots: list, extras: list):
        """ONE device->host pull for the whole batched dispatch: members
        1..K-1's column arrays, validity lanes, and selection masks (plus
        the deferred overflow/counter extras) ride member 0's packed
        ``Batch.to_host`` transfer; host batches are reassembled per
        member afterward. Returns (host_batches, extra_values)."""
        packed: list = list(extras)
        plan: list[list[bool]] = []  # per tail member: has-valid per column
        for r in roots[1:]:
            spec = []
            for c in r.batch.columns:
                packed.append(c.data)
                if c.valid is not None:
                    packed.append(c.valid)
                spec.append(c.valid is not None)
            packed.append(
                r.batch.sel
                if r.batch.sel is not None
                else r.batch.selection_mask()
            )
            plan.append(spec)
        host_head, vals = roots[0].batch.to_host(extras=packed)
        extra_vals = vals[: len(extras)]
        it = iter(vals[len(extras):])
        out = [host_head]
        for r, spec in zip(roots[1:], plan):
            cols = []
            for c, has_valid in zip(r.batch.columns, spec):
                data = next(it)
                valid = next(it) if has_valid else None
                cols.append(Column(c.type, data, valid, c.dictionary))
            sel = next(it)
            out.append(Batch(cols, r.batch.num_rows, sel))
        return out, extra_vals

    def _run_fragment_batched(
        self,
        frag: PlanFragment,
        K: int,
        pstack: tuple,
        results: dict[int, list[Result]],
        names_holder: dict[int, list[str]],
    ) -> list[Result]:
        span = get_tracer().start_span(
            "fragment_execute", attrs={"stage": frag.id, "batch": K}
        )
        with span:
            inputs: dict[str, Any] = {}
            input_layouts: dict[str, dict[str, int]] = {}
            spill_threshold = (
                int(self.session.get("spill_threshold_rows"))
                if self.session.get("spill_enabled")
                and not self._graceful_overflow()
                else None
            )
            for n in P.walk_plan(frag.root):
                if isinstance(n, P.TableScan):
                    res = self._exec_tablescan(n)
                    if (
                        spill_threshold is not None
                        and res.batch.capacity > spill_threshold
                    ):
                        raise BatchUnsupported("spill-sized input")
                    inputs[f"scan{id(n)}"] = res.batch
                    input_layouts[f"scan{id(n)}"] = res.layout
                elif isinstance(n, P.RemoteSource):
                    rs = results[n.fragment_id]
                    inputs[f"remote{n.fragment_id}"] = tuple(
                        r.batch for r in rs
                    )
                    input_layouts[f"remote{n.fragment_id}"] = rs[0].layout
                elif isinstance(n, P.Output):
                    names_holder[frag.id] = list(n.column_names)
            out = self.run_fragment_program_batched(
                frag, K, pstack, inputs, input_layouts, defer=True
            )
            span.set("mode", "batched")
            return out

    def _run_fused_unit_batched(
        self,
        unit: FusedFragment,
        K: int,
        pstack: tuple,
        results: dict[int, list[Result]],
        names_holder: dict[int, list[str]],
    ) -> list[Result]:
        span = get_tracer().start_span(
            "fused_execute",
            attrs={
                "stage": unit.id,
                "fragments": len(unit.fragments),
                "batch": K,
            },
        )
        with span:
            member_ids = set(unit.fragment_ids)
            inputs: dict[str, Any] = {}
            input_layouts: dict[str, dict[str, int]] = {}
            spill_threshold = (
                int(self.session.get("spill_threshold_rows"))
                if self.session.get("spill_enabled")
                and not self._graceful_overflow()
                else None
            )
            for frag in unit.fragments:
                for n in P.walk_plan(frag.root):
                    if isinstance(n, P.TableScan):
                        res = self._exec_tablescan(n)
                        if (
                            spill_threshold is not None
                            and res.batch.capacity > spill_threshold
                        ):
                            raise BatchUnsupported("spill-sized input")
                        inputs[f"scan{id(n)}"] = res.batch
                        input_layouts[f"scan{id(n)}"] = res.layout
                    elif (
                        isinstance(n, P.RemoteSource)
                        and n.fragment_id not in member_ids
                    ):
                        rs = results[n.fragment_id]
                        inputs[f"remote{n.fragment_id}"] = tuple(
                            r.batch for r in rs
                        )
                        input_layouts[f"remote{n.fragment_id}"] = rs[0].layout
                    elif isinstance(n, P.Output):
                        names_holder[frag.id] = list(n.column_names)
            out = self.run_fused_program_batched(
                unit.fragments, K, pstack, inputs, input_layouts, defer=True
            )
            span.set("mode", "batched-fused")
            get_registry().counter("trino_tpu_fused_programs_total").inc()
            return out

    def run_fragment_program_batched(
        self,
        frag: PlanFragment,
        K: int,
        pstack: tuple,
        inputs: dict[str, Any],
        input_layouts: dict[str, dict[str, int]],
        apply_exchange: bool = True,
        defer: bool = False,
    ) -> list[Result]:
        """K-unrolled variant of :meth:`run_fragment_program`: the build
        closure constructs K copies of the member program inside ONE
        ``jax.jit``, each over its own slice of the stacked parameter
        vector — the same ops as K sequential dispatches (bit-identical
        member results), one XLA program, one dispatch round-trip.
        Capacities are SHARED with the single-query path, so a batch
        benefits from (and feeds) the same overflow ladder."""
        caps = self.programs.setdefault(("caps", frag.id), _Caps())
        self._seed_history(frag, caps)
        self._seed_caps(frag, caps)
        inputs = dict(inputs)
        inputs["__params__"] = pstack

        def build(meta: _Meta):
            def fn(inp: dict[str, Any]):
                summary = _BatchSummary()
                data, sels = [], []
                res = None
                for k in range(K):
                    tracer = _FragmentTracer(
                        self, _member_inputs(inp, k), input_layouts, caps
                    )
                    res = tracer._exec(frag.root)
                    if apply_exchange:
                        res = tracer.apply_output_exchange(frag, res)
                    summary.absorb(tracer)
                    data.append(
                        tuple((c.data, c.valid) for c in res.batch.columns)
                    )
                    sels.append(res.batch.selection_mask())
                summary.exchange_static["dispatchRoundTrips"] = 1
                meta.capture(res, summary)
                meta.batch_size = K
                return (
                    tuple(data),
                    tuple(sels),
                    tuple(f for _, f in summary.overflows),
                    tuple(c for _, c in summary.counters),
                    (),
                )

            return fn

        return self._retry_traced(
            caps,
            build,
            (inputs,),
            input_rows=sum(
                b.capacity for b in inputs.values() if isinstance(b, Batch)
            ),
            # 5-tuple keys bypass _store_program's stale-root eviction:
            # batching disables dynamic filtering, so frag.root is the
            # stable original and its id never churns
            program_key=(
                "bfrag", frag.id, K, apply_exchange, id(frag.root)
            ),
            defer=defer,
        )

    def run_fused_program_batched(
        self,
        frags: Sequence[PlanFragment],
        K: int,
        pstack: tuple,
        inputs: dict[str, Any],
        input_layouts: dict[str, dict[str, int]],
        apply_exchange: bool = True,
        defer: bool = False,
    ) -> list[Result]:
        """K-unrolled :meth:`run_fused_program`: each member's whole
        fragment CHAIN (interior exchanges as in-jit collectives) unrolls
        K times inside one program. Skew detection/salting is off under
        batching — the hot-set handoff would couple members — so
        exchanges run the plain two-tier (cold+spill) routing."""
        frags = list(frags)
        fids = tuple(f.id for f in frags)
        caps = self.programs.setdefault(("caps", "fused", fids), _Caps())
        for f in frags:
            self._seed_history(f, caps)
            self._seed_caps(f, caps)
        inputs = dict(inputs)
        inputs["__params__"] = pstack

        def build(meta: _Meta):
            def fn(inp: dict[str, Any]):
                summary = _BatchSummary()
                data, sels = [], []
                res = None
                for k in range(K):
                    avail = _member_inputs(inp, k)
                    layouts = dict(input_layouts)
                    member = _TracerSummary()
                    for frag in frags:
                        last = frag is frags[-1]
                        tracer = _FragmentTracer(
                            self, avail, layouts, caps
                        )
                        res = tracer._exec(frag.root)
                        if not last or apply_exchange:
                            res = tracer.apply_output_exchange(frag, res)
                        member.absorb(tracer)
                        if not last:
                            avail = dict(avail)
                            layouts = dict(layouts)
                            avail[f"remote{frag.id}"] = res.batch
                            layouts[f"remote{frag.id}"] = res.layout
                    summary.absorb(member)
                    data.append(
                        tuple((c.data, c.valid) for c in res.batch.columns)
                    )
                    sels.append(res.batch.selection_mask())
                summary.exchange_static["dispatchRoundTrips"] = 1
                summary.exchange_static["fusedFragments"] = len(frags)
                meta.capture(res, summary)
                meta.batch_size = K
                return (
                    tuple(data),
                    tuple(sels),
                    tuple(f for _, f in summary.overflows),
                    tuple(c for _, c in summary.counters),
                    (),
                )

            return fn

        return self._retry_traced(
            caps,
            build,
            (inputs,),
            input_rows=sum(
                b.capacity for b in inputs.values() if isinstance(b, Batch)
            ),
            program_key=(
                "bfused",
                fids,
                K,
                apply_exchange,
                tuple(id(f.root) for f in frags),
            ),
            defer=defer,
        )


def _member_inputs(inp: dict, k: int) -> dict:
    """Member k's view of a batched program's inputs: shared scans pass
    through, per-member tuples (remote feeds, the stacked ``__params__``
    vector) slice at k — exactly the inputs dict a sequential run of
    member k would see, as traced values."""
    mi: dict = {}
    for key, v in inp.items():
        if key == "__params__":
            mi[key] = tuple(a[k] for a in v)
        elif isinstance(v, tuple):
            mi[key] = v[k]
        else:
            mi[key] = v
    return mi


def _dup_key_rows(keys, sel):
    """Boolean per-row flags: row's full key appears on MORE than one
    selected row. Sort-based (scatter-free): one narrow bit-packed sort
    (ops/keypack.py) puts equal keys adjacent; neighbors with equal keys
    are duplicates; a scatter-free inverse-permutation sort restores
    original row order."""
    from trino_tpu.ops import keypack as KP

    n = sel.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    eq_lanes, perm, s_sel = KP.grouping_sort(keys, sel, n)
    same_prev = idx > 0  # first sorted row has no predecessor
    for k in eq_lanes:
        prev = jnp.concatenate([k[:1], k[:-1]])
        same_prev = same_prev & (k == prev)
    same_prev = same_prev & s_sel
    same_next = jnp.concatenate([same_prev[1:], jnp.zeros(1, jnp.bool_)])
    dup_sorted = (same_prev | same_next) & s_sel
    return KP.inverse_permute_mask(perm, dup_sorted)


class _OptPack:
    """Unpacker for flat shard_map operand lists built by
    :func:`pack_opt_pairs` (optional validity lanes are simply absent)."""

    def __init__(self, has_kv, input_kinds):
        self.has_kv = has_kv
        self.input_kinds = input_kinds

    def unpack(self, ops):
        i = 0
        lkeys = []
        for hk in self.has_kv:
            kd = ops[i]
            i += 1
            kv = None
            if hk:
                kv = ops[i]
                i += 1
            lkeys.append((kd, kv))
        lsel = ops[i]
        i += 1
        linputs = []
        for kind in self.input_kinds:
            if kind == "none":
                linputs.append(None)
            elif kind == "data":
                linputs.append((ops[i], None))
                i += 1
            else:
                linputs.append((ops[i], ops[i + 1]))
                i += 2
        return lkeys, lsel, linputs, i


def pack_opt_pairs(keys, sel, agg_inputs):
    """Flatten (key pairs, selection, agg-input pairs) into shard_map
    operands, omitting None validity lanes (columns with no nulls cost
    zero extra sort passes downstream)."""
    flat = []
    has_kv = []
    for kd, kv in keys:
        flat.append(kd)
        has_kv.append(kv is not None)
        if kv is not None:
            flat.append(kv)
    flat.append(sel)
    kinds = []
    for p in agg_inputs:
        if p is None:
            kinds.append("none")
        elif p[1] is None:
            kinds.append("data")
            flat.append(p[0])
        else:
            kinds.append("data+valid")
            flat.extend([p[0], p[1]])
    return flat, _OptPack(has_kv, kinds)


class _FragmentTracer(DistributedExecutor):
    """Pure-traceable execution of one fragment's node chain. Instances are
    created inside ``jax.jit``; every method avoids host synchronization —
    capacities come from the shared :class:`_Caps`, and data-dependent
    overflow is reported via traced flags instead of host retries."""

    def __init__(
        self,
        base: DistributedExecutor,
        inputs,
        input_layouts,
        caps,
        skew: Optional[dict] = None,
    ):
        super().__init__(base.catalogs, base.session, base.mesh, memory_ctx=None)
        self._inputs = inputs
        self._input_layouts = input_layouts
        # traced parameter vector (hoisted plan literals); the inherited
        # ExprCompiler call sites read it via getattr(self, "_params")
        self._params = (
            inputs.get("__params__") if isinstance(inputs, dict) else None
        )
        self.caps = caps
        self.skew = skew or {}
        self.overflows: list[tuple[str, jax.Array]] = []
        # exchange observability: traced int64 scalars pulled with the
        # overflow flags, plus statically-known wire-slot accounting
        self.counters: list[tuple[str, jax.Array]] = []
        self.exchange_static: dict[str, int] = {}
        # replicated hot-key tables exported for the peer build exchange
        self.aux_out: tuple = ()
        self._memo: dict[int, Result] = {}
        # operator telemetry: per-node traced row counts appended to the
        # shared counter channel (pulled with the overflow flags — zero
        # extra host round trips). Off -> no extra ops traced at all.
        self._op_enabled = bool(base.session.get("operator_stats"))
        self._op_rowcounts: dict[int, jax.Array] = {}

    @property
    def n(self) -> int:
        return self.mesh.devices.size

    def _exec(self, node: P.PlanNode) -> Result:
        key = id(node)
        if key not in self._memo:
            self._memo[key] = self._dispatch(node)
            self._op_count(node)
        return self._memo[key]

    def _dispatch(self, node: P.PlanNode) -> Result:
        method = getattr(self, f"_exec_{type(node).__name__.lower()}", None)
        if method is None:
            raise FusedUnsupported(type(node).__name__)
        return method(node)

    # --- operator telemetry (op! counter channel) -----------------------

    def _op_rows(self, node: P.PlanNode) -> jax.Array:
        """Traced selected-row count of a memoized node result, computed
        once per node regardless of how many parents (or the in/out pair)
        reference it."""
        key = id(node)
        r = self._op_rowcounts.get(key)
        if r is None:
            sel = self._memo[key].batch.selection_mask()
            r = jnp.sum(sel.astype(jnp.int64))
            self._op_rowcounts[key] = r
        return r

    def _op_count(self, node: P.PlanNode) -> None:
        """Mint per-operator input/output row counters for the just-memoized
        node. Counters ride the existing deferred pull: per-shard partial
        sums are pure reductions XLA folds into the program, so results
        stay bit-identical with telemetry on or off and no new D2H round
        trip is issued. Site names resolve at trace time via the _Caps
        site map (always registered by _seed_history), so deferred
        accumulation needs no capture context."""
        if not self._op_enabled:
            return
        if isinstance(node, P.Aggregate):
            kind = {
                "partial": "partial-agg",
                "final": "final-agg",
            }.get(node.step, "agg")
            site = self.caps.sites.get(f"agg{id(node)}")
        elif isinstance(node, P.Join):
            kind = "semijoin" if node.join_type in ("SEMI", "ANTI") else "join"
            site = self.caps.sites.get(f"join{id(node)}")
        elif isinstance(node, P.TableScan):
            kind, site = "scan", self.caps.sites.get(f"opscan{id(node)}")
        elif isinstance(node, P.Filter):
            kind, site = "filter", self.caps.sites.get(f"opfilter{id(node)}")
        else:
            return
        if site is None:
            return  # node not registered (e.g. synthetic rewrite artifact)
        sources = [] if isinstance(node, P.TableScan) else list(node.sources)
        if sources and all(id(s) in self._memo for s in sources):
            rows_in = self._op_rows(sources[0])
            for s in sources[1:]:
                rows_in = rows_in + self._op_rows(s)
        else:
            # leaves count their own batch as input (scan in == out)
            rows_in = self._op_rows(node)
        self.counters.append((f"op!{kind}!in!{site}", rows_in))
        self.counters.append((f"op!{kind}!out!{site}", self._op_rows(node)))

    # --- leaves ---------------------------------------------------------

    def _exec_tablescan(self, node: P.TableScan) -> Result:
        batch = self._inputs[f"scan{id(node)}"]
        return Result(batch, dict(self._input_layouts[f"scan{id(node)}"]))

    def _exec_remotesource(self, node: P.RemoteSource) -> Result:
        batch = self._inputs[f"remote{node.fragment_id}"]
        layout = dict(self._input_layouts[f"remote{node.fragment_id}"])
        # rename producer symbols -> this node's symbols (same order)
        producer_order = sorted(layout, key=layout.get)
        if len(producer_order) != len(node.symbols):
            raise FusedUnsupported("remote source arity mismatch")
        new_layout = {
            s.name: layout[p] for s, p in zip(node.symbols, producer_order)
        }
        return Result(batch, new_layout)

    # --- output / row-preserving ---------------------------------------

    def _exec_output(self, node: P.Output) -> Result:
        res = self._exec(node.source)
        cols = [res.column(s) for s in node.symbols]
        layout = {s.name: i for i, s in enumerate(node.symbols)}
        return Result(Batch(cols, res.batch.num_rows, res.batch.sel), layout)

    # _exec_filter / _exec_project inherited (already traceable)

    def _exec_limit(self, node: P.Limit) -> Result:
        res = self._exec(node.source)
        sel = res.batch.selection_mask()
        from trino_tpu.ops.aggregation import _prefix_sum
        rank = _prefix_sum(sel.astype(jnp.int32))
        keep = sel
        if node.offset:
            keep = keep & (rank > node.offset)
        if node.count is not None:
            keep = keep & (rank <= node.offset + node.count)
        return Result(
            Batch(res.batch.columns, res.batch.num_rows, keep), res.layout
        )

    def _exec_sort(self, node: P.Sort) -> Result:
        return self._traced_sort(self._exec(node.source), node.order_by, None)

    def _exec_topn(self, node: P.TopN) -> Result:
        res = self._exec(node.source)
        if node.step == "partial":
            return self._partial_topn(res, node)
        return self._traced_sort(res, node.order_by, node.count)

    def _sort_operands(self, res: Result, order_by):
        key_pairs, keys, ranks = [], [], []
        for o in order_by:
            c = res.column(o.symbol)
            key_pairs.append((c.data, c.valid_mask()))
            keys.append(o.sort_key())
            ranks.append(c.dictionary.ranks() if c.dictionary is not None else None)
        return key_pairs, keys, ranks

    def _traced_sort(
        self, res: Result, order_by, keep: Optional[int]
    ) -> Result:
        b = res.batch
        key_pairs, keys, ranks = self._sort_operands(res, order_by)
        sel = b.selection_mask()
        perm = sort_indices(key_pairs, keys, sel, ranks)
        if keep is not None:
            perm = perm[: min(keep, b.capacity)]
        cols = []
        for c in b.columns:
            cols.append(
                Column(c.type, c.data[perm], c.valid_mask()[perm], c.dictionary)
            )
        out_sel = sel[perm]
        return Result(Batch(cols, perm.shape[0], out_sel), res.layout)

    def _partial_topn(self, res: Result, node: P.TopN) -> Result:
        """Per-shard topN: each shard keeps its own best `count` rows
        (reference: TopNNode PARTIAL)."""
        b = res.batch
        key_pairs, keys, ranks = self._sort_operands(res, node.order_by)
        sel = b.selection_mask()
        keep = min(node.count, max(1, b.capacity // self.n))
        flat = []
        for c in b.columns:
            flat.append(c.data)
            flat.append(c.valid_mask())
        for kd, kv in key_pairs:
            flat.append(kd)
            flat.append(kv)
        flat.append(sel)
        ncols = len(b.columns)
        nkeys = len(key_pairs)

        def shard_topn(*ops):
            cols_ = ops[: 2 * ncols]
            kp = [
                (ops[2 * ncols + 2 * i], ops[2 * ncols + 2 * i + 1])
                for i in range(nkeys)
            ]
            s = ops[-1]
            perm = sort_indices(kp, keys, s, ranks)[:keep]
            outs = [c[perm] for c in cols_]
            return tuple(outs), s[perm]

        mapped = smap(
            shard_topn,
            mesh=self.mesh,
            in_specs=(PS(AXIS),) * len(flat),
            out_specs=(tuple(PS(AXIS) for _ in range(2 * ncols)), PS(AXIS)),
        )
        outs, out_sel = mapped(*flat)
        cols = []
        for i, c in enumerate(b.columns):
            cols.append(Column(c.type, outs[2 * i], outs[2 * i + 1], c.dictionary))
        return Result(Batch(cols, self.n * keep, out_sel), res.layout)

    # --- aggregation -----------------------------------------------------

    def _exec_aggregate(self, node: P.Aggregate) -> Result:
        res = self._exec(node.source)
        if node.step == "partial":
            return self._agg_partial(node, res)
        if node.step == "final":
            return self._agg_final(node, res)
        return self._agg_single(node, res)

    def _agg_inputs(self, node: P.Aggregate, res: Result,
                    distinct_keys=None, distinct_sel=None):
        """Traceable version of the interpreter's aggregate input prep.
        ``distinct_keys``/``distinct_sel`` enable DISTINCT dedup (single
        step only — the fragmenter gathers distinct aggregations)."""
        agg_inputs, specs, string_dicts = [], [], []
        for _, fn in node.aggregates:
            if fn.distinct and distinct_keys is None:
                raise FusedUnsupported("distinct aggregate outside single step")
            if fn.kind == "count_star":
                if fn.filter is not None:
                    fc = res.column(P.Symbol(fn.filter.name, T.BOOLEAN))
                    ones = jnp.ones(res.batch.capacity, dtype=jnp.int64)
                    agg_inputs.append((ones, fc.data & fc.valid_mask()))
                    specs.append(AggSpec("count"))
                    string_dicts.append(None)
                    continue
                agg_inputs.append(None)
                specs.append(AggSpec("count_star"))
                string_dicts.append(None)
                continue
            sym = P.Symbol(fn.argument.name, fn.argument.type)
            c = res.column(sym)
            data, valid = c.data, c.valid  # None valid = no nulls (cheaper)
            if c.dictionary is not None and fn.kind in ("min", "max"):
                data = rank_codes(c.dictionary, data)
                string_dicts.append(c.dictionary)
            else:
                string_dicts.append(None)
            if fn.filter is not None:
                fc = res.column(P.Symbol(fn.filter.name, T.BOOLEAN))
                fmask = fc.data & fc.valid_mask()
                valid = fmask if valid is None else (valid & fmask)
            if fn.distinct:
                # DISTINCT: only the first occurrence of each
                # (group keys, value) pair contributes (reference:
                # MarkDistinctOperator / distinct accumulators)
                from trino_tpu.ops.aggregation import distinct_first_mask

                vmask = (
                    distinct_sel
                    if valid is None
                    else (valid & distinct_sel)
                )
                first = distinct_first_mask(
                    distinct_keys, (data, c.valid_mask()), vmask
                )
                valid = first if valid is None else (valid & first)
            agg_inputs.append((data, valid))
            specs.append(sum_spec_for(fn, data))
        return agg_inputs, specs, string_dicts

    def _agg_partial(self, node: P.Aggregate, res: Result) -> Result:
        """Per-shard partial aggregation -> accumulator rows (sharded)."""
        sel = res.batch.selection_mask()
        agg_inputs, specs, string_dicts = self._agg_inputs(node, res)
        key_cols = [res.column(k) for k in node.group_keys]
        keys = [(c.data, c.valid) for c in key_cols]
        nkeys = len(keys)
        if nkeys == 0:
            return self._agg_partial_global(node, res, sel, agg_inputs, specs, string_dicts)
        G = self.caps.get(f"agg{id(node)}", 1 << 12)

        flat, pack = pack_opt_pairs(keys, sel, agg_inputs)

        def shard_partial(*ops):
            lkeys, lsel, linputs, _ = pack.unpack(ops)
            (kd, kv), raw, ng, ovf = group_aggregate(lkeys, lsel, linputs, specs, G)
            vals, cnts = [], []
            for spec, r in zip(specs, raw):
                if spec.kind in ("count", "count_star"):
                    vals.append(r.astype(jnp.int64))
                    cnts.append(None)
                else:
                    vals.append(r[0])
                    cnts.append(r[1])
            live = jnp.arange(G) < ng
            outs = []
            for i2 in range(nkeys):
                outs.extend([kd[i2], kv[i2]])
            for v, c in zip(vals, cnts):
                outs.append(v)
                if c is not None:
                    outs.append(c)
            ovf_any = jax.lax.pmax(ovf.astype(jnp.int32), AXIS)
            return tuple(outs), live, ovf_any

        # outputs: keys*2 + per agg (1 for count kinds, else value+count)
        n_out = 2 * nkeys + sum(
            1 if s.kind in ("count", "count_star") else 2 for s in specs
        )
        mapped = smap(
            shard_partial,
            mesh=self.mesh,
            in_specs=(PS(AXIS),) * len(flat),
            out_specs=(tuple(PS(AXIS) for _ in range(n_out)), PS(AXIS), PS()),
        )
        outs, live, ovf = mapped(*flat)
        self.overflows.append((f"agg{id(node)}", ovf))

        # assemble accumulator Result
        cols: list[Column] = []
        layout: dict[str, int] = {}
        i = 0
        for ksym, kc in zip(node.group_keys, key_cols):
            data = outs[i].astype(ksym.type.storage_dtype)
            cols.append(Column(ksym.type, data, outs[i + 1], kc.dictionary))
            layout[ksym.name] = len(cols) - 1
            i += 2
        for (vsym, csym), spec, sdict in zip(node.acc_symbols, specs, string_dicts):
            if spec.kind in ("count", "count_star"):
                cols.append(Column(T.BIGINT, outs[i].astype(np.int64), None))
                layout[vsym.name] = len(cols) - 1
                i += 1
            else:
                val = outs[i]
                if getattr(val, "ndim", 1) == 2:
                    # 128-bit limb sums -> wide (hi, lo) acc column
                    from trino_tpu.ops import decimal128 as D128

                    hi, lo = D128.limb_sums_to_pair(val)
                    val = jnp.stack([hi, lo], axis=1)
                elif sdict is not None:
                    # string min/max: convert the winning rank back to a
                    # CODE — the accumulator wire representation is codes
                    # (ranks are dictionary-local, codes travel with it)
                    order = np.argsort(sdict.ranks(), kind="stable")
                    if len(order):
                        val = jnp.asarray(order)[
                            jnp.clip(val, 0, len(order) - 1)
                        ].astype(jnp.int32)
                    else:
                        val = jnp.full(val.shape, -1, dtype=jnp.int32)
                cols.append(Column(vsym.type, val, None, sdict))
                layout[vsym.name] = len(cols) - 1
                i += 1
                cols.append(Column(T.BIGINT, outs[i].astype(np.int64), None))
                layout[csym.name] = len(cols) - 1
                i += 1
        return Result(Batch(cols, cols[0].data.shape[0], live), layout)

    def _agg_partial_global(
        self, node, res, sel, agg_inputs, specs, string_dicts
    ) -> Result:
        """Global (ungrouped) partial: one accumulator row per shard."""

        flat, pack = pack_opt_pairs([], sel, agg_inputs)

        def shard_partial(*ops):
            _, lsel, linputs, _ = pack.unpack(ops)
            raw = global_aggregate(lsel, linputs, specs)
            outs = []
            for spec, r in zip(specs, raw):
                if spec.kind in ("count", "count_star"):
                    outs.append(r.astype(jnp.int64)[None])
                else:
                    v = r[0]
                    # limb-sum matrices (sum128*) are already (1, k)
                    outs.append(v if getattr(v, "ndim", 0) == 2 else v[None])
                    outs.append(r[1].astype(jnp.int64)[None])
            return tuple(outs)

        n_out = sum(1 if s.kind in ("count", "count_star") else 2 for s in specs)
        mapped = smap(
            shard_partial,
            mesh=self.mesh,
            in_specs=(PS(AXIS),) * len(flat),
            out_specs=tuple(PS(AXIS) for _ in range(n_out)),
        )
        outs = mapped(*flat)
        cols: list[Column] = []
        layout: dict[str, int] = {}
        i = 0
        for (vsym, csym), spec, sdict in zip(node.acc_symbols, specs, string_dicts):
            if spec.kind in ("count", "count_star"):
                cols.append(Column(T.BIGINT, outs[i].astype(np.int64), None))
                layout[vsym.name] = len(cols) - 1
                i += 1
            else:
                val = outs[i]
                if getattr(val, "ndim", 1) == 2:
                    from trino_tpu.ops import decimal128 as D128

                    hi, lo = D128.limb_sums_to_pair(val)
                    val = jnp.stack([hi, lo], axis=1)
                elif sdict is not None:
                    order = np.argsort(sdict.ranks(), kind="stable")
                    if len(order):
                        val = jnp.asarray(order)[
                            jnp.clip(val, 0, len(order) - 1)
                        ].astype(jnp.int32)
                    else:
                        val = jnp.full(val.shape, -1, dtype=jnp.int32)
                cols.append(Column(vsym.type, val, None, sdict))
                layout[vsym.name] = len(cols) - 1
                i += 1
                cols.append(Column(T.BIGINT, outs[i].astype(np.int64), None))
                layout[csym.name] = len(cols) - 1
                i += 1
        n_rows = self.n
        return Result(
            Batch(cols, n_rows, jnp.ones(n_rows, dtype=jnp.bool_)), layout
        )

    def _agg_final(self, node: P.Aggregate, res: Result) -> Result:
        """Combine accumulator rows (reference: AggregationNode FINAL +
        the aggregation combine function)."""
        sel = res.batch.selection_mask()
        combine_inputs: list = []
        combine_specs: list[AggSpec] = []
        acc_cols = []
        for (vsym, csym), (_, fn) in zip(node.acc_symbols, node.aggregates):
            vcol = res.column(vsym)
            acc_cols.append(vcol)
            if fn.kind in ("count", "count_star"):
                combine_inputs.append((vcol.data, jnp.ones_like(sel)))
                combine_specs.append(AggSpec("sum"))
            else:
                ccol = res.column(csym)
                nonempty = ccol.data > 0
                vdata = vcol.data
                if vcol.dictionary is not None and fn.kind in ("min", "max"):
                    # accumulator codes -> local ranks for order combining
                    vdata = rank_codes(vcol.dictionary, vdata)
                    nonempty = nonempty & (vcol.data >= 0)
                combine_inputs.append((vdata, nonempty))
                if fn.kind in ("sum", "avg"):
                    from trino_tpu.ops.decimal128 import is_wide_data

                    combine_specs.append(
                        AggSpec("sum128w" if is_wide_data(vdata) else "sum")
                    )
                else:
                    combine_specs.append(AggSpec(fn.kind))
                combine_inputs.append((ccol.data, jnp.ones_like(sel)))
                combine_specs.append(AggSpec("sum"))

        dicts = [c.dictionary for c in acc_cols]
        if not node.group_keys:
            raw = global_aggregate(sel, combine_inputs, combine_specs)
            results = self._fold_combined(node, raw)
            cols = self._finalize_traced(node, results, dicts, 1)
            return Result(
                Batch(cols, 1, jnp.ones(1, dtype=jnp.bool_)),
                {s.name: i for i, s in enumerate(node.output_symbols)},
            )

        key_cols = [res.column(k) for k in node.group_keys]
        keys = [(c.data, c.valid_mask()) for c in key_cols]
        nkeys = len(keys)
        G = self.caps.get(f"agg{id(node)}", 1 << 12)

        flat = []
        for kd, kv in keys:
            flat.extend([kd, kv])
        flat.append(sel)
        for d, v in combine_inputs:
            flat.extend([d, v])

        def shard_combine(*ops):
            i = 0
            lkeys = []
            for _ in range(nkeys):
                lkeys.append((ops[i], ops[i + 1]))
                i += 2
            lsel = ops[i]
            i += 1
            linputs = []
            for _ in combine_specs:
                linputs.append((ops[i], ops[i + 1]))
                i += 2
            (kd, kv), raw, ng, ovf = group_aggregate(
                lkeys, lsel, linputs, combine_specs, G
            )
            live = jnp.arange(G) < ng
            outs = []
            for i2 in range(nkeys):
                outs.extend([kd[i2], kv[i2]])
            for r in raw:
                outs.append(r[0])  # all combine kinds return (value, cnt)
            ovf_any = jax.lax.pmax(ovf.astype(jnp.int32), AXIS)
            return tuple(outs), live, ovf_any

        n_out = 2 * nkeys + len(combine_specs)
        mapped = smap(
            shard_combine,
            mesh=self.mesh,
            in_specs=(PS(AXIS),) * len(flat),
            out_specs=(tuple(PS(AXIS) for _ in range(n_out)), PS(AXIS), PS()),
        )
        outs, live, ovf = mapped(*flat)
        self.overflows.append((f"agg{id(node)}", ovf))

        i = 0
        cols: list[Column] = []
        for ksym, kc in zip(node.group_keys, key_cols):
            data = outs[i].astype(ksym.type.storage_dtype)
            cols.append(Column(ksym.type, data, outs[i + 1], kc.dictionary))
            i += 2
        combined = outs[i:]
        results = self._fold_combined(node, list(combined))
        total = cols[0].data.shape[0] if cols else combined[0].shape[0]
        cols.extend(self._finalize_traced(node, results, dicts, total))
        return Result(
            Batch(cols, total, live),
            {s.name: i2 for i2, s in enumerate(node.output_symbols)},
        )

    def _fold_combined(self, node: P.Aggregate, raw):
        """Fold the combine outputs back to per-aggregate (value, count).
        ``raw`` entries are either plain arrays (per-shard path) or
        ``(value, count)`` tuples from :func:`global_aggregate` — take the
        value part either way."""

        def val(x):
            return x[0] if isinstance(x, tuple) else x

        results = []
        j = 0
        for _, fn in node.aggregates:
            if fn.kind in ("count", "count_star"):
                results.append(val(raw[j]))
                j += 1
            else:
                results.append((val(raw[j]), val(raw[j + 1])))
                j += 2
        return results

    def _agg_single(self, node: P.Aggregate, res: Result) -> Result:
        sel = res.batch.selection_mask()
        dkeys = [res.pair(k) for k in node.group_keys]
        agg_inputs, specs, string_dicts = self._agg_inputs(
            node, res, distinct_keys=dkeys, distinct_sel=sel
        )
        if not node.group_keys:
            raw = global_aggregate(sel, agg_inputs, specs)
            cols = self._finalize_traced(node, raw, string_dicts, 1)
            return Result(
                Batch(cols, 1, jnp.ones(1, dtype=jnp.bool_)),
                {s.name: i for i, s in enumerate(node.output_symbols)},
            )
        keys = [res.opt_pair(k) for k in node.group_keys]
        key_cols = [res.column(k) for k in node.group_keys]
        G = self.caps.get(f"agg{id(node)}", 1 << 12)
        (kd, kv), raw, ng, ovf = group_aggregate(keys, sel, agg_inputs, specs, G)
        self.overflows.append((f"agg{id(node)}", ovf.astype(jnp.int32)))
        live = jnp.arange(G) < ng
        cols = []
        for i, (ksym, kc) in enumerate(zip(node.group_keys, key_cols)):
            cols.append(
                Column(
                    ksym.type,
                    kd[i].astype(ksym.type.storage_dtype),
                    kv[i],
                    kc.dictionary,
                )
            )
        cols.extend(self._finalize_traced(node, raw, string_dicts, G))
        return Result(
            Batch(cols, G, live),
            {s.name: i for i, s in enumerate(node.output_symbols)},
        )

    def _finalize_traced(self, node, results, dicts, n) -> list[Column]:
        """Traceable _finalize_aggs: avg division, NULL-on-empty, string
        min/max rank->code mapping."""
        cols = []
        for (sym, fn), raw, sdict in zip(node.aggregates, results, dicts):
            t = fn.result_type
            if fn.kind in ("count", "count_star"):
                data = jnp.reshape(raw, (-1,)).astype(jnp.int64)
                cols.append(Column(t, data, None))
                continue
            ssum, cnt = raw
            if getattr(ssum, "ndim", 1) == 2 and ssum.shape[1] in (3, 5):
                # limb sums -> wide (hi, lo) lanes, in-program
                from trino_tpu.ops import decimal128 as D128

                hi, lo = D128.limb_sums_to_pair(ssum)
                ssum = jnp.stack([hi, lo], axis=1)
            if getattr(ssum, "ndim", 1) == 2 and ssum.shape[1] == 2:
                cnt = jnp.reshape(cnt, (-1,))
                valid = cnt > 0
                if fn.kind == "avg":
                    from trino_tpu.ops.decimal128 import (
                        div128_round,
                        widen_i64,
                    )

                    chi, clo = widen_i64(jnp.maximum(cnt, 1))
                    qhi, qlo, _ok = div128_round(
                        ssum[:, 0], ssum[:, 1], chi, clo, 0
                    )
                    if isinstance(t, T.DecimalType) and t.wide:
                        cols.append(
                            Column(t, jnp.stack([qhi, qlo], axis=1), valid)
                        )
                    else:
                        cols.append(Column(t, qlo.astype(t.storage_dtype), valid))
                    continue
                if fn.kind not in ("sum", "min", "max"):
                    raise FusedUnsupported(f"wide decimal {fn.kind}")
                cols.append(Column(t, ssum, valid))
                continue
            ssum = jnp.reshape(ssum, (-1,))
            cnt = jnp.reshape(cnt, (-1,))
            valid = cnt > 0
            if fn.kind == "sum":
                cols.append(Column(t, ssum.astype(t.storage_dtype), valid))
            elif fn.kind == "avg":
                safe = jnp.maximum(cnt, 1)
                if isinstance(t, T.DecimalType):
                    data = jnp.where(
                        ssum >= 0,
                        (ssum + safe // 2) // safe,
                        -((-ssum + safe // 2) // safe),
                    ).astype(jnp.int64)
                else:
                    data = (ssum / safe).astype(t.storage_dtype)
                cols.append(Column(t, data, valid))
            else:  # min / max
                if sdict is not None:
                    order = np.argsort(sdict.ranks(), kind="stable")
                    data = jnp.asarray(order)[
                        jnp.clip(ssum, 0, len(order) - 1)
                    ].astype(jnp.int32)
                    cols.append(Column(t, data, valid, sdict))
                else:
                    cols.append(Column(t, ssum.astype(t.storage_dtype), valid))
        return cols

    # --- joins -----------------------------------------------------------

    def _join_strategy(self, node: P.Join, lkeys) -> str:
        """Pick the join kernel for one Join node (ops/dense_join.py
        module doc).  ``sort`` is the PR-0 bitonic path; ``dense`` the
        open-addressing table; ``matmul`` the identity-binned table for
        densely-binning single integer keys.  The auto→matmul promotion
        is a cost gate seeded from PR-15 history: a history-seeded
        ``densejoin`` capacity within the domain bound proves an earlier
        run's observed table fit a dense domain — static stats cannot
        prove that cold, and a sparse 64-bit key domain would walk the
        whole retry ladder before demoting.  Sites the ladder demoted
        (duplicate chains beyond the probe window) are pinned to sort."""
        if not bool(self.session.get("dense_join")):
            return "sort"
        site = f"densejoin{id(node)}"
        # demotions are recorded under the restart-stable alias (node
        # ids churn across retraces); the alias map is registered by
        # _seed_history before any node of this fragment traces
        if self.caps.sites.get(site, site) in self.caps.demoted:
            return "sort"
        pref = str(self.session.get("join_strategy") or "auto").lower()
        if pref == "sort":
            return "sort"
        matmul_ok = len(lkeys) == 1 and jnp.issubdtype(
            lkeys[0][0].dtype, jnp.integer
        )
        if pref == "matmul":
            return "matmul" if matmul_ok else "dense"
        if pref != "dense" and matmul_ok:
            seeded = self.caps.seeded(site)
            bound = int(self.session.get("matmul_join_max_domain"))
            if (
                seeded is not None
                and seeded[1].startswith("history")
                and 0 < seeded[0] <= bound
            ):
                return "matmul"
        return "dense"

    def _exec_join(self, node: P.Join) -> Result:
        if node.join_type in ("SEMI", "ANTI"):
            return self._exec_semi_join_traced(node)
        if node.join_type == "CROSS" and node.single_row:
            return self._exec_scalar_cross_traced(node)
        if node.join_type not in ("INNER", "LEFT") or not node.criteria:
            raise FusedUnsupported(f"join {node.join_type}")
        right = self._exec(node.right)
        left = self._exec(node.left)
        lkeys, rkeys = self._join_keys(left, right, node.criteria)
        if node.single_row:
            # correlated scalar subquery (EnforceSingleRowNode analog):
            # any build-key group with >1 selected rows that a probe row
            # actually joins is a runtime error. The dup flag rides the
            # probe as a synthetic build column so unmatched dup groups
            # (which the reference tolerates) don't fire.
            dup = _dup_key_rows(rkeys, right.batch.selection_mask())
            self._single_row_dup = dup  # consumed below via build columns
        ph, _pv = J.hash_keys(lkeys)
        bh, _bv = J.hash_keys(rkeys)
        # per-shard probing needs key-co-partitioned sides, which only a
        # hash exchange guarantees; any other placement (broadcast, single,
        # same-fragment subtree) probes a replicated build — XLA inserts
        # the gather when the value isn't replicated already
        build_sharded = (
            isinstance(node.right, P.RemoteSource)
            and node.right.exchange_type == "hash"
        )
        probe_cols, probe_schema = [], []
        for s in node.left.output_symbols:
            c = left.column(s)
            probe_cols.extend([c.data, c.valid_mask()])
            probe_schema.append((s, c.dictionary))
        build_cols, build_schema = [], []
        for s in node.right.output_symbols:
            c = right.column(s)
            build_cols.extend([c.data, c.valid_mask()])
            build_schema.append((s, c.dictionary))
        if node.single_row:
            # synthetic build lane: gathered per output row, True only
            # when the matched build row's key group had duplicates
            build_cols.extend(
                [self._single_row_dup, jnp.ones_like(self._single_row_dup)]
            )
        probe_keys = []
        for kd, kv in lkeys:
            probe_keys.extend([kd, kv])
        build_keys = []
        for kd, kv in rkeys:
            build_keys.extend([kd, kv])

        probe_cap = left.batch.capacity
        default_cap = bucket_capacity(
            max(1024, 2 * probe_cap // max(self.n, 1))
        )
        cap = self.caps.get(f"join{id(node)}", default_cap)
        strategy = self._join_strategy(node, lkeys)
        self.caps.join_strategies[f"densejoin{id(node)}"] = strategy
        table_cap = None
        if strategy != "sort":
            # table slots per shard: 4x the per-shard build rows (load
            # factor <= 0.25 — linear-probe clusters coalesce past the
            # static window at 0.5); a replicated build holds ALL rows
            build_cap = right.batch.capacity
            per_shard_build = (
                build_cap // max(self.n, 1) if build_sharded else build_cap
            )
            table_cap = self.caps.get(
                f"densejoin{id(node)}",
                bucket_capacity(max(1024, 4 * per_shard_build)),
            )
        res = _sharded_probe(
            self.mesh,
            probe_cols,
            probe_keys,
            ph,
            left.batch.selection_mask(),
            build_cols,
            build_keys,
            bh,
            right.batch.selection_mask(),
            cap,
            node.join_type,
            len(lkeys),  # wide criteria expand into two lane pairs
            build_sharded=build_sharded,
            strategy=strategy,
            table_cap=table_cap,
        )
        if strategy == "sort":
            out_cols, out_sel, ovf = res
        else:
            out_cols, out_sel, ovf, table_ovf = res
            # graceful overflow: the ladder doubles the table site and
            # re-hashes — never the interpreter's partitioned spill
            self.overflows.append((f"densejoin{id(node)}", table_ovf))
        self.overflows.append((f"join{id(node)}", ovf))
        cols: list[Column] = []
        layout: dict[str, int] = {}
        i = 0
        for s, d in probe_schema:
            cols.append(Column(s.type, out_cols[i], out_cols[i + 1], d))
            layout[s.name] = len(cols) - 1
            i += 2
        for s, d in build_schema:
            cols.append(Column(s.type, out_cols[i], out_cols[i + 1], d))
            layout[s.name] = len(cols) - 1
            i += 2
        if node.single_row:
            dup_hit = out_cols[i] & out_cols[i + 1] & out_sel
            self.overflows.append(
                (
                    "err!Scalar sub-query has returned multiple rows",
                    jnp.any(dup_hit),
                )
            )
            i += 2
        total = out_cols[0].shape[0]
        result = Result(Batch(cols, total, out_sel), layout)
        if node.filter is not None:
            from trino_tpu.compiler import ExprCompiler
            from trino_tpu.strings import lower_string_calls

            expr = self._bind(node.filter, result.layout)
            work = list(result.batch.columns)
            expr = lower_string_calls(expr, work)
            mask = ExprCompiler(
                work, params=getattr(self, "_params", None)
            ).predicate_mask(expr)
            result = Result(Batch(result.batch.columns, total, mask & out_sel), layout)
        return result

    def _exec_scalar_cross_traced(self, node: P.Join) -> Result:
        """Uncorrelated scalar subquery (single-row CROSS): broadcast the
        one selected build row into every probe row. Zero rows -> NULL;
        more than one -> runtime error via the err! flag channel
        (reference: ``EnforceSingleRowNode`` semantics)."""
        right = self._exec(node.right)
        left = self._exec(node.left)
        rsel = right.batch.selection_mask()
        cnt = jnp.sum(rsel.astype(jnp.int32))
        self.overflows.append(
            ("err!Scalar sub-query has returned multiple rows", cnt > 1)
        )
        pick = jnp.argmax(rsel)  # index of the selected row (0 if none)
        cap = left.batch.capacity
        cols: list[Column] = []
        layout: dict[str, int] = {}
        for s in node.left.output_symbols:
            c = left.column(s)
            cols.append(c)
            layout[s.name] = len(cols) - 1
        from jax.sharding import NamedSharding

        from trino_tpu.parallel.mesh import AXIS as _AXIS

        row_sh = NamedSharding(self.mesh, PS(_AXIS))
        has_row = cnt >= 1
        for s in node.right.output_symbols:
            c = right.column(s)
            val = c.data[pick]
            # materialized row-sharded arrays (not lazy broadcast views of
            # the replicated build): these columns feed shard_map operands
            # downstream, which need real global row-sharded arrays
            data = jax.lax.with_sharding_constraint(
                jnp.zeros((cap,) + val.shape, dtype=c.data.dtype) + val,
                row_sh,
            )
            valid = jax.lax.with_sharding_constraint(
                jnp.zeros((cap,), dtype=jnp.bool_)
                | (c.valid_mask()[pick] & has_row),
                row_sh,
            )
            cols.append(Column(s.type, data, valid, c.dictionary))
            layout[s.name] = len(cols) - 1
        return Result(
            Batch(cols, left.batch.num_rows, left.batch.sel), layout
        )

    def _exec_semi_join_traced(self, node: P.Join) -> Result:
        """SEMI/ANTI as a traced membership mark: probe key rows carry only
        their global row id through the hash-partitioned lookup, matches
        scatter back into a boolean mark column (reference:
        ``HashSemiJoinOperator.java`` — mark semantics incl. 3-valued IN).
        """
        left = self._exec(node.left)
        right = self._exec(node.right)
        cap = left.batch.capacity
        lsel = left.batch.selection_mask()
        bsel = right.batch.selection_mask()

        if not node.criteria:
            if node.filter is not None:
                raise FusedUnsupported("uncorrelated EXISTS with filter")
            nonempty = bsel.any()
            mark = jnp.broadcast_to(
                nonempty if node.join_type == "SEMI" else ~nonempty, (cap,)
            )
            cols = list(left.batch.columns) + [Column(T.BOOLEAN, mark, None)]
            layout = dict(left.layout)
            layout[node.mark_symbol.name] = len(cols) - 1
            return Result(Batch(cols, cap, left.batch.sel), layout)

        lkeys, rkeys = self._join_keys(left, right, node.criteria)
        ph, _ = J.hash_keys(lkeys)
        bh, bv_all = J.hash_keys(rkeys)
        build_sharded = (
            isinstance(node.right, P.RemoteSource)
            and node.right.exchange_type == "hash"
        )
        row_ids = jnp.arange(cap, dtype=jnp.int64)
        probe_cols = [row_ids, jnp.ones(cap, dtype=jnp.bool_)]
        probe_keys = []
        for kd, kv in lkeys:
            probe_keys.extend([kd, kv])
        build_keys = []
        for kd, kv in rkeys:
            build_keys.extend([kd, kv])
        out_cap = self.caps.get(
            f"semi{id(node)}",
            bucket_capacity(max(1024, 2 * cap // max(self.n, 1))),
        )
        out_cols, out_sel, ovf = _sharded_probe(
            self.mesh,
            probe_cols,
            probe_keys,
            ph,
            lsel,
            [],  # no build payload — membership only
            build_keys,
            bh,
            bsel,
            out_cap,
            "INNER",
            len(lkeys),
            build_sharded=build_sharded,
        )
        self.overflows.append((f"semi{id(node)}", ovf))
        match_ids = out_cols[0]
        matched = (
            jnp.zeros(cap, dtype=jnp.bool_)
            .at[jnp.where(out_sel, match_ids, cap)]
            .set(True, mode="drop")
        )
        # 3-valued IN: NULL probe key (or build NULLs without a match)
        # yields NULL; EXISTS semantics are strict TRUE/FALSE
        pv = jnp.ones(cap, dtype=jnp.bool_)
        for _, kv in lkeys:
            pv = pv & kv
        build_nonempty = bsel.any()
        any_null_build = ((~bv_all) & bsel).any()
        if node.null_aware:
            valid = jnp.where(
                build_nonempty,
                matched | (pv & ~any_null_build),
                jnp.ones(cap, dtype=jnp.bool_),
            )
        else:
            valid = jnp.ones(cap, dtype=jnp.bool_)
        value = matched if node.join_type == "SEMI" else ~matched
        cols = list(left.batch.columns) + [Column(T.BOOLEAN, value, valid)]
        layout = dict(left.layout)
        layout[node.mark_symbol.name] = len(cols) - 1
        return Result(Batch(cols, cap, left.batch.sel), layout)

    # --- output exchange --------------------------------------------------

    def apply_output_exchange(self, frag: PlanFragment, res: Result) -> Result:
        if frag.output_exchange in (None, "single"):
            return res  # SPMD consumers read global arrays directly
        b = res.batch
        sel = b.selection_mask()
        # flatten columns into 1-D lane arrays (wide DECIMAL columns ship
        # as separate hi/lo lanes through the collective kernels)
        arrays = []
        schema = []  # (type, dictionary, n_lanes)
        for c in b.columns:
            if getattr(c.data, "ndim", 1) == 2:
                arrays.extend([c.data[:, 0], c.data[:, 1], c.valid_mask()])
                schema.append((c.type, c.dictionary, 2))
            else:
                arrays.extend([c.data, c.valid_mask()])
                schema.append((c.type, c.dictionary, 1))

        def rebuild(out):
            cols = []
            i = 0
            for t, d, lanes in schema:
                if lanes == 2:
                    data = jnp.stack([out[i], out[i + 1]], axis=1)
                    cols.append(Column(t, data, out[i + 2], d))
                    i += 3
                else:
                    cols.append(Column(t, out[i], out[i + 1], d))
                    i += 2
            return cols

        if frag.output_exchange == "broadcast":
            out, out_sel = X.broadcast_all(self.mesh, arrays, sel)
            cols = rebuild(out)
            self._op_exchange(frag, sel, out_sel)
            return Result(
                Batch(cols, cols[0].data.shape[0], out_sel), res.layout
            )
        # hash: two-tier repartition by output key hash — a small cold
        # bucket per (src,dst) plus a shared spill tier, optionally with a
        # salted hot region for heavy-hitter keys (see exchange.py)
        key_pairs = [res.pair(s) for s in frag.output_keys]
        khash, _ = J.hash_keys(key_pairs)
        n = max(self.n, 1)
        detect = self.skew.get("detect")
        hot_set = (
            self._inputs.get(self.skew.get("hotset_key", "__hotset__"))
            if self.skew.get("salt")
            else None
        )
        salted = detect is not None or hot_set is not None
        # cold tier: ~2x the uniform per-(src,dst) share; when a hot set
        # routes the heavy mass away from the cold path, half that
        per_pair = b.capacity // max(n * n, 1)
        default_bucket = bucket_capacity(
            max(64, per_pair if salted else 2 * per_pair), minimum=64
        )
        bucket = self.caps.get(f"exch{frag.id}", default_bucket)
        spill = self.caps.get(f"spill{frag.id}", max(64, bucket // 2))
        if detect is not None:
            # probe side: detect heavy hitters in-program; hot rows stay
            # on their source shard (zero wire cost), so the hot region
            # is safely sized at the full per-shard row count
            hot_mode = "local"
            hot_cap = self.caps.get(
                f"hot{frag.id}",
                bucket_capacity(max(64, b.capacity // n), minimum=64),
            )
        elif hot_set is not None:
            # build side: replicate just the hot slice (partial
            # broadcast); near-unique build keys make this slice tiny
            hot_mode = "replicate"
            hot_cap = self.caps.get(
                f"hot{frag.id}",
                bucket_capacity(max(64, per_pair), minimum=64),
            )
        else:
            hot_mode, hot_cap = None, 0
        out, out_sel, (sp_ovf, hot_ovf), (sent, hot_rows, hot_keys), hotset = (
            X.skewed_repartition(
                self.mesh, arrays, khash, sel, bucket, spill,
                hot_mode=hot_mode, hot_cap=hot_cap, hot_set=hot_set,
                detect=detect,
            )
        )
        self.overflows.append((f"spill{frag.id}", sp_ovf))
        if hot_mode is not None:
            self.overflows.append((f"hot{frag.id}", hot_ovf))
            self.counters.append((f"salted{frag.id}", hot_rows))
        if detect is not None:
            self.aux_out = hotset
            self.counters.append((f"hotkeys{frag.id}", hot_keys))
        self.counters.append((f"sent{frag.id}", sent))
        # wire accounting is static: slots each source ships per attempt
        wire_slots = n * bucket + spill + (
            hot_cap if hot_mode == "replicate" else 0
        )
        row_bytes = sum(int(a.dtype.itemsize) for a in arrays)
        self.exchange_static["exchanges"] = (
            self.exchange_static.get("exchanges", 0) + 1
        )
        self.exchange_static["padded_shuffle_rows"] = (
            self.exchange_static.get("padded_shuffle_rows", 0) + n * wire_slots
        )
        self.exchange_static["shuffle_bytes"] = (
            self.exchange_static.get("shuffle_bytes", 0)
            + n * wire_slots * row_bytes
        )
        cols = rebuild(out)
        self._op_exchange(frag, sel, out_sel)
        return Result(Batch(cols, cols[0].data.shape[0], out_sel), res.layout)

    def _op_exchange(self, frag: PlanFragment, sel_in, sel_out) -> None:
        """Exchange leg of the op! channel: rows offered to the exchange
        vs rows landed after repartition/broadcast (broadcast lands n×
        copies — the fan-out is the signal). The in/out pair around a
        partial-agg producer is the per-exchange reduction-ratio seed the
        mid-query-adaptivity roadmap item reads from history."""
        if not self._op_enabled:
            return
        site = self.caps.sites.get(f"exch{frag.id}", f"exch@{frag.id}")
        self.counters.append(
            (f"op!exchange!in!{site}", jnp.sum(sel_in.astype(jnp.int64)))
        )
        self.counters.append(
            (f"op!exchange!out!{site}", jnp.sum(sel_out.astype(jnp.int64)))
        )
