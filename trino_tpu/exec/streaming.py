"""Streaming scan execution: bounded device-resident chunks through one
compiled step program, with H2D transfer overlapping compute.

Reference: Trino drives scans through the operator pipeline in bounded
pages (``operator/Driver.java:355-392``,
``ScanFilterAndProjectOperator.java:64``) so working memory stays bounded
regardless of table size. The TPU translation: a scan→filter→project→
aggregate fragment becomes ONE jitted *step* function with carried
accumulator state

    state' = step(state, chunk)

executed in a host loop over split chunks. Chunk shapes are fixed
(padded), so the step compiles once; JAX dispatch is asynchronous, so the
host reads and transfers chunk k+1 while the device reduces chunk k
(double buffering without explicit streams). Overflow flags are carried
IN the state and inspected once at the end — no host sync per step; on
overflow the caller grows capacities and restarts the stream.

Wide-DECIMAL sums stream too: chunk partials produce per-group limb sums
(ops/decimal128), and limb lanes are independent int64 accumulators, so
the cross-chunk merge just sums each lane (carry resolution happens once,
at finalize)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as PS

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column, bucket_capacity
from trino_tpu.exec.local import Result
from trino_tpu.ops.aggregation import AggSpec, global_aggregate, group_aggregate
from trino_tpu.parallel.mesh import AXIS, shard_batch, smap
from trino_tpu.planner import plan as P


class StreamOverflow(Exception):
    """A capacity overflowed mid-stream; retry with grown caps."""

    def __init__(self, names):
        super().__init__(f"stream capacity overflow: {names}")
        self.names = names


def streamable_chain(frag_root: P.PlanNode):
    """Detect a streamable fragment:
    Output?→Aggregate→(Filter|Project|Join)*→TableScan along the PROBE
    (left) spine. Joins on the spine have their build (right) sides
    materialized once before the stream (reference: build-once
    ``HashBuilderOperator.java:51``, probe-streamed
    ``LookupJoinOperator.java:71``); each probe chunk then flows through
    join→agg inside the compiled step with bounded output capacity.

    Returns (agg_node, probe_scan, build_roots) or None. ``build_roots``
    is the list of build-side subtree roots, outermost first."""
    node = frag_root
    if isinstance(node, P.Output):
        node = node.source
    if not isinstance(node, P.Aggregate):
        return None
    agg = node
    if agg.step == "final":
        return None
    if any(fn.distinct for _, fn in agg.aggregates):
        return None
    for _, fn in agg.aggregates:
        if fn.kind not in ("sum", "count", "count_star", "min", "max", "avg"):
            return None
    node = agg.source
    build_roots: list[P.PlanNode] = []
    while True:
        if isinstance(node, (P.Filter, P.Project)):
            node = node.source
            continue
        if isinstance(node, P.Join):
            if node.join_type not in ("INNER", "LEFT", "SEMI", "ANTI"):
                return None
            if not node.criteria:
                return None
            build_roots.append(node.right)
            node = node.left
            continue
        break
    if not isinstance(node, P.TableScan):
        return None
    return agg, node, build_roots


class StreamingAggregator:
    """Runs one streamable fragment as a chunk loop with carried state.

    Joins on the probe spine stream too: the build (right) sides are
    materialized ONCE up front (``_prebuild``), and every probe chunk
    flows through join→agg inside the compiled step — the reference's
    build-once/probe-streamed hash join (``HashBuilderOperator.java:51``,
    ``LookupJoinOperator.java:71``) with the probe loop compiled."""

    def __init__(self, executor, frag, agg_node, scan_node, caps,
                 build_roots=(), build_inputs=None, build_layouts=None):
        self.executor = executor
        self.mesh = executor.mesh
        self.n = self.mesh.devices.size
        self.frag = frag
        self.agg = agg_node
        self.scan = scan_node
        self.caps = caps
        self.build_roots = list(build_roots)
        self.build_inputs = build_inputs or {}
        self.build_layouts = build_layouts or {}
        self._prememo: Optional[dict] = None
        self.nkeys = len(agg_node.group_keys)
        self.G = caps.get(
            f"agg{id(agg_node)}",
            int(executor.session.get("stream_group_budget")),
        )
        # running per-column dictionaries for the chunk stream; ids of
        # dictionaries whose growth would invalidate the traced step
        self._running_dicts: Optional[list] = None
        self._sensitive_dicts: set[int] = set()

    def _prebuild(self) -> None:
        """Materialize the build sides of probe-spine joins once (device
        resident for the whole stream). Their overflow flags join the
        deferred check; build capacities grow through the same retry."""
        if self._prememo is not None or not self.build_roots:
            self._prememo = self._prememo or {}
            return
        from trino_tpu.exec.fragments import _FragmentTracer

        tracer = _FragmentTracer(
            self.executor, self.build_inputs, self.build_layouts, self.caps
        )
        self._prememo = {}
        for root in self.build_roots:
            self._prememo[id(root)] = tracer._exec(root)
        if tracer.overflows:
            names = [nm for nm, _ in tracer.overflows]
            flags = jnp.stack(
                [f.astype(jnp.int32) for _, f in tracer.overflows]
            )
            dfl = getattr(self.executor, "deferred_flags", None)
            if dfl is not None:
                dfl.append((None, names, flags, self.caps))
            else:
                fired = np.asarray(flags)
                if fired.any():
                    raise StreamOverflow(
                        [nm for nm, f in zip(names, fired) if f]
                    )

    # === chunk source ====================================================

    def _canonicalize_dicts(self, b: Batch) -> Batch:
        """Remap every string column of a split batch onto the stream's
        *running* dictionaries (one stable object per column, grown
        append-only via ``Dictionary.absorb``).

        Two reasons (both bite on any multi-split table):
        - correctness: per-split dictionaries assign unrelated codes to
          the same strings, so carried group keys / min-max state would
          compare garbage across chunks;
        - jit stability: ``Dictionary`` objects are static aux data of the
          chunk pytree, so a fresh dictionary per chunk would retrace and
          recompile the step every chunk.

        If a dictionary grows after the step was traced AND the trace
        embedded growth-sensitive constants from it (rank tables, missed
        equality encodes — see ``Dictionary.trace_log``), the compiled
        step is stale: raise and let the executor fall back."""
        from trino_tpu.exec.fragments import FusedUnsupported

        if not any(c.dictionary is not None for c in b.columns):
            return b
        if self._running_dicts is None:
            self._running_dicts = [None] * b.width
        cols = list(b.columns)
        for j, c in enumerate(cols):
            if c.dictionary is None:
                continue
            running = self._running_dicts[j]
            if running is None:
                self._running_dicts[j] = c.dictionary
                continue
            remap, grew = running.absorb(c.dictionary)
            if grew and id(running) in self._sensitive_dicts:
                raise FusedUnsupported(
                    "split dictionary grew under a rank-dependent trace"
                )
            if remap is not None:
                data = np.asarray(c.data)
                data = np.where(
                    data >= 0, remap[np.maximum(data, 0)], -1
                ).astype(np.int32)
                cols[j] = Column(c.type, data, c.valid, running)
            elif c.dictionary is not running:
                cols[j] = Column(c.type, c.data, c.valid, running)
        return Batch(cols, b.num_rows, b.sel)

    def _chunks(self, chunk_rows: int):
        """Yield lists of n host part-batches, each padded to a fixed
        per-shard capacity (decided from the first split)."""
        connector = self.executor.catalogs.get(self.scan.catalog)
        est = connector.estimate_rows(self.scan.schema, self.scan.table)
        target = max(self.n, (est + chunk_rows - 1) // chunk_rows)
        splits = connector.get_splits(
            self.scan.schema,
            self.scan.table,
            target_splits=target,
            constraint=self.scan.constraint,
        )
        if not splits:
            return
        cap: Optional[int] = None
        proto: Optional[Batch] = None
        pending: list[Batch] = []
        # double-buffered decode (trino_tpu/ingest.py): the next split
        # decodes on a background thread while the device steps over the
        # current chunk — the streaming loop is where overlap pays most
        for b in self.executor._read_splits(
            connector,
            self.scan.schema,
            self.scan.table,
            self.scan.column_names,
            splits,
        ):
            b = self._canonicalize_dicts(b)
            if cap is None:
                cap = bucket_capacity(max(1, min(b.num_rows, chunk_rows)))
                proto = b
            lo = 0
            while True:
                hi = min(lo + cap, b.num_rows)
                piece = _slice_rows(b, lo, hi) if b.num_rows else b
                pending.append(piece)
                if len(pending) == self.n:
                    yield pending, cap
                    pending = []
                lo = hi
                if lo >= b.num_rows:
                    break
        if pending:
            while len(pending) < self.n:
                pending.append(_empty_like(proto))
            yield pending, cap

    # === driver loop =====================================================

    def run(self) -> Result:
        chunk_rows = int(self.executor.session.get("stream_chunk_rows"))
        self._prebuild()
        res = self._run_device_slab(chunk_rows)
        if res is not None:
            return res
        it = self._chunks(chunk_rows)
        first = next(it, None)
        if first is None:
            from trino_tpu.exec.fragments import FusedUnsupported

            raise FusedUnsupported("streaming scan with zero splits")
        parts, cap = first
        chunk, counts = _pad_batch(self.mesh, parts, cap)
        meta = self._collect_meta(chunk)
        state = self._init_state(meta)
        step = jax.jit(self._make_step(meta), donate_argnums=(0,))
        # the real trace happens on this first call — log dictionary
        # accesses here too (eval_shape in _collect_meta covers the same
        # path, but belt-and-braces keeps the invalidation set complete)
        from trino_tpu.columnar import Dictionary

        prev_log = Dictionary.begin_trace_log()
        try:
            state = step(state, chunk, counts)
        finally:
            log = Dictionary.end_trace_log(prev_log)
        self._sensitive_dicts |= set(log.get("growth_sensitive", ()))
        for parts, cap in it:
            chunk, counts = _pad_batch(self.mesh, parts, cap)
            state = step(state, chunk, counts)
        self._check_overflow(state, None, meta)
        return self._finish(state, meta)

    def _check_overflow(self, state, prog_key, meta) -> None:
        """Overflow handling: inside a fragmented query, queue the flag
        vector on the executor's deferred list (ONE device->host pull per
        query, in ``_execute_fragments``); otherwise pull and raise here
        so the caller's retry loop grows the fired budgets."""
        names = meta["ovf_names"]
        dfl = getattr(self.executor, "deferred_flags", None)
        if dfl is not None:
            dfl.append((prog_key, names, state["overflow"], self.caps))
            return
        fired = np.asarray(state["overflow"])
        if fired.any():
            raise StreamOverflow([nm for nm, f in zip(names, fired) if f])

    # === device-resident slab source =====================================

    def _run_device_slab(self, chunk_rows: int) -> Optional[Result]:
        """Stream a device-resident table: the connector stages the whole
        table into HBM once (``device_slab``), and each chunk is a
        ``dynamic_slice`` INSIDE the compiled step — zero per-chunk host
        work or host->device transfer, one dispatch per chunk.

        Single-device meshes only (a sharded slab would need per-shard
        offsets; multi-device streams use the host chunk path)."""
        if self.n != 1:
            return None
        connector = self.executor.catalogs.get(self.scan.catalog)
        # device chunks can be much larger than host chunks (no transfer
        # to overlap, and fewer dispatches beat smaller sorts)
        cap = bucket_capacity(
            max(1, int(self.executor.session.get("stream_device_chunk_rows")))
        )
        slab = None
        chunk_cols = None
        stage = getattr(connector, "device_slab", None)
        if stage is not None:
            limit = int(self.executor.session.get("stream_device_cache_bytes"))
            staged = stage(
                self.scan.schema, self.scan.table, self.scan.column_names,
                cap, limit,
            )
            if staged is not None:
                slab, num_rows = staged
                cap = min(cap, slab.capacity)
        if slab is None:
            gen = getattr(connector, "device_generator", None)
            if gen is None:
                return None
            spec = gen(self.scan.schema, self.scan.table, self.scan.column_names)
            if spec is None:
                return None
            chunk_cols, num_rows = spec
            if num_rows <= 0:
                return None
        if slab is not None and not self.build_roots:
            dense = self._try_dense(slab, num_rows)
            if dense is not None:
                return dense
        programs = getattr(self.executor, "programs", None)
        if self.build_roots:
            # the step closes over this query's materialized build
            # batches; a cached program would pin stale builds
            programs = None
        # wide pipelines (many payload lanes) can exceed scoped vmem at
        # large chunk sizes: on a compile failure, halve the chunk (the
        # slab's quantum padding stays valid for any smaller power of
        # two) and REMEMBER the working cap so warm queries never repeat
        # the failing compile
        if programs is not None:
            cap = min(cap, programs.get(("slabcap", id(self.agg)), cap))
        while True:
            n_steps = (num_rows + cap - 1) // cap
            prog_key = ("slab", id(self.agg), self.G, cap, slab is None)
            hit = programs.get(prog_key) if programs is not None else None
            if hit is not None:
                program, meta = hit
                state = self._init_state(meta)
                state = program(
                    state, slab, np.int32(n_steps), np.int64(num_rows)
                )
                self._check_overflow(state, prog_key, meta)
                return self._finish(state, meta)
            if slab is not None:
                probe_cols = [
                    Column(
                        c.type,
                        jax.ShapeDtypeStruct((cap,) + c.data.shape[1:], c.data.dtype),
                        None
                        if c.valid is None
                        else jax.ShapeDtypeStruct((cap,), jnp.bool_),
                        c.dictionary,
                    )
                    for c in slab.columns
                ]
            else:
                probe_cols = [
                    Column(
                        c.type,
                        jax.ShapeDtypeStruct((cap,) + c.data.shape[1:], c.data.dtype),
                        None,
                        c.dictionary,
                    )
                    for c in jax.eval_shape(
                        lambda: chunk_cols(jnp.zeros((), jnp.int32), cap)
                    )
                ]
            probe_chunk = Batch(
                probe_cols, cap, jax.ShapeDtypeStruct((cap,), jnp.bool_)
            )
            meta = self._collect_meta(probe_chunk)
            state = self._init_state(meta)
            program = jax.jit(
                self._make_slab_program(meta, cap, chunk_cols),
                donate_argnums=(0,),
            )
            try:
                state = program(
                    state, slab, np.int32(n_steps), np.int64(num_rows)
                )
            except jax.errors.JaxRuntimeError as e:
                msg = str(e).lower()
                compile_failure = any(
                    tok in msg
                    for tok in ("compile", "vmem", "resource_exhausted")
                )
                if not compile_failure or cap <= 1 << 18:
                    raise
                cap //= 2
                continue
            if programs is not None:
                programs[prog_key] = (program, meta)
                programs[("slabcap", id(self.agg))] = cap
            self._check_overflow(state, prog_key, meta)
            return self._finish(state, meta)

    def _try_dense(self, slab: Batch, num_rows: int) -> Optional[Result]:
        """Dense-domain fast path: when the group keys span a small
        integer domain (from data min/max — the ``BigintGroupByHash``
        precondition) and every aggregate is a null-free sum/count, the
        whole slab runs through ONE Pallas MXU binning kernel
        (ops/dense_groupby.py) — measured ~280M rows/s vs ~25M for the
        sort-based step on v5e.  Returns None when ineligible."""
        import numpy as np

        from trino_tpu.ops import dense_groupby as DG

        agg = self.agg
        if agg.step != "partial" or not self.nkeys:
            return None
        cap = slab.capacity
        if cap < (1 << 15) or cap & (cap - 1):
            return None
        if jax.devices()[0].platform not in ("tpu",):
            return None
        # trace filters/projections over the WHOLE resident slab (eager
        # device compute; no host transfer)
        from trino_tpu.exec.fragments import FusedUnsupported

        live0 = jnp.arange(cap, dtype=jnp.int32) < num_rows
        batch = Batch(slab.columns, cap, live0)
        try:
            tracer = self._tracer_for(batch)
            agg_inputs, specs, string_dicts, keys, key_dicts, sel = (
                self._chunk_prep(tracer)
            )
        except FusedUnsupported:
            return None
        if tracer.overflows:
            return None
        for spec in specs:
            if spec.kind not in ("sum", "avg", "count", "count_star", "sum128"):
                return None
        for pair in agg_inputs:
            if pair is None:
                continue
            data, valid = pair
            if valid is not None or getattr(data, "ndim", 1) != 1:
                return None
            if not np.issubdtype(np.dtype(data.dtype), np.integer):
                return None
        for (kd, kv) in keys:
            if kv is not None or getattr(kd, "ndim", 1) != 1:
                return None
            if not np.issubdtype(np.dtype(kd.dtype), np.integer):
                return None
        # key domain from data min/max (ONE device round-trip, cached on
        # the executor's program cache per resident slab)
        programs = getattr(self.executor, "programs", None)
        stats_key = ("dense_stats", id(slab), num_rows, id(self.agg))
        stats = programs.get(stats_key) if programs is not None else None
        distinct_vals: list = []
        for pair in agg_inputs:
            if pair is not None and not any(
                pair[0] is d for d in distinct_vals
            ):
                distinct_vals.append(pair[0])
        if stats is None:
            mins, maxs = [], []
            for kd, _ in keys:
                mins.append(jnp.min(jnp.where(sel, kd, jnp.iinfo(jnp.int64).max)))
                maxs.append(jnp.max(jnp.where(sel, kd, jnp.iinfo(jnp.int64).min)))
            vmins, vmaxs = [], []
            for d in distinct_vals:
                vmins.append(jnp.min(jnp.where(sel, d, 0)))
                vmaxs.append(jnp.max(jnp.where(sel, d, 0)))
            packed = np.asarray(
                jnp.stack([jnp.stack(mins + vmins), jnp.stack(maxs + vmaxs)])
            )
            stats = (packed[0].tolist(), packed[1].tolist())
            if programs is not None:
                programs[stats_key] = stats
        lo_list, hi_list = stats
        kmins = lo_list[: len(keys)]
        kmaxs = hi_list[: len(keys)]
        vmins = lo_list[len(keys):]
        vmaxs = hi_list[len(keys):]
        if any(mx < mn for mn, mx in zip(kmins, kmaxs)):
            return None  # zero selected rows: let the sort path handle
        ranges = [int(mx - mn) + 1 for mn, mx in zip(kmins, kmaxs)]
        g_raw = 1
        for r in ranges:
            g_raw *= r
            if g_raw > 8192:
                return None
        G = max(128, ((g_raw + 127) // 128) * 128)
        # lane plan from value ranges; a column consumed by any sum128
        # spec gets the exact 128-bit pair output REGARDLESS of sign
        # (downstream dispatches on the spec kind, not the data range)
        pair_cols: set = set()
        for spec, pair in zip(specs, agg_inputs):
            if spec.kind == "sum128" and pair is not None:
                for ci, d in enumerate(distinct_vals):
                    if pair[0] is d:
                        pair_cols.add(ci)
        cols, pair128 = [], []
        for ci, (d, mn, mx) in enumerate(zip(distinct_vals, vmins, vmaxs)):
            nonneg = mn >= 0
            bits = max(int(mx).bit_length(), 1) if nonneg else 64
            cols.append(DG.DenseCol(nonneg=nonneg, bits=bits))
            pair128.append(ci in pair_cols)
        plan = DG.DensePlan(G=G, cols=tuple(cols), pair128=tuple(pair128))
        if plan.m > 4096:
            return None  # accumulator VMEM budget
        # row-major key offsets; bins computed INSIDE the jitted program
        # (each eager op is a separate ~10-20ms dispatch over the remote
        # tunnel; one fused program is one dispatch). mins/strides are
        # dynamic args so one compile serves any key range of this shape.
        strides = []
        acc = 1
        for r in reversed(ranges):
            strides.append(acc)
            acc *= r
        strides.reverse()
        nk = len(keys)
        prog_key = ("dense", plan, cap, nk, len(distinct_vals))
        fn = programs.get(prog_key) if programs is not None else None
        if fn is None:
            G_const = G

            def prog(sel_, mins_, strides_, key_arrs, val_arrs):
                bin_ = jnp.zeros(sel_.shape[0], jnp.int32)
                for i, kd in enumerate(key_arrs):
                    bin_ = bin_ + (
                        (kd - mins_[i]).astype(jnp.int32) * strides_[i]
                    )
                bin_ = jnp.where(sel_, bin_, jnp.int32(G_const))
                return DG.dense_groupby_device(
                    plan, bin_, [v.astype(jnp.int64) for v in val_arrs]
                )

            fn = jax.jit(prog)
            if programs is not None:
                programs[prog_key] = fn
        hi, lo = fn(
            sel,
            jnp.asarray(np.asarray(kmins, np.int64)),
            jnp.asarray(np.asarray(strides, np.int32)),
            [kd for kd, _ in keys],
            list(distinct_vals),
        )
        # reconstruction runs on DEVICE in a SECOND jit (separate from the
        # pallas producer — in-graph consumers fused with the pallas call
        # read corrupted values on this stack, and a host round-trip costs
        # ~100ms per pull over the remote tunnel)
        recon_key = ("dense_recon", plan, nk)
        rfn = programs.get(recon_key) if programs is not None else None
        if rfn is None:
            rfn = jax.jit(
                lambda h, l, mn, st, rg: DG.reconstruct_device(
                    plan, h, l, mn, st, rg
                )
            )
            if programs is not None:
                programs[recon_key] = rfn
        key_vals, col_sums, counts = rfn(
            hi, lo,
            jnp.asarray(np.asarray(kmins, np.int64)),
            jnp.asarray(np.asarray(strides, np.int64)),
            jnp.asarray(np.asarray(ranges, np.int64)),
        )
        return self._dense_finish(
            plan, keys, key_dicts, specs, string_dicts, agg_inputs,
            distinct_vals, key_vals, col_sums, counts,
        )

    def _dense_finish(self, plan, keys, key_dicts, specs, string_dicts,
                      agg_inputs, distinct_vals, key_vals, col_sums,
                      counts) -> Result:
        """Build the partial-accumulator Result (same wire format as
        ``_finish_partial``) from device-reconstructed sums."""
        agg = self.agg
        G = plan.G
        live = counts > 0
        cols: list[Column] = []
        layout: dict[str, int] = {}
        for i, ksym in enumerate(agg.group_keys):
            cols.append(
                Column(
                    ksym.type,
                    key_vals[i].astype(ksym.type.storage_dtype),
                    live,
                    key_dicts[i],
                )
            )
            layout[ksym.name] = len(cols) - 1

        def col_index(pair):
            for ci, d in enumerate(distinct_vals):
                if pair[0] is d:
                    return ci
            raise KeyError

        for (vsym, csym), spec, sdict, pair in zip(
            agg.acc_symbols, specs, string_dicts, agg_inputs
        ):
            if spec.kind in ("count", "count_star"):
                cols.append(Column(T.BIGINT, counts, None))
                layout[vsym.name] = len(cols) - 1
                continue
            ci = col_index(pair)
            val = col_sums[ci]
            if spec.kind != "sum128":
                if getattr(val, "ndim", 1) == 2:
                    # column shared with a sum128 spec: the pair's lo
                    # limb IS the modular int64 sum
                    val = val[:, 1]
                val = val.astype(vsym.type.storage_dtype)
            cols.append(Column(vsym.type, val, None, sdict))
            layout[vsym.name] = len(cols) - 1
            cols.append(Column(T.BIGINT, counts, None))
            layout[csym.name] = len(cols) - 1
        return Result(Batch(cols, G, live), layout)

    def _make_slab_program(self, meta: dict, cap: int, chunk_cols=None):
        """The ENTIRE chunk loop as one compiled program: a
        ``lax.fori_loop`` whose body takes chunk i — dynamic-sliced from
        the resident slab, or computed by the connector's traced
        generator (``chunk_cols``) — and folds it into the carried
        accumulators. One dispatch per query regardless of table size,
        and the dynamic trip count means one compilation serves any row
        count."""
        inner = self._make_step(meta)

        def body_for(slab, num_rows):
            def body(i, state):
                # int64 offset: i*cap wraps int32 past 2^31 rows (the
                # generator path has no table-size bound)
                off = i.astype(jnp.int64) * cap
                cnt = jnp.minimum(cap, (num_rows - off).astype(jnp.int32))
                if slab is not None:
                    cols = []
                    for c in slab.columns:
                        data = jax.lax.dynamic_slice_in_dim(c.data, off, cap, axis=0)
                        valid = (
                            None
                            if c.valid is None
                            else jax.lax.dynamic_slice_in_dim(c.valid, off, cap, axis=0)
                        )
                        cols.append(Column(c.type, data, valid, c.dictionary))
                else:
                    cols = chunk_cols(off, cap)
                live = jnp.arange(cap, dtype=jnp.int32) < cnt
                return inner(state, Batch(cols, cap, live), None)

            return body

        def program(state, slab, n_steps, num_rows):
            return jax.lax.fori_loop(
                0, n_steps, body_for(slab, num_rows), state
            )

        return program

    # === metadata (eager pass over the first chunk) ======================

    def _tracer_for(self, chunk: Batch):
        from trino_tpu.exec.fragments import _FragmentTracer

        tracer = _FragmentTracer(
            self.executor,
            {f"scan{id(self.scan)}": chunk},
            {
                f"scan{id(self.scan)}": {
                    s.name: i for i, s in enumerate(self.scan.symbols)
                }
            },
            self.caps,
        )
        if self._prememo:
            # build sides of probe-spine joins: already materialized, so
            # the chunk trace consumes them as constants instead of
            # re-executing the build per chunk
            tracer._memo.update(self._prememo)
        return tracer

    def _chunk_prep(self, tracer):
        res = tracer._exec(self.agg.source)
        sel = res.batch.selection_mask()
        agg_inputs, specs, string_dicts = tracer._agg_inputs(self.agg, res)
        keys = [res.opt_pair(k) for k in self.agg.group_keys]
        key_dicts = [res.column(k).dictionary for k in self.agg.group_keys]
        return agg_inputs, specs, string_dicts, keys, key_dicts, sel

    def _collect_meta(self, chunk: Batch) -> dict:
        """Static metadata (specs/widths/dicts) via abstract evaluation —
        no device compute; the first chunk is only executed by the step.
        Dictionary accesses that embed growth-sensitive constants (rank
        tables, missed encodes) are recorded so later chunks know whether
        growing a dictionary invalidates the step."""
        from trino_tpu.columnar import Dictionary

        box = {}

        def probe(ch):
            tracer = self._tracer_for(ch)
            agg_inputs, specs, string_dicts, keys, key_dicts, sel = (
                self._chunk_prep(tracer)
            )
            box["specs"] = specs
            box["string_dicts"] = string_dicts
            box["key_dicts"] = key_dicts
            box["key_dtypes"] = [kd.dtype for kd, _ in keys]
            # per-chunk overflow sources (probe-spine join capacities);
            # execution order is deterministic, so the step trace will
            # produce flags in this same order
            box["ovf_names"] = [nm for nm, _ in tracer.overflows]
            return sel

        prev_log = Dictionary.begin_trace_log()
        try:
            jax.eval_shape(probe, chunk)
        finally:
            log = Dictionary.end_trace_log(prev_log)
        self._sensitive_dicts = set(log.get("growth_sensitive", ()))
        specs = box["specs"]
        string_dicts = box["string_dicts"]
        key_dicts = box["key_dicts"]
        widths = []
        for spec in specs:
            if spec.kind == "sum128":
                widths.append(3)
            elif spec.kind == "sum128w":
                widths.append(5)
            else:
                widths.append(1)
        combine = []
        for spec in specs:
            if spec.kind in ("min", "max"):
                combine.append(spec.kind)
            else:
                combine.append("sum")  # counts and (limb) sums add
        return {
            "specs": specs,
            "combine": combine,
            "widths": widths,
            "string_dicts": string_dicts,
            "key_dicts": key_dicts,
            "key_dtypes": box["key_dtypes"],
            "ovf_names": [f"agg{id(self.agg)}"] + box["ovf_names"],
        }

    def _init_state(self, meta: dict) -> dict:
        rows = self.n * self.G if self.nkeys else self.n
        sh = NamedSharding(self.mesh, PS(AXIS))

        def zeros(shape, dt):
            return jax.device_put(jnp.zeros(shape, dtype=dt), sh)

        state: dict = {
            "overflow": jnp.zeros(len(meta["ovf_names"]), dtype=jnp.int32)
        }
        if self.nkeys:
            state["key_data"] = [
                zeros((rows,), dt) for dt in meta["key_dtypes"]
            ]
            state["key_valid"] = [
                zeros((rows,), jnp.bool_) for _ in range(self.nkeys)
            ]
            state["live"] = zeros((rows,), jnp.bool_)
        state["values"] = [
            zeros((rows,) if w == 1 else (rows, w), jnp.int64)
            for w in meta["widths"]
        ]
        state["counts"] = [zeros((rows,), jnp.int64) for _ in meta["specs"]]
        return state

    # === the compiled step ==============================================

    def _make_step(self, meta: dict):
        specs = meta["specs"]
        combine = meta["combine"]
        widths = meta["widths"]
        nkeys, G, n = self.nkeys, self.G, self.n
        nspec = len(specs)
        sagg = self

        def step(state, chunk: Batch, counts):
            if counts is not None:
                # per-shard valid-row counts (dynamic) instead of a host
                # mask: tail chunks keep the same pytree structure, so the
                # step compiles exactly once per stream
                cap = chunk.capacity // sagg.n
                pos = jnp.arange(chunk.capacity, dtype=jnp.int32)
                live = pos % cap < counts[pos // cap]
                chunk = Batch(chunk.columns, chunk.num_rows, live)
            tracer = sagg._tracer_for(chunk)
            agg_inputs, _specs, _sd, keys, _kd, sel = sagg._chunk_prep(tracer)
            prev_ovf = state["overflow"]
            if nkeys == 0:
                out = sagg._step_global(
                    state, sel, agg_inputs, specs, combine, widths
                )
            else:
                out = sagg._step_grouped(
                    state, keys, sel, agg_inputs, specs, combine, widths
                )
            # overflow lanes: [agg] + per-chunk join capacities, max'd
            # with the carried vector
            flags = [jnp.reshape(out["overflow"], ())] + [
                jnp.reshape(f.astype(jnp.int32), ())
                for _, f in tracer.overflows
            ]
            out["overflow"] = jnp.maximum(prev_ovf, jnp.stack(flags))
            return out

        return step

    def _step_grouped(self, state, keys, sel, agg_inputs, specs, combine, widths):
        nkeys, G, n = self.nkeys, self.G, self.n
        nspec = len(specs)
        Gc = G  # chunk groups bounded by the same budget

        from trino_tpu.exec.fragments import pack_opt_pairs

        flat, pack = pack_opt_pairs(keys, sel, agg_inputs)
        flat.extend(state["key_data"])
        flat.extend(state["key_valid"])
        flat.append(state["live"])
        flat.extend(state["values"])
        flat.extend(state["counts"])

        def shard_step(*ops):
            lkeys, lsel, linputs, i = pack.unpack(ops)
            skd = list(ops[i : i + nkeys]); i += nkeys
            skv = list(ops[i : i + nkeys]); i += nkeys
            slive = ops[i]; i += 1
            svals = list(ops[i : i + nspec]); i += nspec
            scnts = list(ops[i : i + nspec]); i += nspec

            # 1) chunk partial: raw rows -> chunk groups
            (ckd, ckv), craw, cng, covf = group_aggregate(
                lkeys, lsel, linputs, specs, Gc
            )
            clive = jnp.arange(Gc) < cng
            cvals, ccnts = [], []
            for spec, r in zip(specs, craw):
                if spec.kind in ("count", "count_star"):
                    v = r.astype(jnp.int64)
                    cvals.append(v)
                    ccnts.append(v)
                else:
                    cvals.append(r[0])
                    ccnts.append(r[1].astype(jnp.int64))

            # 2) merge state + chunk groups (lane-expanded for limb sums)
            mkeys = [
                (
                    jnp.concatenate([skd[k], ckd[k].astype(skd[k].dtype)]),
                    jnp.concatenate([skv[k], ckv[k]]),
                )
                for k in range(nkeys)
            ]
            msel = jnp.concatenate([slive, clive])
            ones = jnp.ones_like(msel)
            minputs, mspecs, mplan = [], [], []
            for j in range(nspec):
                sv, cv = svals[j], cvals[j]
                sc, cc = scnts[j], ccnts[j]
                if widths[j] == 1:
                    mv = jnp.concatenate([sv, cv.astype(jnp.int64)])
                    if combine[j] in ("min", "max"):
                        valid = jnp.concatenate([sc > 0, cc > 0])
                    else:
                        valid = ones
                    minputs.append((mv, valid))
                    mspecs.append(AggSpec(combine[j]))
                    mplan.append(("v", j, 0))
                else:
                    for lane in range(widths[j]):
                        mv = jnp.concatenate([sv[:, lane], cv[:, lane]])
                        minputs.append((mv, ones))
                        mspecs.append(AggSpec("sum"))
                        mplan.append(("v", j, lane))
                minputs.append((jnp.concatenate([sc, cc]), ones))
                mspecs.append(AggSpec("sum"))
                mplan.append(("c", j, 0))
            (nkd, nkv), nraw, nng, novf = group_aggregate(
                mkeys, msel, minputs, mspecs, G
            )
            nlive = jnp.arange(G) < nng
            nvals = [None] * nspec
            ncnts = [None] * nspec
            lanes: dict[int, list] = {}
            for (kind, j, lane), r in zip(mplan, nraw):
                val = r[0]
                if kind == "c":
                    ncnts[j] = val.astype(jnp.int64)
                elif widths[j] == 1:
                    nvals[j] = val
                else:
                    lanes.setdefault(j, [None] * widths[j])[lane] = val
            for j, ln in lanes.items():
                nvals[j] = jnp.stack(ln, axis=1)
            ovf = jax.lax.pmax((covf | novf).astype(jnp.int32), AXIS)
            return (
                tuple(nkd), tuple(nkv), nlive,
                tuple(nvals), tuple(ncnts), ovf,
            )

        out_specs = (
            tuple(PS(AXIS) for _ in range(nkeys)),
            tuple(PS(AXIS) for _ in range(nkeys)),
            PS(AXIS),
            tuple(PS(AXIS) for _ in range(nspec)),
            tuple(PS(AXIS) for _ in range(nspec)),
            PS(),
        )
        mapped = smap(
            shard_step,
            mesh=self.mesh,
            in_specs=(PS(AXIS),) * len(flat),
            out_specs=out_specs,
        )
        nkd, nkv, nlive, nvals, ncnts, ovf = mapped(*flat)
        return {
            "key_data": list(nkd),
            "key_valid": list(nkv),
            "live": nlive,
            "values": list(nvals),
            "counts": list(ncnts),
            # chunk-local agg overflow; the caller folds it into the
            # carried per-source overflow vector
            "overflow": ovf.astype(jnp.int32),
        }

    def _step_global(self, state, sel, agg_inputs, specs, combine, widths):
        from trino_tpu.exec.fragments import pack_opt_pairs

        nspec = len(specs)
        flat, pack = pack_opt_pairs([], sel, agg_inputs)
        flat.extend(state["values"])
        flat.extend(state["counts"])

        def shard_step(*ops):
            _, lsel, linputs, i = pack.unpack(ops)
            svals = list(ops[i : i + nspec]); i += nspec
            scnts = list(ops[i : i + nspec]); i += nspec
            raw = global_aggregate(lsel, linputs, specs)
            outs_v, outs_c = [], []
            for j, (spec, r) in enumerate(zip(specs, raw)):
                if spec.kind in ("count", "count_star"):
                    cv = jnp.reshape(r.astype(jnp.int64), (1,))
                    cc = cv
                else:
                    cv = r[0]
                    cv = cv if getattr(cv, "ndim", 0) == 2 else jnp.reshape(cv, (1,))
                    cc = jnp.reshape(r[1].astype(jnp.int64), (1,))
                sv, sc = svals[j], scnts[j]
                if combine[j] == "min":
                    nv = jnp.where(
                        sc == 0, cv, jnp.where(cc == 0, sv, jnp.minimum(sv, cv))
                    )
                elif combine[j] == "max":
                    nv = jnp.where(
                        sc == 0, cv, jnp.where(cc == 0, sv, jnp.maximum(sv, cv))
                    )
                else:
                    nv = sv + jnp.reshape(cv, sv.shape)
                outs_v.append(jnp.reshape(nv, sv.shape))
                outs_c.append(sc + cc)
            return tuple(outs_v), tuple(outs_c)

        mapped = smap(
            shard_step,
            mesh=self.mesh,
            in_specs=(PS(AXIS),) * len(flat),
            out_specs=(
                tuple(PS(AXIS) for _ in range(nspec)),
                tuple(PS(AXIS) for _ in range(nspec)),
            ),
        )
        nvals, ncnts = mapped(*flat)
        return {
            "values": list(nvals),
            "counts": list(ncnts),
            "overflow": jnp.zeros((), dtype=jnp.int32),  # global agg: none
        }

    # === result assembly =================================================

    def _finish(self, state, meta) -> Result:
        if self.agg.step == "partial":
            return self._finish_partial(state, meta)
        return self._finish_single(state, meta)

    def _acc_value_column(self, vsym, spec, sdict, v, c):
        """Accumulator wire representation (mirrors _agg_partial)."""
        from trino_tpu.ops import decimal128 as D128

        val = v
        if getattr(val, "ndim", 1) == 2 and val.shape[1] in (3, 5):
            hi, lo = D128.limb_sums_to_pair(val)
            val = jnp.stack([hi, lo], axis=1)
        elif sdict is not None:
            order = np.argsort(sdict.ranks(), kind="stable")
            if len(order):
                val = jnp.asarray(order)[
                    jnp.clip(val, 0, len(order) - 1)
                ].astype(jnp.int32)
            else:
                val = jnp.full(val.shape, -1, dtype=jnp.int32)
        return Column(vsym.type, val, None, sdict)

    def _finish_partial(self, state, meta) -> Result:
        agg = self.agg
        cols: list[Column] = []
        layout: dict[str, int] = {}
        if self.nkeys:
            for i, ksym in enumerate(agg.group_keys):
                cols.append(
                    Column(
                        ksym.type,
                        state["key_data"][i].astype(ksym.type.storage_dtype),
                        state["key_valid"][i],
                        meta["key_dicts"][i],
                    )
                )
                layout[ksym.name] = len(cols) - 1
            live = state["live"]
            total = self.n * self.G
        else:
            live = jnp.ones(self.n, dtype=jnp.bool_)
            total = self.n
        for (vsym, csym), spec, sdict, v, c in zip(
            agg.acc_symbols,
            meta["specs"],
            meta["string_dicts"],
            state["values"],
            state["counts"],
        ):
            if spec.kind in ("count", "count_star"):
                cols.append(
                    Column(T.BIGINT, v.reshape(-1).astype(jnp.int64), None)
                )
                layout[vsym.name] = len(cols) - 1
                continue
            cols.append(self._acc_value_column(vsym, spec, sdict, v, c))
            layout[vsym.name] = len(cols) - 1
            cols.append(Column(T.BIGINT, c.astype(jnp.int64), None))
            layout[csym.name] = len(cols) - 1
        return Result(Batch(cols, total, live), layout)

    def _finish_single(self, state, meta) -> Result:
        from trino_tpu.exec.fragments import _FragmentTracer

        agg = self.agg
        tracer = _FragmentTracer(self.executor, {}, {}, self.caps)
        if self.nkeys:
            results = []
            for spec, v, c in zip(
                meta["specs"], state["values"], state["counts"]
            ):
                if spec.kind in ("count", "count_star"):
                    results.append(v.reshape(-1))
                else:
                    results.append((v, c))
            total = self.n * self.G
            cols = []
            for i, ksym in enumerate(agg.group_keys):
                cols.append(
                    Column(
                        ksym.type,
                        state["key_data"][i].astype(ksym.type.storage_dtype),
                        state["key_valid"][i],
                        meta["key_dicts"][i],
                    )
                )
            cols.extend(
                tracer._finalize_traced(
                    agg, results, meta["string_dicts"], total
                )
            )
            layout = {s.name: i for i, s in enumerate(agg.output_symbols)}
            return Result(Batch(cols, total, state["live"]), layout)
        # global: fold the n per-shard accumulators on host (n rows)
        results = []
        for spec, v, c in zip(meta["specs"], state["values"], state["counts"]):
            vn = np.asarray(v)
            cn = np.asarray(c)
            if spec.kind in ("count", "count_star"):
                results.append(jnp.asarray([int(vn.sum())]))
            elif spec.kind in ("min", "max"):
                valid = cn > 0
                if valid.any():
                    vv = vn[valid]
                    val = int(vv.min() if spec.kind == "min" else vv.max())
                else:
                    val = 0
                results.append(
                    (jnp.asarray([val]), jnp.asarray([int(cn.sum())]))
                )
            else:
                ssum = vn.sum(axis=0)
                ssum = ssum[None] if ssum.ndim else np.asarray([ssum])
                results.append(
                    (jnp.asarray(ssum), jnp.asarray([int(cn.sum())]))
                )
        cols = tracer._finalize_traced(agg, results, meta["string_dicts"], 1)
        layout = {s.name: i for i, s in enumerate(agg.output_symbols)}
        return Result(Batch(cols, 1, jnp.ones(1, dtype=jnp.bool_)), layout)


# === host-side batch helpers ================================================


def _slice_rows(b: Batch, lo: int, hi: int) -> Batch:
    cols = []
    for c in b.columns:
        data, valid = c.to_numpy()
        v = valid[lo:hi]
        cols.append(
            Column(c.type, data[lo:hi], None if v.all() else v, c.dictionary)
        )
    out = Batch(cols, hi - lo)
    if b.sel is not None:
        sel = np.asarray(b.sel)[lo:hi]
        out = Batch(cols, hi - lo, sel)
    return out


def _empty_like(b: Batch) -> Batch:
    cols = [
        Column(
            c.type,
            np.zeros(
                (0,) + np.asarray(c.data).shape[1:],
                dtype=np.asarray(c.data).dtype,
            ),
            None,
            c.dictionary,
        )
        for c in b.columns
    ]
    return Batch(cols, 0)


def _pad_batch(mesh, parts: list[Batch], cap: int):
    """shard_batch with every part padded to exactly ``cap`` rows so each
    step shares one compiled shape.

    Returns (chunk, counts): when no part carries a selection mask, the
    padding is expressed as per-shard valid-row *counts* (an (n,) int32
    array the compiled step turns into a mask in-trace) — no mask bytes
    cross to the device, and full and tail chunks share one pytree
    structure (one compile per stream). Sources that do carry ``sel``
    fall back to explicit masks (counts=None)."""
    if all(p.sel is None for p in parts):
        counts = np.asarray([p.num_rows for p in parts], dtype=np.int32)
        padded = []
        for p in parts:
            if p.capacity == cap and p.num_rows == cap:
                padded.append(p)
                continue
            cols = []
            for c in p.columns:
                data = np.asarray(c.data)
                pad = cap - data.shape[0]
                if pad:
                    data = np.concatenate(
                        [data, np.zeros((pad,) + data.shape[1:], dtype=data.dtype)]
                    )
                valid = c.valid
                if valid is not None:
                    valid = np.concatenate(
                        [np.asarray(valid), np.zeros(pad, dtype=np.bool_)]
                    ) if pad else valid
                cols.append(Column(c.type, data, valid, c.dictionary))
            # num_rows=cap: padding liveness is carried by `counts`
            padded.append(Batch(cols, cap))
        return shard_batch(mesh, padded), counts
    padded = []
    for p in parts:
        if p.capacity == cap and p.sel is None and p.num_rows == cap:
            padded.append(p)
            continue
        cols = []
        for c in p.columns:
            data, valid = c.to_numpy()
            pad = cap - data.shape[0]
            if pad:
                data = np.concatenate(
                    [data, np.zeros((pad,) + data.shape[1:], dtype=data.dtype)]
                )
                valid = np.concatenate([valid, np.zeros(pad, dtype=np.bool_)])
            cols.append(Column(c.type, data, valid, c.dictionary))
        sel = np.zeros(cap, dtype=np.bool_)
        sel[: p.num_rows] = True
        if p.sel is not None:
            sel[: p.capacity] &= np.asarray(p.sel)
        padded.append(Batch(cols, cap, sel))
    return shard_batch(mesh, padded), None