"""Cross-query device batching (continuous batching for SQL).

Reference: continuous batching in inference serving (Orca, vLLM) applied
to the PR-4 program cache. Queries whose plans canonicalize to the same
fingerprint differ only in their hoisted-literal parameter vectors, so K
of them can share ONE stacked device dispatch through the cached
(optionally fused) program instead of paying K dispatch round-trips.

The :class:`BatchCollector` holds compatible pending queries for a short
window (``batch_window_ms`` session property, flushed early at
``batch_max_size``). The first arrival for a group becomes the *leader*:
it waits out the window on the calling thread, then executes the whole
group while followers block on per-member events. Compatibility =
same program-cache entry (fingerprint + data versions + ACL generation)
AND the same session-property signature — a member with, say, a
different ``batch_capacity`` would trace a different program and must
not share the dispatch.

Correctness contract: a batched run is bit-identical to K sequential
runs (the stacked program unrolls K copies of the same traced ops — see
``FragmentedExecutor.execute_batched``). Any shape the batched path
cannot carry raises ``BatchUnsupported`` and the group falls back to
sequential per-member execution; a member that fails there fails alone
without poisoning its batchmates.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Optional

__all__ = ["BatchCollector"]


@dataclasses.dataclass
class _Member:
    """One pending query waiting for its group's dispatch."""

    query_id: str
    session: Any  # this member's own Session (identical signature)
    params: list  # hoisted (value, type) literals for this member
    enq_mono: float  # monotonic enqueue time (batchWaitMs)
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )
    result: Any = None
    error: Optional[BaseException] = None


class _Group:
    """A collecting batch: one leader thread + joined followers."""

    __slots__ = ("entry", "plan", "members", "closed", "full")

    def __init__(self, entry: dict, plan) -> None:
        self.entry = entry  # strong ref: pins id(entry) against reuse
        self.plan = plan  # leader's exec plan (first cached wins)
        self.members: list[_Member] = []
        self.closed = False  # no further joins once set (under lock)
        self.full = threading.Event()  # set at batch_max_size


def _session_signature(session) -> tuple:
    """Hashable view of the session overrides.

    The canonical fingerprint already folds in codegen-relevant
    properties, but non-codegen overrides (capacities, retry policy,
    spill knobs…) still shape execution — only sessions with IDENTICAL
    overrides may share a dispatch.
    """
    return tuple(
        sorted((k, repr(v)) for k, v in session.properties.items())
    )


class BatchCollector:
    """Groups compatible in-flight queries into stacked dispatches."""

    def __init__(self, engine) -> None:
        self._engine = engine
        self._lock = threading.Lock()
        self._groups: dict[tuple, _Group] = {}

    # --- admission --------------------------------------------------------

    def submit(self, entry, plan, session, params, query_id):
        """Join (or open) the batch for this program-cache entry; blocks
        until this member's result is ready. Called on the query's own
        dispatch thread from ``Engine._dispatch_parsed``."""
        window_ms = int(session.get("batch_window_ms"))
        max_size = max(1, int(session.get("batch_max_size")))
        member = _Member(query_id, session, list(params), time.monotonic())
        key = (id(entry), _session_signature(session))
        with self._lock:
            group = self._groups.get(key)
            if group is not None and not group.closed:
                group.members.append(member)
                if len(group.members) >= max_size:
                    group.closed = True
                    del self._groups[key]
                    group.full.set()
                group = None  # follower: just wait below
                leader = False
            else:
                group = _Group(entry, plan)
                group.members.append(member)
                if max_size <= 1:
                    group.closed = True  # degenerate: never collects
                else:
                    self._groups[key] = group
                leader = True
        if leader:
            if not group.closed:
                # hold the window open; a size-triggered flush wakes us
                # early (deterministic for tests: max_size members ==
                # immediate dispatch, no timing dependence)
                group.full.wait(window_ms / 1000.0)
                with self._lock:
                    if not group.closed:
                        group.closed = True
                        if self._groups.get(key) is group:
                            del self._groups[key]
            self._run_group(group)
        member.done.wait()
        if member.error is not None:
            raise member.error
        return member.result

    # --- execution (leader thread only) -----------------------------------

    def _run_group(self, group: _Group) -> None:
        engine = self._engine
        entry = group.entry
        members = group.members
        k = len(members)
        exec_start = time.monotonic()
        try:
            # same discipline as the single-query path: the entry lock
            # serializes executors over the shared program store and
            # capacity objects. Blocking here is fine — followers are
            # parked on their events, not on this lock, and unrelated
            # programs use different entries. The concurrency lint's
            # CONC001/LOCK002 hits on this block are baselined
            # (lint/baseline.json notes): moving execution outside the
            # lock would let a second leader re-collect the same window.
            with entry["lock"]:
                if entry["plan"] is None:
                    entry["plan"] = group.plan
                plan = entry["plan"]
                programs = entry["programs"]
                if k == 1:
                    m = members[0]
                    try:
                        m.result = engine._execute_query_plan(
                            plan, m.session, query_id=m.query_id,
                            programs=programs, params=m.params,
                        )
                    except BaseException as e:  # noqa: BLE001
                        m.error = e
                elif not members[0].params:
                    # no hoisted literals: the K members are the SAME
                    # query — run once, replicate the result
                    self._run_replicated(plan, programs, members, exec_start)
                else:
                    self._run_batched(plan, programs, members, exec_start)
        except BaseException as e:  # noqa: BLE001 — never strand a member
            for m in members:
                if m.result is None and m.error is None:
                    m.error = e
        finally:
            dur_ms = (time.monotonic() - exec_start) * 1000.0
            self._observe(members, dur_ms)
            for m in members:
                m.done.set()

    def _run_replicated(self, plan, programs, members, exec_start) -> None:
        leader = members[0]
        try:
            res = self._engine._execute_query_plan(
                plan, leader.session, query_id=leader.query_id,
                programs=programs, params=leader.params,
            )
        except BaseException as e:  # noqa: BLE001
            # identical queries: the failure IS each member's failure
            for m in members:
                m.error = e
            return
        stats = self._batch_stats(members, exec_start)
        for m, bs in zip(members, stats):
            m.result = dataclasses.replace(res, batch_stats=bs)

    def _run_batched(self, plan, programs, members, exec_start) -> None:
        engine = self._engine
        try:
            results = engine._execute_query_plan_batched(
                plan,
                members[0].session,
                [m.query_id for m in members],
                [m.params for m in members],
                programs=programs,
            )
        except Exception:  # noqa: BLE001 — BatchUnsupported, capacity, …
            # fall back to K sequential runs; a failing member fails
            # alone without poisoning its batchmates
            for m in members:
                try:
                    m.result = engine._execute_query_plan(
                        plan, m.session, query_id=m.query_id,
                        programs=programs, params=m.params,
                    )
                except BaseException as e:  # noqa: BLE001
                    m.error = e
            return
        stats = self._batch_stats(members, exec_start)
        for m, res, bs in zip(members, results, stats):
            m.result = dataclasses.replace(res, batch_stats=bs)

    # --- surfacing --------------------------------------------------------

    def _batch_stats(self, members, exec_start) -> list[dict]:
        # wait = enqueue → dispatch start, NOT including execution: this
        # is the latency the window itself cost the member
        k = len(members)
        return [
            {
                "batchedQueries": k,
                "batchSize": k,
                "batchWaitMs": round((exec_start - m.enq_mono) * 1000.0, 1),
            }
            for m in members
        ]

    def _observe(self, members, dur_ms: float) -> None:
        from trino_tpu.obs.metrics import get_registry
        from trino_tpu.obs.trace import get_tracer

        k = len(members)
        # size=1 groups count too: mean batch size over the bench is
        # sum(size*n)/sum(n), so solo dispatches must stay in the
        # denominator
        get_registry().counter(
            # size is bounded by batch_max_queries (a handful of values)
            "trino_tpu_batched_dispatches_total", size=str(k)  # lint: ignore[OBS001]
        ).inc()
        if k < 2:
            return
        tracer = get_tracer()
        leader_qid = members[0].query_id
        for m in members:
            # one span per member on its OWN trace so the web-UI
            # waterfall shows which queries shared the dispatch
            tracer.record(
                "batched_dispatch",
                dur_ms,
                attrs={"batchSize": k, "batchLeader": leader_qid},
                trace_id=m.query_id,
            )
