"""Datetime formatting: format_datetime (Joda patterns) and date_format
(MySQL patterns).

Reference: ``operator/scalar/DateTimeFunctions.java`` (formatDatetime with
Joda ``DateTimeFormatter``; dateFormat with the MySQL ``%``-pattern set).

TPU-first execution: dates/timestamps are integer storage on device; string
rendering happens host-side over the *unique* values only (O(distinct), the
same cost model as dictionary string transforms), producing a
dictionary-encoded varchar column.
"""

from __future__ import annotations

import datetime
import re

import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import Column, Dictionary

_JODA_MAP = [
    ("yyyy", "%Y"), ("yy", "%y"), ("MMMM", "%B"), ("MMM", "%b"),
    ("MM", "%m"), ("M", "%-m"), ("dd", "%d"), ("d", "%-d"),
    ("EEEE", "%A"), ("EEE", "%a"), ("HH", "%H"), ("H", "%-H"),
    ("hh", "%I"), ("mm", "%M"), ("m", "%-M"), ("ss", "%S"), ("s", "%-S"),
    ("a", "%p"), ("DDD", "%j"),
]

_MYSQL_MAP = {
    "%Y": "%Y", "%y": "%y", "%M": "%B", "%b": "%b", "%m": "%m",
    "%c": "%-m", "%d": "%d", "%e": "%-d", "%H": "%H", "%k": "%-H",
    "%h": "%I", "%i": "%M", "%s": "%S", "%S": "%S", "%W": "%A",
    "%a": "%a", "%j": "%j", "%p": "%p", "%T": "%H:%M:%S", "%%": "%%",
}


def _joda_to_strftime(pattern: str) -> str:
    out = []
    i = 0
    while i < len(pattern):
        if pattern[i] == "'":
            j = pattern.find("'", i + 1)
            if j < 0:
                out.append(pattern[i + 1 :])
                break
            out.append(pattern[i + 1 : j].replace("%", "%%"))
            i = j + 1
            continue
        for tok, rep in _JODA_MAP:
            if pattern.startswith(tok, i):
                out.append(rep)
                i += len(tok)
                break
        else:
            out.append(pattern[i].replace("%", "%%"))
            i += 1
    return "".join(out)


def _mysql_to_strftime(pattern: str) -> str:
    out = []
    i = 0
    while i < len(pattern):
        if pattern[i] == "%" and i + 1 < len(pattern):
            tok = pattern[i : i + 2]
            out.append(_MYSQL_MAP.get(tok, tok))
            i += 2
        else:
            out.append(pattern[i].replace("%", "%%"))
            i += 1
    return "".join(out)


def _strftime(dt: datetime.datetime, fmt: str) -> str:
    # "%-m"-style (no zero pad) is GNU-only; emulate portably
    def repl(m):
        val = {
            "m": dt.month, "d": dt.day, "H": dt.hour, "M": dt.minute,
            "S": dt.second,
        }[m.group(1)]
        return str(val)

    fmt = re.sub(r"%-([mdHMS])", repl, fmt)
    return dt.strftime(fmt)


def lower_datetime_format_calls(expr, columns):
    """Rewrite format_datetime/date_format Calls (at any nesting depth)
    into InputRefs to synthetic rendered columns (appended to ``columns``,
    mutated) — the same shape as strings.lower_string_calls, so nested
    uses like upper(format_datetime(..)) and WHERE predicates work."""
    from trino_tpu.compiler import ExprCompiler
    from trino_tpu.ir import Call, SpecialForm, input_ref

    def walk(e):
        if isinstance(e, Call):
            args = tuple(walk(a) for a in e.args)
            e = Call(type=e.type, name=e.name, args=args)
            if e.name in ("format_datetime", "date_format"):
                ec = ExprCompiler(columns)
                data, valid = ec.evaluate(e.args[0])
                col = format_datetime_column(
                    np.asarray(data),
                    np.asarray(valid),
                    e.args[0].type,
                    str(e.args[1].value),
                    "joda" if e.name == "format_datetime" else "mysql",
                )
                columns.append(col)
                return input_ref(len(columns) - 1, T.VARCHAR)
            return e
        if isinstance(e, SpecialForm):
            return SpecialForm(
                type=e.type, form=e.form, args=tuple(walk(a) for a in e.args)
            )
        return e

    return walk(expr)


def format_datetime_column(
    data: np.ndarray,
    valid: np.ndarray,
    src_type: T.SqlType,
    pattern: str,
    dialect: str,
) -> Column:
    """Render a DATE/TIMESTAMP column to a dictionary varchar column."""
    fmt = (
        _joda_to_strftime(pattern)
        if dialect == "joda"
        else _mysql_to_strftime(pattern)
    )
    uniq, inverse = np.unique(np.asarray(data), return_inverse=True)
    epoch = datetime.datetime(1970, 1, 1)
    values = []
    for u in uniq:
        if isinstance(src_type, T.DateType):
            dt = epoch + datetime.timedelta(days=int(u))
        else:
            dt = epoch + datetime.timedelta(microseconds=int(u))
        values.append(_strftime(dt, fmt))
    d, codes0 = Dictionary.from_strings(values)
    codes = np.asarray(codes0)[inverse].astype(np.int32)
    v = np.asarray(valid)
    codes = np.where(v, codes, -1).astype(np.int32)
    return Column(T.VARCHAR, codes, None if v.all() else v, d)
