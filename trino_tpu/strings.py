"""String function lowering — dictionary-transform execution.

Reference: ``operator/scalar/StringFunctions.java:71-86`` (upper/lower/trim/
substr/replace/concat/strpos/...).

TPU-first: varchar columns are dictionary-encoded (int32 codes on device +
host dictionary, see :mod:`trino_tpu.columnar`). A string->string scalar
function therefore never touches the device: it maps the *dictionary values*
on the host (O(|dict|) Python work) and re-uses the device code array
unchanged. ``upper(c)`` over a billion rows costs one dictionary pass.
String->numeric functions (length, strpos, starts_with) become per-code
lookup tables gathered on device (:mod:`trino_tpu.compiler`).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import Column, Dictionary
from trino_tpu.ir import Call, Constant, InputRef, RowExpr, SpecialForm, input_ref

_CROSS_DICT_CAP = 1 << 18


def _substr(s: str, start: int, length: Optional[int] = None) -> str:
    # Trino SUBSTR semantics: 1-based; negative counts from the end; 0 -> ''
    if start == 0:
        return ""
    if start > 0:
        out = s[start - 1 :]
    else:
        out = s[start:] if -start <= len(s) else ""
    if length is not None:
        out = out[: max(length, 0)]
    return out


def _replace(s: str, search: str, repl: str) -> str:
    if search == "":
        return s  # Trino: empty search returns the string unchanged
    return s.replace(search, repl)


def _lpad(s: str, size: int, pad: str) -> str:
    if size <= len(s):
        return s[:size]
    fill = (pad * ((size - len(s)) // max(len(pad), 1) + 1))[: size - len(s)]
    return fill + s


def _rpad(s: str, size: int, pad: str) -> str:
    if size <= len(s):
        return s[:size]
    fill = (pad * ((size - len(s)) // max(len(pad), 1) + 1))[: size - len(s)]
    return s + fill


def _split_part(s: str, delim: str, index: int) -> str:
    if delim == "":
        return ""
    parts = s.split(delim)
    return parts[index - 1] if 1 <= index <= len(parts) else ""


def _repeat(s: str, k: int) -> str:
    return s * int(k)


def _unary_fn(name: str) -> Callable[[str], str]:
    return {
        "upper": str.upper,
        "lower": str.lower,
        "trim": str.strip,
        "ltrim": str.lstrip,
        "rtrim": str.rstrip,
        "reverse": lambda s: s[::-1],
    }[name]


STRING_TRANSFORMS = {
    "upper", "lower", "trim", "ltrim", "rtrim", "reverse",
    "substr", "replace", "lpad", "rpad", "split_part", "concat", "repeat",
    "regexp_replace", "regexp_extract",
    "json_extract_scalar", "json_extract",
}


_JSON_SEGMENT = __import__("re").compile(
    r"\.(?P<key>[A-Za-z_][A-Za-z0-9_]*)|\[(?P<idx>\d+)\]|\[\"(?P<qkey>[^\"]+)\"\]"
)


def _json_path_get(doc, path: str):
    """Walk a $.a.b[1] JSON path. Returns (found, value). The path must
    parse completely — garbage segments yield not-found, never a parent
    value."""
    if not path.startswith("$"):
        return False, None
    cur = doc
    pos = 1
    while pos < len(path):
        m = _JSON_SEGMENT.match(path, pos)
        if m is None:
            return False, None  # invalid path segment
        pos = m.end()
        key = m.group("key") or m.group("qkey")
        if key is not None:
            if not isinstance(cur, dict) or key not in cur:
                return False, None
            cur = cur[key]
        else:
            i = int(m.group("idx"))
            if not isinstance(cur, list) or i >= len(cur):
                return False, None
            cur = cur[i]
    return True, cur


def _const_args(args) -> list:
    out = []
    for a in args:
        if not isinstance(a, Constant) or a.value is None:
            raise NotImplementedError(
                "string function arguments beyond the first must be literals"
            )
        v = a.value
        if isinstance(a.type, T.DecimalType):
            v = v // a.type.unscale
        out.append(v)
    return out


def transformed_column(base: Column, new_values: list[Optional[str]]) -> Column:
    """Column with same rows but transformed dictionary values. Duplicate
    values after transformation (upper('a')==upper('A')) are deduplicated
    with a device-side code remap so group-by/join-by-code stays correct.
    A ``None`` dictionary value (e.g. regexp_extract no-match) maps its
    rows to NULL (code -1, valid cleared)."""
    has_null = any(v is None for v in new_values)
    if not has_null and len(set(new_values)) == len(new_values):
        return Column(T.VARCHAR, base.data, base.valid, Dictionary(new_values))
    uniq: list[str] = []
    index: dict[str, int] = {}
    remap = np.empty(len(new_values), dtype=np.int32)
    for i, v in enumerate(new_values):
        if v is None:
            remap[i] = -1
            continue
        code = index.get(v)
        if code is None:
            code = len(uniq)
            index[v] = code
            uniq.append(v)
        remap[i] = code
    r = jnp.asarray(remap)
    codes = jnp.where(base.data >= 0, r[jnp.maximum(base.data, 0)], -1).astype(
        jnp.int32
    )
    valid = base.valid_mask() & (codes >= 0) if has_null else base.valid
    d = Dictionary(uniq)
    d._index = index
    return Column(T.VARCHAR, codes, valid, d)


def lower_string_calls(expr: RowExpr, columns: list[Column]) -> RowExpr:
    """Rewrite string->string Calls into InputRefs to synthetic
    dictionary-transformed columns (appended to ``columns``, mutated).
    Bottom-up, so ``upper(trim(x))`` chains compose on the host."""

    def add_column(col: Column) -> InputRef:
        columns.append(col)
        return input_ref(len(columns) - 1, T.VARCHAR)

    def walk(e: RowExpr) -> RowExpr:
        if isinstance(e, Call):
            args = tuple(walk(a) for a in e.args)
            e = Call(type=e.type, name=e.name, args=args)
            if e.name in STRING_TRANSFORMS and T.is_string(e.type):
                return lower_one(e)
            return e
        if isinstance(e, SpecialForm):
            return SpecialForm(
                type=e.type, form=e.form, args=tuple(walk(a) for a in e.args)
            )
        return e

    def lower_one(e: Call) -> RowExpr:
        name = e.name
        if name == "concat":
            return lower_concat(e)
        base = e.args[0]
        if isinstance(base, Constant):
            # constant folding on the host
            if base.value is None:
                return Constant(type=T.VARCHAR, value=None)
            v = str(base.value)
            rest = _const_args(e.args[1:])
            return Constant(type=T.VARCHAR, value=_apply(name, v, rest))
        if not isinstance(base, InputRef):
            raise NotImplementedError(f"{name} over non-column expression")
        col = columns[base.channel]
        d = col.dictionary or Dictionary([])
        rest = _const_args(e.args[1:])
        new_values = [_apply(name, v, rest) for v in d.values]
        return add_column(transformed_column(col, new_values))

    def _coalesce_to_ref(a: RowExpr) -> RowExpr:
        """COALESCE(string_col, 'const') -> synthetic column with the
        constant folded into the dictionary (nulls remapped to its code)."""
        if not (
            isinstance(a, SpecialForm)
            and a.form == "coalesce"
            and len(a.args) == 2
            and isinstance(a.args[0], InputRef)
            and isinstance(a.args[1], Constant)
            and a.args[1].value is not None
        ):
            return a
        col = columns[a.args[0].channel]
        d = col.dictionary or Dictionary([])
        fill = str(a.args[1].value)
        values = list(d.values)
        try:
            fill_code = values.index(fill)
        except ValueError:
            fill_code = len(values)
            values = values + [fill]
        valid = col.valid_mask() & (jnp.asarray(col.data) >= 0)
        codes = jnp.where(valid, jnp.maximum(col.data, 0), fill_code).astype(
            jnp.int32
        )
        return add_column(Column(T.VARCHAR, codes, None, Dictionary(values)))

    def lower_concat(e: Call) -> RowExpr:
        parts = []  # "const" str | ("ref", channel)
        channels: list[int] = []
        any_null_const = False
        for a in e.args:
            a = _coalesce_to_ref(a)
            if isinstance(a, Constant):
                if a.value is None:
                    any_null_const = True
                parts.append(str(a.value) if a.value is not None else "")
            elif isinstance(a, InputRef):
                parts.append(("ref", a.channel))
                if a.channel not in channels:
                    channels.append(a.channel)
            else:
                raise NotImplementedError("concat over complex expression")
        if any_null_const:
            return Constant(type=T.VARCHAR, value=None)
        if not channels:
            return Constant(type=T.VARCHAR, value="".join(parts))
        if len(channels) == 1:
            ch = channels[0]
            col = columns[ch]
            d = col.dictionary or Dictionary([])
            new_values = [
                "".join(p if isinstance(p, str) else v for p in parts)
                for v in d.values
            ]
            return add_column(transformed_column(col, new_values))
        if len(channels) == 2:
            ca, cb = columns[channels[0]], columns[channels[1]]
            da = ca.dictionary or Dictionary([])
            db = cb.dictionary or Dictionary([])
            if max(len(da), 1) * max(len(db), 1) > _CROSS_DICT_CAP:
                # big cross (name x name): materialize per ROW instead of
                # per dictionary pair — O(rows) host work, bounded output
                import numpy as np

                codes_a = np.asarray(ca.data)
                codes_b = np.asarray(cb.data)
                valid = np.asarray(ca.valid_mask() & cb.valid_mask()) & (
                    codes_a >= 0
                ) & (codes_b >= 0)
                row_strings = []
                for i in range(len(codes_a)):
                    if not valid[i]:
                        row_strings.append("")
                        continue
                    va = da.decode(int(codes_a[i])) or ""
                    vb = db.decode(int(codes_b[i])) or ""
                    row_strings.append(
                        "".join(
                            p
                            if isinstance(p, str)
                            else (va if p[1] == channels[0] else vb)
                            for p in parts
                        )
                    )
                d, codes = Dictionary.from_strings(row_strings)
                codes = np.where(valid, codes, -1).astype(np.int32)
                return add_column(Column(T.VARCHAR, jnp.asarray(codes),
                                         jnp.asarray(valid), d))
            values = []
            for va in da.values:
                for vb in db.values:
                    values.append(
                        "".join(
                            p
                            if isinstance(p, str)
                            else (va if p[1] == channels[0] else vb)
                            for p in parts
                        )
                    )
            nb = max(len(db), 1)
            codes = jnp.maximum(ca.data, 0) * nb + jnp.maximum(cb.data, 0)
            valid = ca.valid_mask() & cb.valid_mask() & (ca.data >= 0) & (cb.data >= 0)
            valid_np = valid
            return add_column(
                Column(T.VARCHAR, codes.astype(jnp.int32), valid_np, Dictionary(values))
            )
        raise NotImplementedError("concat over >2 distinct string columns")

    def _apply(name: str, v: str, rest: list) -> str:
        if name in ("upper", "lower", "trim", "ltrim", "rtrim", "reverse"):
            return _unary_fn(name)(v)
        if name == "substr":
            return _substr(v, int(rest[0]), int(rest[1]) if len(rest) > 1 else None)
        if name == "replace":
            return _replace(v, str(rest[0]), str(rest[1]) if len(rest) > 1 else "")
        if name == "lpad":
            return _lpad(v, int(rest[0]), str(rest[1]) if len(rest) > 1 else " ")
        if name == "rpad":
            return _rpad(v, int(rest[0]), str(rest[1]) if len(rest) > 1 else " ")
        if name == "split_part":
            return _split_part(v, str(rest[0]), int(rest[1]))
        if name == "repeat":
            return _repeat(v, int(rest[0]))
        if name == "regexp_replace":
            import re as _re

            repl = str(rest[1]) if len(rest) > 1 else ""
            # Trino replacement uses $N group refs; Python uses \\N.
            # Escape literal backslashes, convert $N, leave lone $ literal.
            py_repl = repl.replace("\\", "\\\\")
            py_repl = _re.sub(r"\$(\d+)", r"\\\1", py_repl)
            return _re.sub(str(rest[0]), py_repl, v)
        if name in ("json_extract_scalar", "json_extract"):
            import json as _json

            try:
                doc = _json.loads(v)
            except ValueError:
                return None
            found, out = _json_path_get(doc, str(rest[0]))
            if not found:
                return None
            if name == "json_extract":
                return _json.dumps(out, separators=(",", ":"))
            # scalar: NULL for objects/arrays (reference semantics)
            if isinstance(out, (dict, list)):
                return None
            if out is None:
                return None
            if isinstance(out, bool):
                return "true" if out else "false"
            return str(out)
        if name == "regexp_extract":
            import re as _re

            # Reference semantics: NULL on no match and for a
            # non-participating group (not empty string).
            m = _re.search(str(rest[0]), v)
            if m is None:
                return None
            group = int(rest[1]) if len(rest) > 1 else 0
            return m.group(group)
        raise AssertionError(name)

    return walk(expr)
