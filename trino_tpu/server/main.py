"""Server entry point: ``python -m trino_tpu.server.main``.

Reference: ``server/Server.java:73`` — one binary, coordinator vs worker by
config. Workers take ``--discovery`` pointing at the coordinator and
announce themselves (DiscoveryNodeManager analog in server/cluster.py).
"""

from __future__ import annotations

import argparse
import signal
import sys
import time


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="trino-tpu server")
    parser.add_argument("--role", choices=["coordinator", "worker"], default="coordinator")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--node-id", default=None)
    parser.add_argument("--discovery", default=None, help="coordinator URI (workers)")
    parser.add_argument(
        "--platform",
        default=None,
        help="force a JAX platform (e.g. cpu) before engine start",
    )
    parser.add_argument(
        "--spmd-coordinator",
        default=None,
        help="jax.distributed coordinator host:port (enables multi-host SPMD)",
    )
    parser.add_argument("--spmd-procs", type=int, default=0)
    parser.add_argument("--spmd-rank", type=int, default=0)
    args = parser.parse_args(argv)

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    if args.spmd_coordinator:
        # must run before any jax computation initializes backends
        from trino_tpu.parallel.spmd import initialize_spmd

        initialize_spmd(args.spmd_coordinator, args.spmd_procs, args.spmd_rank)

    from trino_tpu.server.http import TrinoTpuServer

    server = TrinoTpuServer(
        host=args.host,
        port=args.port,
        role=args.role,
        node_id=args.node_id,
        discovery_uri=args.discovery,
        spmd=bool(args.spmd_coordinator),
    )
    server.start()
    # parent supervisors (tests, orchestration) read this line
    print(f"LISTENING {server.base_uri}", flush=True)

    stop = {"flag": False}

    def on_term(_sig, _frm):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    try:
        while not stop["flag"]:
            time.sleep(0.2)
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
