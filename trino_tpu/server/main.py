"""Server entry point: ``python -m trino_tpu.server.main``.

Reference: ``server/Server.java:73`` — one binary, coordinator vs worker by
config. Workers take ``--discovery`` pointing at the coordinator and
announce themselves (DiscoveryNodeManager analog in server/cluster.py).
"""

from __future__ import annotations

import argparse
import signal
import sys
import time


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="trino-tpu server")
    parser.add_argument("--role", choices=["coordinator", "worker"], default="coordinator")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--node-id", default=None)
    parser.add_argument("--discovery", default=None, help="coordinator URI (workers)")
    parser.add_argument(
        "--platform",
        default=None,
        help="force a JAX platform (e.g. cpu) before engine start",
    )
    parser.add_argument(
        "--spmd-coordinator",
        default=None,
        help="jax.distributed coordinator host:port (enables multi-host SPMD)",
    )
    parser.add_argument("--spmd-procs", type=int, default=0)
    parser.add_argument("--spmd-rank", type=int, default=0)
    parser.add_argument(
        "--catalog",
        action="append",
        default=[],
        help="register a catalog: name=kind[:arg] (etc/catalog analog)",
    )
    parser.add_argument(
        "--cluster-memory-limit-bytes",
        type=int,
        default=None,
        help="coordinator-enforced cluster-wide memory ceiling",
    )
    parser.add_argument(
        "--max-inflight-requests",
        type=int,
        default=None,
        help="global ceiling on concurrently handled external requests"
        " (excess shed with 503 + Retry-After)",
    )
    parser.add_argument(
        "--tenant-rate-limit-qps",
        type=float,
        default=None,
        help="per-tenant statement token-bucket refill rate (0 disables)",
    )
    parser.add_argument(
        "--client-timeout-s",
        type=float,
        default=None,
        help="cancel a query unpolled by its client for this long",
    )
    parser.add_argument(
        "--result-page-max-bytes",
        type=int,
        default=None,
        help="byte budget per streamed result page (0 = materialized)",
    )
    args = parser.parse_args(argv)

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    if args.spmd_coordinator:
        # must run before any jax computation initializes backends
        from trino_tpu.parallel.spmd import initialize_spmd

        initialize_spmd(args.spmd_coordinator, args.spmd_procs, args.spmd_rank)

    from trino_tpu.server.http import TrinoTpuServer

    engine = None
    if args.catalog:
        from trino_tpu.connectors.api import register_catalog_spec
        from trino_tpu.engine import Engine

        engine = Engine()
        for spec in args.catalog:
            register_catalog_spec(engine.catalogs, spec)

    server_config = None
    overrides = {
        "max_inflight_requests": args.max_inflight_requests,
        "tenant_rate_limit_qps": args.tenant_rate_limit_qps,
        "client_timeout_s": args.client_timeout_s,
        "result_page_max_bytes": args.result_page_max_bytes,
    }
    if any(v is not None for v in overrides.values()):
        from trino_tpu.config import ServerConfig

        server_config = ServerConfig(
            **{k: v for k, v in overrides.items() if v is not None}
        )

    server = TrinoTpuServer(
        engine=engine,
        host=args.host,
        port=args.port,
        role=args.role,
        node_id=args.node_id,
        discovery_uri=args.discovery,
        spmd=bool(args.spmd_coordinator),
        cluster_memory_limit_bytes=args.cluster_memory_limit_bytes,
        server_config=server_config,
    )
    server.start()
    # parent supervisors (tests, orchestration) read this line
    print(f"LISTENING {server.base_uri}", flush=True)

    stop = {"flag": False}

    def on_term(_sig, _frm):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    try:
        # a drained worker (PUT /v1/info/state SHUTTING_DOWN) stops its
        # server itself; the process must then exit so rolling restarts
        # can respawn it
        while not stop["flag"] and server.state != "STOPPED":
            time.sleep(0.2)
    finally:
        if server.state != "STOPPED":
            server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
