"""Non-blocking event-loop HTTP tier for the trino-tpu front door.

The coordinator's serving edge must survive thousands of idle ``nextUri``
pollers without spending an OS thread per connection.  This module provides
the stdlib-only (``selectors``) machinery the server builds on:

- :class:`EventLoop` — a single-threaded reactor with thread-safe
  ``call_soon`` and heap-scheduled ``call_later`` timers.
- :class:`HttpConnection` — a per-connection state machine
  (read head -> read body -> handle -> write -> keep-alive) over a
  non-blocking socket.  Long-poll handlers park a :class:`Responder`
  instead of a thread; completions marshal back onto the loop.
- :class:`EventLoopHttpServer` — accept loop, connection registry and a
  periodic sweep enforcing read/idle/write timeouts (slowloris defence).
- :class:`TokenBucket` / :class:`TenantRateLimiter` — per-tenant QPS
  shedding for the robustness layer.
- :func:`parse_max_wait` — the one shared parse/clamp/NaN-guard for every
  ``maxWait``-style knob (previously duplicated across handler paths).

Nothing in this module knows about Trino routes; ``server/http.py`` wires
the actual protocol on top.
"""

from __future__ import annotations

import collections
import heapq
import itertools
import json
import os
import selectors
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "EventLoop",
    "EventLoopHttpServer",
    "Headers",
    "HttpConnection",
    "Request",
    "Responder",
    "Response",
    "TenantRateLimiter",
    "TokenBucket",
    "assert_not_loop_thread",
    "current_thread_in_loop",
    "json_response",
    "parse_max_wait",
]

# --- loop-thread discipline helpers ----------------------------------------
#
# Idents of threads currently running an EventLoop (there can be several in
# tests). Blocking code paths — dispatch-pool workers, ManagedQuery.run —
# assert they are NOT on one of these; loop-only paths (send_response)
# assert they ARE. Misuse raises under pytest / TT_LOOP_ASSERTS=raise and
# only bumps the trino_tpu_loop_thread_violations_total counter in
# production, so a discipline bug degrades observability, not the service.

_LOOP_THREAD_IDS: set[int] = set()


def current_thread_in_loop() -> bool:
    """True when the calling thread is running any EventLoop."""
    return threading.get_ident() in _LOOP_THREAD_IDS


def _strict_thread_asserts() -> bool:
    mode = os.environ.get("TT_LOOP_ASSERTS", "")
    if mode == "raise":
        return True
    if mode == "count":
        return False
    return "PYTEST_CURRENT_TEST" in os.environ


def _loop_thread_violation(what: str) -> None:
    if _strict_thread_asserts():
        raise RuntimeError(f"loop-thread discipline violation: {what}")
    try:
        from trino_tpu.obs.metrics import get_registry

        get_registry().counter(
            "trino_tpu_loop_thread_violations_total"
        ).inc()
    except Exception:  # noqa: BLE001 — observability must not break serving
        pass


def assert_not_loop_thread(what: str = "blocking call") -> bool:
    """Guard for code that may block: must not run on any loop thread."""
    if not current_thread_in_loop():
        return True
    _loop_thread_violation(f"{what} on an event-loop thread")
    return False

# Hard framing limits; requests beyond these are refused outright.
MAX_HEADER_BYTES = 64 << 10
MAX_BODY_BYTES = 512 << 20  # spool pages can be large, but not unbounded
RECV_CHUNK = 64 << 10

_STATUS_REASONS = {
    200: "OK",
    204: "No Content",
    400: "Bad Request",
    401: "Unauthorized",
    404: "Not Found",
    405: "Method Not Allowed",
    408: "Request Timeout",
    413: "Payload Too Large",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
}


def parse_max_wait(
    raw: Any,
    default: float = 1.0,
    lo: float = 0.0,
    hi: float = 30.0,
) -> float:
    """Parse a ``maxWait``-style value and clamp it to ``[lo, hi]``.

    Accepts a float, an int, or a numeric string.  ``None``, garbage, NaN
    and infinities all fall back to ``default`` (itself clamped), so a
    malicious ``maxWait=nan`` can never wedge a poll loop.
    """
    value = default
    if raw is not None:
        try:
            value = float(raw)
        except (TypeError, ValueError):
            value = default
    if value != value or value in (float("inf"), float("-inf")):  # NaN/inf guard
        value = default
    if value != value:  # default itself was NaN
        value = lo
    return min(max(value, lo), hi)


# ---------------------------------------------------------------------------
# Request / response primitives
# ---------------------------------------------------------------------------


class Headers:
    """Case-insensitive header multimap (last value wins, like http.client)."""

    __slots__ = ("_items",)

    def __init__(self) -> None:
        self._items: Dict[str, str] = {}

    def add(self, name: str, value: str) -> None:
        self._items[name.lower()] = value

    def get(self, name: str, default: Optional[str] = None) -> Optional[str]:
        return self._items.get(name.lower(), default)

    def __contains__(self, name: str) -> bool:
        return name.lower() in self._items

    def items(self):
        return self._items.items()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Headers({self._items!r})"


class Request:
    """A fully-framed HTTP request as parsed off the wire."""

    __slots__ = ("method", "target", "headers", "body", "version")

    def __init__(self, method: str, target: str, version: str, headers: Headers) -> None:
        self.method = method
        self.target = target
        self.version = version
        self.headers = headers
        self.body = b""


class Response:
    """An HTTP response to be serialized by the connection."""

    __slots__ = ("status", "body", "content_type", "headers")

    def __init__(
        self,
        status: int = 200,
        body: bytes = b"",
        content_type: str = "application/json",
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        self.status = status
        self.body = body
        self.content_type = content_type
        self.headers = headers or {}


def json_response(
    payload: Any,
    status: int = 200,
    headers: Optional[Dict[str, str]] = None,
) -> Response:
    body = json.dumps(payload).encode("utf-8")
    return Response(status, body, "application/json", headers)


class Responder:
    """One-shot, thread-safe completion handle for an in-flight request.

    Handlers may respond inline (on the loop) or from a pool thread later;
    either way the response is marshalled onto the event loop and written
    from there.  ``respond`` returns ``False`` if something already
    responded (e.g. a long-poll timer racing its wakeup listener).
    """

    __slots__ = ("_conn", "_done", "_lock")

    def __init__(self, conn: "HttpConnection") -> None:
        self._conn = conn
        self._done = False
        self._lock = threading.Lock()

    @property
    def done(self) -> bool:
        return self._done

    @property
    def connected(self) -> bool:
        return not self._conn.closed

    def respond(self, response: Response) -> bool:
        with self._lock:
            if self._done:
                return False
            self._done = True
        conn = self._conn
        conn.loop.call_soon(conn.send_response, self, response)
        return True


# ---------------------------------------------------------------------------
# Event loop
# ---------------------------------------------------------------------------


class Timer:
    """Cancellable handle returned by :meth:`EventLoop.call_later`."""

    __slots__ = ("when", "fn", "args", "cancelled")

    def __init__(self, when: float, fn: Callable, args: tuple) -> None:
        self.when = when
        self.fn = fn
        self.args = args
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class EventLoop:
    """Single-threaded selector reactor with timers and a wakeup pipe."""

    def __init__(self) -> None:
        self._selector = selectors.DefaultSelector()
        self._ready: "collections.deque[Tuple[Callable, tuple]]" = collections.deque()
        self._timers: List[Tuple[float, int, Timer]] = []
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._closed = False
        # Self-pipe so call_soon from foreign threads interrupts select().
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._woken = False
        self._selector.register(self._wake_r, selectors.EVENT_READ, self._drain_wakeup)

    # -- registration -----------------------------------------------------

    def register(self, sock: socket.socket, events: int, callback: Callable[[int], None]) -> None:
        self._selector.register(sock, events, callback)

    def modify(self, sock: socket.socket, events: int, callback: Callable[[int], None]) -> None:
        self._selector.modify(sock, events, callback)

    def unregister(self, sock: socket.socket) -> None:
        try:
            self._selector.unregister(sock)
        except (KeyError, ValueError):
            pass

    # -- scheduling -------------------------------------------------------

    def call_soon(self, fn: Callable, *args: Any) -> None:
        with self._lock:
            if self._closed:
                return
            self._ready.append((fn, args))
            wake = not self._woken
            self._woken = True
        if wake and threading.current_thread() is not self._thread:
            try:
                self._wake_w.send(b"\x00")
            except OSError:
                pass

    def call_later(self, delay: float, fn: Callable, *args: Any) -> Timer:
        timer = Timer(time.monotonic() + max(0.0, delay), fn, args)

        def _add() -> None:
            heapq.heappush(self._timers, (timer.when, next(self._seq), timer))

        if threading.current_thread() is self._thread:
            _add()
        else:
            self.call_soon(_add)
        return timer

    def in_loop(self) -> bool:
        return threading.current_thread() is self._thread

    def assert_loop_thread(self, what: str = "loop-only call") -> bool:
        """Guard for loop-affine code (connection I/O, timer wheel)."""
        if self.in_loop():
            return True
        _loop_thread_violation(f"{what} off the loop thread")
        return False

    def assert_not_loop_thread(self, what: str = "blocking call") -> bool:
        """Guard for blocking code handed off from this loop."""
        if not self.in_loop():
            return True
        _loop_thread_violation(f"{what} on the loop thread")
        return False

    # -- run / stop -------------------------------------------------------

    def run(self) -> None:
        self._thread = threading.current_thread()
        self._running = True
        ident = threading.get_ident()
        _LOOP_THREAD_IDS.add(ident)
        try:
            from trino_tpu.lint import lockdep

            lockdep.register_loop_thread(ident)
        except Exception:  # noqa: BLE001 — lockdep is optional tooling
            lockdep = None
        try:
            self._run()
        finally:
            _LOOP_THREAD_IDS.discard(ident)
            if lockdep is not None:
                lockdep.unregister_loop_thread(ident)

    def _run(self) -> None:
        while self._running:
            timeout = self._next_timeout()
            try:
                events = self._selector.select(timeout)
            except OSError:
                # A socket was closed underneath the selector; callbacks
                # unregister as they close, so just retry.
                events = []
            for key, mask in events:
                if not self._running:
                    break
                try:
                    key.data(mask)
                except Exception:
                    pass
            self._run_timers()
            self._run_ready()

    def stop(self) -> None:
        """Stop the loop from any thread (idempotent)."""
        def _halt() -> None:
            self._running = False

        self.call_soon(_halt)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._ready.clear()
        for sock in (self._wake_r, self._wake_w):
            try:
                sock.close()
            except OSError:
                pass
        try:
            self._selector.close()
        except OSError:
            pass

    # -- internals --------------------------------------------------------

    def _drain_wakeup(self, mask: int) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            pass

    def _next_timeout(self) -> Optional[float]:
        with self._lock:
            if self._ready:
                return 0.0
        while self._timers and self._timers[0][2].cancelled:
            heapq.heappop(self._timers)
        if not self._timers:
            return 1.0  # re-check _running periodically
        return max(0.0, self._timers[0][0] - time.monotonic())

    def _run_timers(self) -> None:
        now = time.monotonic()
        while self._timers and self._timers[0][0] <= now:
            _, _, timer = heapq.heappop(self._timers)
            if timer.cancelled:
                continue
            try:
                timer.fn(*timer.args)
            except Exception:
                pass

    def _run_ready(self) -> None:
        with self._lock:
            batch = list(self._ready)
            self._ready.clear()
            self._woken = False
        for fn, args in batch:
            try:
                fn(*args)
            except Exception:
                pass


# ---------------------------------------------------------------------------
# HTTP connection state machine
# ---------------------------------------------------------------------------

_IDLE = "idle"        # keep-alive, waiting for the next request's first byte
_HEAD = "head"        # reading the request head
_BODY = "body"        # reading the request body
_HANDLING = "handling"  # request dispatched, awaiting a Responder
_WRITING = "writing"  # flushing the serialized response
_CLOSED = "closed"


class HttpConnection:
    """One client connection driven entirely by the event loop."""

    def __init__(self, server: "EventLoopHttpServer", sock: socket.socket) -> None:
        self.server = server
        self.loop = server.loop
        self.sock = sock
        self.state = _IDLE
        self.closed = False
        self._in = bytearray()
        self._out = bytearray()
        self._need_body = 0
        self._request: Optional[Request] = None
        self._keep_alive = True
        now = time.monotonic()
        self.last_activity = now          # any byte in or out
        self.request_started: Optional[float] = None  # first byte of current head
        self.write_stalled_since: Optional[float] = None
        self._events = selectors.EVENT_READ
        self.loop.register(sock, self._events, self._on_event)

    # -- selector callback ------------------------------------------------

    def _on_event(self, mask: int) -> None:
        if self.closed:
            return
        if mask & selectors.EVENT_WRITE:
            self._flush()
        if self.closed:
            return
        if mask & selectors.EVENT_READ:
            self._on_readable()

    def _on_readable(self) -> None:
        while True:
            try:
                chunk = self.sock.recv(RECV_CHUNK)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self.close()
                return
            if not chunk:
                # Peer closed.  A parked long-poll responder becomes a no-op.
                self.close()
                return
            self.last_activity = time.monotonic()
            self._in += chunk
            if len(chunk) < RECV_CHUNK:
                break
        if self.state in (_IDLE, _HEAD, _BODY):
            self._parse()

    # -- request framing --------------------------------------------------

    def _parse(self) -> None:
        while True:
            if self.state in (_IDLE, _HEAD):
                if self.state == _IDLE and self._in:
                    self.state = _HEAD
                    self.request_started = time.monotonic()
                end = self._in.find(b"\r\n\r\n")
                if end < 0:
                    if len(self._in) > MAX_HEADER_BYTES:
                        self._fail(400, "request head too large")
                    return
                head = bytes(self._in[: end])
                del self._in[: end + 4]
                if not self._parse_head(head):
                    return
            if self.state == _BODY:
                if len(self._in) < self._need_body:
                    return
                assert self._request is not None
                self._request.body = bytes(self._in[: self._need_body])
                del self._in[: self._need_body]
                self.state = _HANDLING
                self.request_started = None
                self._dispatch(self._request)
                # Pipelined bytes (rare) stay buffered until the response
                # is flushed; _finish_response resumes parsing.
                return
            if self.state != _HEAD:
                return

    def _parse_head(self, head: bytes) -> bool:
        try:
            text = head.decode("iso-8859-1")
            lines = text.split("\r\n")
            method, target, version = lines[0].split(" ", 2)
        except (UnicodeDecodeError, ValueError):
            self._fail(400, "malformed request line")
            return False
        headers = Headers()
        for line in lines[1:]:
            if not line:
                continue
            name, sep, value = line.partition(":")
            if not sep:
                self._fail(400, "malformed header")
                return False
            headers.add(name.strip(), value.strip())
        try:
            length = int(headers.get("Content-Length", "0") or "0")
        except ValueError:
            self._fail(400, "bad Content-Length")
            return False
        if length < 0 or length > MAX_BODY_BYTES:
            self._fail(413, "body too large")
            return False
        if headers.get("Transfer-Encoding"):
            self._fail(400, "chunked bodies unsupported")
            return False
        self._request = Request(method, target, version, headers)
        self._keep_alive = version != "HTTP/1.0" and (
            (headers.get("Connection") or "").lower() != "close"
        )
        self._need_body = length
        self.state = _BODY
        return True

    # -- dispatch / response ----------------------------------------------

    def _dispatch(self, request: Request) -> None:
        responder = Responder(self)
        try:
            self.server.handler(request, responder)
        except Exception as exc:
            responder.respond(
                json_response({"error": f"internal error: {exc}"}, 500)
            )

    def _fail(self, status: int, message: str) -> None:
        self.state = _HANDLING
        self._keep_alive = False
        Responder(self).respond(json_response({"error": message}, status))

    def send_response(self, responder: Responder, response: Response) -> None:
        """Loop-thread only (marshalled by Responder.respond)."""
        self.loop.assert_loop_thread("HttpConnection.send_response")
        if self.closed:
            return
        keep = self._keep_alive and response.status != 408
        reason = _STATUS_REASONS.get(response.status, "Unknown")
        lines = [f"HTTP/1.1 {response.status} {reason}"]
        if response.status != 204:
            lines.append(f"Content-Type: {response.content_type}")
            lines.append(f"Content-Length: {len(response.body)}")
        for name, value in response.headers.items():
            lines.append(f"{name}: {value}")
        lines.append(f"Connection: {'keep-alive' if keep else 'close'}")
        payload = ("\r\n".join(lines) + "\r\n\r\n").encode("iso-8859-1")
        if response.status != 204:
            payload += response.body
        self._keep_alive = keep
        self._out += payload
        self.state = _WRITING
        self._flush()

    def _flush(self) -> None:
        while self._out:
            try:
                sent = self.sock.send(self._out)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self.close()
                return
            if sent <= 0:
                break
            del self._out[: sent]
            self.last_activity = time.monotonic()
        if self._out:
            if self.write_stalled_since is None:
                self.write_stalled_since = time.monotonic()
            self._want(selectors.EVENT_READ | selectors.EVENT_WRITE)
            return
        self.write_stalled_since = None
        self._want(selectors.EVENT_READ)
        if self.state == _WRITING:
            self._finish_response()

    def _finish_response(self) -> None:
        if not self._keep_alive:
            self.close()
            return
        self.state = _IDLE
        self._request = None
        self.last_activity = time.monotonic()
        if self._in:
            self._parse()

    def _want(self, events: int) -> None:
        if self.closed or events == self._events:
            return
        self._events = events
        try:
            self.loop.modify(self.sock, events, self._on_event)
        except (KeyError, ValueError, OSError):
            self.close()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self.state = _CLOSED
        self.loop.unregister(self.sock)
        try:
            self.sock.close()
        except OSError:
            pass
        self.server._conns.discard(self)


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class EventLoopHttpServer:
    """Accepts connections and runs them on a single :class:`EventLoop`.

    ``handler(request, responder)`` is invoked on the loop thread for every
    framed request; it must never block (offload to a pool and respond via
    the responder).
    """

    def __init__(
        self,
        host: str,
        port: int,
        handler: Callable[[Request, Responder], None],
        *,
        max_connections: int = 4096,
        read_timeout_s: float = 30.0,
        idle_timeout_s: float = 300.0,
        write_timeout_s: float = 60.0,
        on_shed: Optional[Callable[[str], None]] = None,
    ) -> None:
        self.handler = handler
        self.max_connections = max_connections
        self.read_timeout_s = read_timeout_s
        self.idle_timeout_s = idle_timeout_s
        self.write_timeout_s = write_timeout_s
        self.on_shed = on_shed
        self.loop = EventLoop()
        self._conns: "set[HttpConnection]" = set()
        self._thread: Optional[threading.Thread] = None
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(256)
        self._sock.setblocking(False)
        self.server_address = self._sock.getsockname()
        self._closed = False

    @property
    def connection_count(self) -> int:
        return len(self._conns)

    def start(self) -> None:
        self.loop.register(self._sock, selectors.EVENT_READ, self._on_accept)
        self._thread = threading.Thread(
            target=self.loop.run, name="http-event-loop", daemon=True
        )
        self._thread.start()
        self._schedule_sweep()

    def close(self) -> None:
        """Stop the loop, close every connection and the listener."""
        if self._closed:
            return
        self._closed = True

        def _teardown() -> None:
            for conn in list(self._conns):
                conn.close()
            self.loop.unregister(self._sock)
            self.loop.stop()

        self.loop.call_soon(_teardown)
        if self._thread is not None and not self.loop.in_loop():
            self._thread.join(timeout=5.0)
        try:
            self._sock.close()
        except OSError:
            pass
        self.loop.close()

    # -- loop-side --------------------------------------------------------

    def _on_accept(self, mask: int) -> None:
        while True:
            try:
                csock, _addr = self._sock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            try:
                csock.setblocking(False)
                csock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                csock.close()
                continue
            if len(self._conns) >= self.max_connections:
                # Shed at the door with a minimal, pre-baked 503.
                body = b'{"error": "too many connections"}'
                head = (
                    b"HTTP/1.1 503 Service Unavailable\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Content-Length: %d\r\nRetry-After: 1\r\n"
                    b"Connection: close\r\n\r\n" % len(body)
                )
                try:
                    csock.send(head + body)
                except OSError:
                    pass
                csock.close()
                if self.on_shed is not None:
                    self.on_shed("connections")
                continue
            self._conns.add(HttpConnection(self, csock))

    def _schedule_sweep(self) -> None:
        interval = min(
            1.0,
            max(0.05, min(self.read_timeout_s, self.idle_timeout_s, self.write_timeout_s) / 4.0),
        )
        self.loop.call_later(interval, self._sweep)

    def _sweep(self) -> None:
        if self._closed:
            return
        now = time.monotonic()
        for conn in list(self._conns):
            if conn.closed:
                self._conns.discard(conn)
                continue
            # Slowloris: a request head/body trickling in too slowly.
            if (
                conn.state in (_HEAD, _BODY)
                and conn.request_started is not None
                and now - conn.request_started > self.read_timeout_s
            ):
                conn._fail(408, "request read timeout")
                continue
            # Write stall: peer stopped draining our response.
            if (
                conn.write_stalled_since is not None
                and now - conn.write_stalled_since > self.write_timeout_s
            ):
                conn.close()
                continue
            # Idle keep-alive past its welcome.
            if conn.state == _IDLE and now - conn.last_activity > self.idle_timeout_s:
                conn.close()
        self._schedule_sweep()


# ---------------------------------------------------------------------------
# Rate limiting
# ---------------------------------------------------------------------------


class TokenBucket:
    """Classic token bucket over a monotonic clock."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float) -> None:
        self.rate = max(1e-9, float(rate))
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self.stamp = time.monotonic()

    def try_acquire(self, now: Optional[float] = None) -> float:
        """Take one token.  Returns 0.0 on success, else seconds until
        the next token would be available (a Retry-After hint)."""
        if now is None:
            now = time.monotonic()
        elapsed = max(0.0, now - self.stamp)
        self.stamp = now
        self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return (1.0 - self.tokens) / self.rate


class TenantRateLimiter:
    """Per-tenant token buckets with bounded LRU occupancy."""

    def __init__(self, qps: float, burst: float, max_tenants: int = 10_000) -> None:
        self.qps = float(qps)
        self.burst = float(burst)
        self.max_tenants = max_tenants
        self._buckets: "collections.OrderedDict[str, TokenBucket]" = collections.OrderedDict()
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.qps > 0.0

    def try_acquire(self, tenant: str) -> float:
        """0.0 when admitted; otherwise a Retry-After hint in seconds."""
        if not self.enabled:
            return 0.0
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = TokenBucket(self.qps, self.burst)
                self._buckets[tenant] = bucket
                while len(self._buckets) > self.max_tenants:
                    self._buckets.popitem(last=False)
            else:
                self._buckets.move_to_end(tenant)
            return bucket.try_acquire()
