"""Listener-based finite state machines for queries.

Reference: ``core/trino-main/src/main/java/io/trino/execution/StateMachine.java``
(generic compare-and-set FSM with listeners) and ``QueryState`` /
``QueryStateMachine.java`` (QUEUED → ... → terminal).
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Callable, Generic, Optional, TypeVar

S = TypeVar("S")


class StateMachine(Generic[S]):
    """Thread-safe state holder with transition listeners and terminal
    states (mirrors StateMachine.java's setIf/addStateChangeListener)."""

    def __init__(self, name: str, initial: S, terminal: set[S]):
        self.name = name
        self._state = initial
        self._terminal = set(terminal)
        # reentrant: wait_for predicates may call back into get()/is_terminal()
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._listeners: list[Callable[[S], None]] = []

    def get(self) -> S:
        with self._lock:
            return self._state

    def is_terminal(self) -> bool:
        with self._lock:
            return self._state in self._terminal

    def compare_and_set(self, expected: S, new: S) -> bool:
        with self._lock:
            if self._state != expected or self._state in self._terminal:
                return False
            self._state = new
            listeners = list(self._listeners)
            self._cond.notify_all()
        for fn in listeners:
            fn(new)
        return True

    def set(self, new: S) -> bool:
        """Transition unless already terminal. Returns True on change."""
        with self._lock:
            if self._state in self._terminal or self._state == new:
                return False
            self._state = new
            listeners = list(self._listeners)
            self._cond.notify_all()
        for fn in listeners:
            fn(new)
        return True

    def add_listener(self, fn: Callable[[S], None]) -> None:
        with self._lock:
            self._listeners.append(fn)
            current = self._state
        fn(current)

    def remove_listener(self, fn: Callable[[S], None]) -> None:
        """Detach a listener (long-polls must not accumulate forever)."""
        with self._lock:
            try:
                self._listeners.remove(fn)
            except ValueError:
                pass

    def wait_for(self, predicate: Callable[[S], bool], timeout: float) -> S:
        """Block until predicate(state) or timeout (long-poll support)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while not predicate(self._state):
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._state in self._terminal:
                    break
                self._cond.wait(remaining)
            return self._state


class QueryState(str, enum.Enum):
    """Reference: ``execution/QueryState.java``."""

    QUEUED = "QUEUED"
    WAITING_FOR_RESOURCES = "WAITING_FOR_RESOURCES"
    PLANNING = "PLANNING"
    STARTING = "STARTING"
    RUNNING = "RUNNING"
    FINISHING = "FINISHING"
    FINISHED = "FINISHED"
    FAILED = "FAILED"
    CANCELED = "CANCELED"


TERMINAL_QUERY_STATES = {QueryState.FINISHED, QueryState.FAILED, QueryState.CANCELED}


def new_query_state_machine(query_id: str) -> StateMachine[QueryState]:
    return StateMachine(query_id, QueryState.QUEUED, TERMINAL_QUERY_STATES)


class TaskState:
    """Worker task states (reference: ``execution/TaskState.java``).

    Plain string constants — worker task state crosses the HTTP boundary
    as JSON, so the wire form IS the state. ``CANCELED_SPECULATIVE``
    marks the loser of a hedged (speculative) attempt pair: cancelled by
    the scheduler because a sibling finished first, not because the
    query failed — terminal and failed-for-consumers, but not an error.
    """

    RUNNING = "RUNNING"
    FINISHED = "FINISHED"
    FAILED = "FAILED"
    CANCELED = "CANCELED"
    CANCELED_SPECULATIVE = "CANCELED_SPECULATIVE"


TERMINAL_TASK_STATES = {
    TaskState.FINISHED,
    TaskState.FAILED,
    TaskState.CANCELED,
    TaskState.CANCELED_SPECULATIVE,
}
