"""Worker task runtime: execute one fragment, buffer partitioned output.

Reference: ``execution/SqlTaskManager.java:88,370`` (task registry +
updateTask), ``execution/SqlTaskExecution.java`` (fragment -> drivers),
``execution/buffer/OutputBuffer.java:23,88-94`` with its Partitioned /
Broadcast variants (producer side of the shuffle), and
``server/TaskResource.java:84,127,261`` (the REST surface in http.py).

A task executes its fragment with a :class:`WorkerExecutor` — the local
interpreter with two overrides: scans read only the task's assigned splits,
and RemoteSource leaves pull pages from upstream tasks over HTTP
(``operator/ExchangeOperator.java:35`` / ``ExchangeClient.java:149``).
Output rows are partitioned per the fragment's output exchange into
per-consumer page lists served token-acked (at-least-once + dedupe, like
``HttpPageBufferClient.java:93``).
"""

from __future__ import annotations

import base64
import json
import threading
import time
import traceback
import urllib.request
from typing import Any, Optional

import numpy as np

from trino_tpu.columnar import Batch, Column, concat_batches
from trino_tpu.config import Session
from trino_tpu.exec.local import LocalExecutor, Result
from trino_tpu.ops import join as J
from trino_tpu.planner import plan as P
from trino_tpu.planner.fragmenter import PlanFragment
from trino_tpu.serde import deserialize_batch, serialize_batch
from trino_tpu.server.statemachine import TaskState

PAGE_ROWS = 1 << 16

# Worker-side fragment/program memo (cross-attempt compile reuse): TASK
# retry re-sends the same fragment payload to a worker; deserializing it
# afresh gives the plan nodes new object identities, which makes every
# program-store key miss and forces a full retrace per attempt. Keyed by
# (query_id, fragment_id, payload digest), each entry pins ONE deserialized
# PlanFragment (stable node ids) plus the program dict compiled against it,
# so attempt N+1 re-executes attempt N's compiled programs. Entries hold
# compiled executables — keep the bound small.
_WORKER_FRAGMENT_CACHE_MAX = 8


def _shared_fragment_entry(
    engine, query_id, payload_fragment, validate, payload_members=None
):
    """Return a locked {fragment, members, programs, lock} entry for this
    payload, or None when another live task of the same fragment holds it
    (concurrent partitions must not share a FragmentedExecutor's mutable
    state). ``payload_members`` is the serialized fused-unit chain when
    the task ships one — it joins the digest so a fused and an unfused
    payload of the same root fragment never share programs."""
    import hashlib

    from trino_tpu.planner.serde import fragment_from_json

    cache = getattr(engine, "_task_fragment_cache", None)
    if cache is None:
        from collections import OrderedDict

        cache = engine._task_fragment_cache = OrderedDict()
        engine._task_fragment_cache_lock = threading.Lock()
    digest = hashlib.sha256(
        json.dumps(
            [payload_fragment, payload_members], sort_keys=True, default=str
        ).encode()
    ).hexdigest()
    key = (query_id, payload_fragment.get("id"), digest)
    with engine._task_fragment_cache_lock:
        entry = cache.get(key)
        if entry is None:
            members = (
                [
                    fragment_from_json(m, validate=validate)
                    for m in payload_members
                ]
                if payload_members
                else None
            )
            entry = {
                # the chain's last member IS the unit root — reuse the
                # deserialized object so plan-node identities agree
                "fragment": (
                    members[-1]
                    if members
                    else fragment_from_json(payload_fragment, validate=validate)
                ),
                "members": members,
                "programs": {},
                "lock": threading.Lock(),
            }
            cache[key] = entry
            while len(cache) > _WORKER_FRAGMENT_CACHE_MAX:
                cache.popitem(last=False)
        else:
            cache.move_to_end(key)
    if not entry["lock"].acquire(blocking=False):
        return None
    return entry


class OutputBuffer:
    """Per-partition page deques with token-acked consumption, bounded
    memory, and producer backpressure.

    Reference: ``execution/buffer/OutputBufferMemoryManager.java`` — the
    producer blocks once buffered bytes exceed the cap; a consumer GET with
    token N acknowledges (and frees) every page below N, releasing the
    producer. At-least-once delivery: unacknowledged pages are re-served on
    retry with the same token.

    ``retain=True`` (TASK retry policy) switches to materialized-exchange
    semantics (reference: Tardigrade's spooled exchange): acks no longer
    free pages, so a *retried consumer attempt* can re-pull the stream
    from token 0 bit-identically. Memory is released when the coordinator
    deletes the task after the consuming stage finishes. Retained buffers
    skip producer backpressure — blocking would deadlock a stage-barrier
    schedule where consumers only start after producers finish.
    """

    def __init__(
        self,
        n_partitions: int,
        max_buffered_bytes: int = 64 << 20,
        retain: bool = False,
    ):
        self.n = n_partitions
        self._pages: list[list[bytes]] = [[] for _ in range(n_partitions)]
        self._base: list[int] = [0] * n_partitions  # first unacked token
        self._buffered = 0
        self.max_buffered_bytes = max_buffered_bytes
        self.retain = retain
        self._complete = False
        self._aborted = False
        self.dropped_unacked = False  # abort() discarded undelivered pages
        # spooled exchange (exchange/spool.py): mirrors every enqueued
        # page to the coordinator's spool store off the critical path
        self.spool_writer = None
        self._lock = threading.Condition()

    def enqueue(self, partition: int, page: bytes) -> None:
        with self._lock:
            # backpressure: block until consumers ack enough pages
            while (
                not self.retain
                and self._buffered + len(page) > self.max_buffered_bytes
                and self._buffered > 0
                and not self._aborted
            ):
                self._lock.wait(1.0)
            if self._aborted:
                return
            self._pages[partition].append(page)
            self._buffered += len(page)
            self._lock.notify_all()
        if self.spool_writer is not None:
            self.spool_writer.offer(partition, page)

    def set_complete(self) -> None:
        with self._lock:
            self._complete = True
            self._lock.notify_all()

    def abort(self) -> None:
        """Unblock producers and drop buffered pages (task cancel/fail).
        Also aborts any in-flight spool write — DELETE /v1/task and
        speculative cancels must not leave half-spooled (or now-stale)
        pages in the coordinator's store."""
        with self._lock:
            self._aborted = True
            self._complete = True
            if any(self._pages):
                # a consumer re-reading these tokens must not mistake the
                # truncated stream for a successful empty result
                self.dropped_unacked = True
            self._pages = [[] for _ in range(self.n)]
            self._buffered = 0
            self._lock.notify_all()
        if self.spool_writer is not None:
            self.spool_writer.abort()

    def get(self, partition: int, token: int, max_wait: float = 1.0):
        """Pages from `token` on; blocks up to max_wait for more data.
        A request at token N acks (frees) pages below N. Returns
        (pages, next_token, complete)."""
        deadline = time.monotonic() + max_wait
        with self._lock:
            # acknowledge everything below `token`
            base = self._base[partition]
            if token > base and not self.retain:
                drop = token - base
                dropped = self._pages[partition][:drop]
                del self._pages[partition][:drop]
                self._base[partition] = token
                self._buffered -= sum(len(p) for p in dropped)
                self._lock.notify_all()
            while True:
                base = self._base[partition]
                pages = self._pages[partition][max(0, token - base):]
                if pages or self._complete:
                    return pages, token + len(pages), self._complete
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return [], token, False
                self._lock.wait(remaining)


class ExchangeClient:
    """Pull one partition of an upstream fragment from all its tasks.

    Reference: ``operator/ExchangeClient.java:56,149`` — one buffer client
    per upstream location, token-advancing GETs until complete.

    Timeouts come from the session (``exchange_timeout_s`` /
    ``exchange_poll_s``) so chaos tests can shrink them. Each GET is
    retried through transient connection errors (and injected HTTP drops)
    with deterministic backoff: token-addressed reads are idempotent —
    the producer re-serves unacknowledged pages at the same token — so a
    replayed pull cannot duplicate or lose rows.
    """

    def __init__(
        self,
        locations: list[str],
        partition: int,
        timeout: float = 300.0,
        poll_wait: float = 15.0,
        injector=None,
        http_retries: int = 3,
        backoff=None,
        trace=None,
    ):
        from trino_tpu.ft.retry import Backoff

        self.locations = locations
        self.partition = partition
        self.timeout = timeout
        self.poll_wait = poll_wait  # server-side long-poll hold per GET
        self.injector = injector
        self.http_retries = max(1, int(http_retries))
        self.backoff = backoff or Backoff()
        # (trace_id, parent_span_id) for the exchange_read span: pull
        # threads start with a fresh context, so callers that spawn one
        # thread per source must capture and pass the parent explicitly
        self.trace = trace

    @classmethod
    def for_session(
        cls, session, locations: list[str], partition: int, injector=None,
        trace=None,
    ) -> "ExchangeClient":
        """Injector may be passed in to share one event log / counter set
        with the caller (the owning task); otherwise it is derived from
        the session."""
        from trino_tpu.ft.injection import FaultInjector
        from trino_tpu.ft.retry import Backoff

        try:
            return cls(
                locations,
                partition,
                timeout=float(session.get("exchange_timeout_s")),
                poll_wait=float(session.get("exchange_poll_s")),
                injector=injector or FaultInjector.from_session(session),
                http_retries=int(session.get("http_retry_attempts")),
                backoff=Backoff.from_session(session),
                trace=trace,
            )
        except KeyError:  # sessions predating the ft properties
            return cls(locations, partition, injector=injector, trace=trace)

    def _get_json(self, loc: str, uri: str, token: int, deadline: float) -> dict:
        """One token read, retried through transient errors. The site key
        strips per-run identifiers (host:port, query counter) so injected
        drops replay deterministically."""
        from trino_tpu.ft.retry import is_retryable

        task_tail = loc.rsplit("/", 1)[-1].split(".", 1)[-1]
        last: Optional[Exception] = None
        for attempt in range(1, self.http_retries + 1):
            if time.monotonic() > deadline and last is not None:
                break
            from trino_tpu.server import auth

            try:
                if self.injector is not None:
                    site = self.injector.http_site(
                        "results",
                        f"{task_tail}.p{self.partition}.k{token}",
                        attempt,
                    )
                    self.injector.delay_http(site)
                    self.injector.maybe_drop_http(site)
                req = urllib.request.Request(uri, headers=auth.headers())
                with urllib.request.urlopen(
                    req, timeout=self.poll_wait + 30
                ) as r:
                    return json.loads(r.read().decode())
            except Exception as e:  # noqa: BLE001
                if not is_retryable(e) or attempt >= self.http_retries:
                    raise
                last = e
                time.sleep(self.backoff.delay(attempt))
        raise last  # deadline exceeded mid-retry

    def read_all(self) -> list[Batch]:
        from trino_tpu.obs.metrics import get_registry
        from trino_tpu.obs.trace import get_tracer

        tracer = get_tracer()
        ctx = self.trace or tracer.context()
        t0 = time.monotonic()
        batches: list[Batch] = []
        threads = []
        errors: list[Exception] = []
        xfer = {"pages": 0, "bytes": 0}
        lock = threading.Lock()

        def pull(loc: str):
            from trino_tpu.server import auth

            try:
                token = 0
                deadline = time.monotonic() + self.timeout
                while True:
                    uri = (
                        f"{loc}/results/{self.partition}/{token}"
                        f"?maxWait={self.poll_wait}"
                    )
                    payload = self._get_json(loc, uri, token, deadline)
                    for b64 in payload["pages"]:
                        raw = base64.b64decode(b64)
                        batch = deserialize_batch(raw)
                        with lock:
                            batches.append(batch)
                            xfer["pages"] += 1
                            xfer["bytes"] += len(raw)
                    token = payload["token"]
                    if payload["complete"]:
                        # final ack frees the last unacked page window on
                        # the producer (nothing re-reads a complete buffer)
                        try:
                            ack = f"{loc}/results/{self.partition}/{token}?maxWait=0"
                            urllib.request.urlopen(
                                urllib.request.Request(
                                    ack, headers=auth.headers()
                                ),
                                timeout=5,
                            ).close()
                        except Exception:  # noqa: BLE001 - best-effort
                            pass
                        return
                    if payload.get("failed"):
                        raise RuntimeError(payload.get("error", "upstream task failed"))
                    if time.monotonic() > deadline:
                        raise TimeoutError(f"exchange timed out reading {uri}")
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(e)

        try:
            for loc in self.locations:
                t = threading.Thread(target=pull, args=(loc,), daemon=True)
                t.start()
                threads.append(t)
            deadline = time.monotonic() + self.timeout
            for t in threads:
                t.join(max(0.0, deadline - time.monotonic()))
            if errors:
                raise errors[0]
            if any(t.is_alive() for t in threads):
                # a stalled puller must not yield silently-partial results
                raise TimeoutError("exchange read timed out with pulls in flight")
            return batches
        finally:
            dur_ms = (time.monotonic() - t0) * 1000.0
            tracer.record(
                "exchange_read", dur_ms,
                attrs={
                    "locations": len(self.locations),
                    "partition": self.partition,
                    "pages": xfer["pages"],
                    "bytes": xfer["bytes"],
                },
                trace_id=ctx[0] if ctx else None,
                parent_id=ctx[1] if ctx else None,
                status="OK" if not errors else "ERROR",
            )
            reg = get_registry()
            reg.histogram("trino_tpu_exchange_read_ms").observe(dur_ms)
            reg.counter("trino_tpu_exchange_read_bytes_total").inc(xfer["bytes"])
            reg.counter("trino_tpu_exchange_read_pages_total").inc(xfer["pages"])


class WorkerExecutor(LocalExecutor):
    """Local interpreter + assigned-split scans + HTTP remote sources."""

    def __init__(
        self,
        catalogs,
        session: Session,
        splits: dict[str, list[dict]],
        sources: dict[int, dict],
        prefetched: Optional[dict[int, list[Batch]]] = None,
    ):
        super().__init__(catalogs, session)
        self._splits = splits
        self._sources = sources
        self._prefetched = prefetched or {}

    def _exec_tablescan(self, node: P.TableScan) -> Result:
        from trino_tpu.connectors.api import Split

        connector = self.catalogs.get(node.catalog)
        key = f"{node.catalog}.{node.schema}.{node.table}"
        assigned = self._splits.get(key, [])
        layout = {s.name: i for i, s in enumerate(node.symbols)}
        if not assigned:
            return Result(self._empty_batch(node), layout)
        # assigned splits decode through the double-buffered ingest tier
        # (decode of split k+1 overlaps device work on split k)
        batches = list(
            self._read_splits(
                connector,
                node.schema,
                node.table,
                node.column_names,
                [
                    Split(d["table"], d["index"], d["total"], d.get("info"))
                    for d in assigned
                ],
            )
        )
        batch = concat_batches(batches) if len(batches) > 1 else batches[0]
        return Result(batch, layout)

    def _exec_remotesource(self, node: P.RemoteSource) -> Result:
        if node.fragment_id in self._prefetched:
            batches = self._prefetched[node.fragment_id]
        else:
            src = self._sources[node.fragment_id]
            client = ExchangeClient.for_session(
                self.session, src["locations"], src["partition"]
            )
            batches = client.read_all()
        layout = {s.name: i for i, s in enumerate(node.symbols)}
        nonempty = [b for b in batches if b.num_rows > 0]
        if not nonempty:
            cols = [
                Column(s.type, np.zeros(0, dtype=s.type.storage_dtype))
                for s in node.symbols
            ]
            return Result(Batch(cols, 0), layout)
        return Result(concat_batches(nonempty), layout)


class FusedWorkerRunner:
    """Execute one fragment on this worker's local devices as a single
    fused program (the reference hooks its compiled tier in at
    ``LocalExecutionPlanner.java:307``; here the whole fragment is one
    ``jax.jit`` program over the worker-local mesh).

    Inputs arrive as host batches (splits, HTTP pages) and are placed onto
    the mesh respecting the exchange semantics the in-process fused path
    gets from its collectives:
    - broadcast sources replicate (every local shard sees the full build);
    - hash sources re-partition rows by key hash over local shards (the
      per-shard joins/combines in the tracer require co-partitioning);
    - everything else splits contiguously.
    """

    def __init__(
        self,
        engine,
        session: Session,
        fragment: PlanFragment,
        programs: Optional[dict] = None,
    ):
        from trino_tpu.exec.fragments import FragmentedExecutor
        from trino_tpu.parallel.mesh import make_local_mesh

        mesh = getattr(engine, "mesh", None) or make_local_mesh()
        # device execution must not re-enter cluster scheduling
        local = Session(
            user=session.user, catalog=session.catalog, schema=session.schema
        )
        for k, v in session.properties.items():
            if k != "execution_mode":
                local.properties[k] = v
        self.executor = FragmentedExecutor(
            engine.catalogs, local, mesh, programs=programs
        )
        self.fragment = fragment
        self.mesh = mesh

    @property
    def n(self) -> int:
        return self.mesh.devices.size

    def run(
        self,
        splits: dict[str, list[dict]],
        source_batches: dict[int, list[Batch]],
        source_meta: dict[int, dict],
        stats_sink: Optional[dict] = None,
    ) -> Result:
        self.fragment = self._df_rewrite(self.fragment, source_batches)
        inputs: dict[str, Batch] = {}
        layouts: dict[str, dict[str, int]] = {}
        self._gather_inputs(
            self.fragment.root,
            splits,
            source_batches,
            source_meta,
            inputs,
            layouts,
        )
        return self.executor.run_fragment_program(
            self.fragment,
            inputs,
            layouts,
            apply_exchange=False,
            stats_sink=stats_sink,
        )

    def run_chain(
        self,
        members: list[PlanFragment],
        splits: dict[str, list[dict]],
        source_batches: dict[int, list[Batch]],
        source_meta: dict[int, dict],
        stats_sink: Optional[dict] = None,
    ) -> Result:
        """Fused-unit chain (bottom-up, unit root LAST) as ONE program on
        the worker-local mesh: interior hash/'single' exchanges lower to
        in-program collectives, so every member rides this task's single
        dispatch round-trip. Only external feeds — member table scans and
        out-of-unit remote sources — are placed host-side; in-unit links
        never leave the device."""
        member_ids = frozenset(m.id for m in members)
        frags = [self._df_rewrite(m, source_batches) for m in members]
        self.fragment = frags[-1]
        # in-unit skew pairs detect/salt in-trace; _skew_roles normally
        # derives roles from the query SubPlan, which never ships to
        # workers — seed the memo from the member list instead
        if self.executor.programs.get("__skewroles__") is None:
            from trino_tpu.planner.fragmenter import partitioned_join_pairs

            roles: dict[int, dict] = {}
            if bool(self.executor.session.get("skew_handling")):
                for probe, build in partitioned_join_pairs(frags):
                    roles[probe] = {"role": "probe"}
                    roles[build] = {"role": "build", "peer": probe}
            self.executor.programs["__skewroles__"] = roles
        inputs: dict[str, Batch] = {}
        layouts: dict[str, dict[str, int]] = {}
        for f in frags:
            self._gather_inputs(
                f.root,
                splits,
                source_batches,
                source_meta,
                inputs,
                layouts,
                skip_fids=member_ids,
            )
        return self.executor.run_fused_program(
            frags,
            inputs,
            layouts,
            apply_exchange=False,
            stats_sink=stats_sink,
        )

    def _df_rewrite(
        self, fragment: PlanFragment, source_batches: dict[int, list[Batch]]
    ) -> PlanFragment:
        """Dynamic filtering: prefetched build pages prune this task's
        probe splits and rows (sound under hash partitioning — probe rows
        are co-partitioned with their build rows). In-unit build sides
        are traced values, not host pages, so they simply don't prune."""
        import dataclasses as _dc

        from trino_tpu.dynfilter import fragment_dynamic_filters

        by_fid = {
            n.fragment_id: n
            for n in P.walk_plan(fragment.root)
            if isinstance(n, P.RemoteSource)
        }

        def build_lookup(fid):
            node = by_fid.get(fid)
            batches = source_batches.get(fid)
            if node is None or batches is None:
                return None
            nonempty = [b for b in batches if b.num_rows > 0]
            pos = {s.name: i for i, s in enumerate(node.symbols)}
            if not nonempty:
                def get_empty(name):
                    if name not in pos:
                        return None
                    return np.zeros(0, dtype=np.int64), None

                return get_empty, 0
            merged = (
                concat_batches(nonempty) if len(nonempty) > 1 else nonempty[0]
            ).compact()

            def get_column(name):
                i = pos.get(name)
                if i is None:
                    return None
                return merged.columns[i].to_numpy()

            return get_column, merged.num_rows

        root = fragment_dynamic_filters(
            fragment.root,
            build_lookup,
            self.executor.session,
            self.executor.dynamic_filters,
        )
        return _dc.replace(fragment, root=root)

    def _gather_inputs(
        self,
        root: P.PlanNode,
        splits: dict[str, list[dict]],
        source_batches: dict[int, list[Batch]],
        source_meta: dict[int, dict],
        inputs: dict[str, Batch],
        layouts: dict[str, dict[str, int]],
        skip_fids: frozenset = frozenset(),
    ) -> None:
        from trino_tpu.connectors.api import Split
        from trino_tpu.exec.fragments import FusedUnsupported

        spill_threshold = (
            int(self.executor.session.get("spill_threshold_rows"))
            if self.executor.session.get("spill_enabled")
            else None
        )
        for node in P.walk_plan(root):
            if isinstance(node, P.TableScan):
                key = f"{node.catalog}.{node.schema}.{node.table}"
                assigned = splits.get(key, [])
                connector = self.executor.catalogs.get(node.catalog)
                if node.constraint is not None and assigned:
                    # dynamic-filter (and pushed) constraints drop whole
                    # splits before any read
                    objs = [
                        Split(d["table"], d["index"], d["total"], d.get("info"))
                        for d in assigned
                    ]
                    kept = connector.prune_splits(
                        node.schema, node.table, objs, node.constraint
                    )
                    kept_ids = {(s.index, s.total) for s in kept}
                    assigned = [
                        d
                        for d in assigned
                        if (d["index"], d["total"]) in kept_ids
                    ]
                parts: list[list[Batch]] = [[] for _ in range(self.n)]
                for i, d in enumerate(assigned):
                    parts[i % self.n].append(
                        connector.read_split(
                            node.schema,
                            node.table,
                            node.column_names,
                            Split(d["table"], d["index"], d["total"], d.get("info")),
                        )
                    )
                layout = {s.name: i for i, s in enumerate(node.symbols)}
                batch = self._assemble(
                    [self._concat(p) for p in parts], node.symbols
                )
                if spill_threshold is not None and batch.capacity > spill_threshold:
                    # same guard as the in-process fused path: spill-sized
                    # working sets belong to the interpreter's spill tier
                    raise FusedUnsupported("spill-sized input")
                inputs[f"scan{id(node)}"] = batch
                layouts[f"scan{id(node)}"] = layout
            elif isinstance(node, P.RemoteSource):
                if node.fragment_id in skip_fids:
                    continue  # in-unit link: fed as a traced value
                batches = source_batches[node.fragment_id]
                meta = source_meta.get(node.fragment_id, {})
                batch = self._place(node, batches, meta)
                inputs[f"remote{node.fragment_id}"] = batch
                layouts[f"remote{node.fragment_id}"] = {
                    s.name: i for i, s in enumerate(node.symbols)
                }

    # --- input placement --------------------------------------------------

    def _concat(self, batches: list[Batch]) -> Optional[Batch]:
        nonempty = [b for b in batches if b.num_rows > 0]
        if not nonempty:
            return None
        return (
            concat_batches(nonempty) if len(nonempty) > 1 else nonempty[0]
        ).compact()

    def _assemble(
        self, parts: list[Optional[Batch]], symbols
    ) -> Batch:
        from trino_tpu.parallel.mesh import shard_batch

        proto = next((p for p in parts if p is not None), None)
        filled = []
        for p in parts:
            if p is not None:
                filled.append(p)
            elif proto is not None:
                cols = [
                    Column(
                        c.type,
                        np.zeros(
                            (0,) + np.asarray(c.data).shape[1:],
                            dtype=np.asarray(c.data).dtype,
                        ),
                        None,
                        c.dictionary,
                    )
                    for c in proto.columns
                ]
                filled.append(Batch(cols, 0))
            else:
                cols = [_empty_column(s.type) for s in symbols]
                filled.append(Batch(cols, 0))
        return shard_batch(self.mesh, filled)

    def _place(self, node: P.RemoteSource, batches: list[Batch], meta: dict) -> Batch:
        from trino_tpu.parallel.mesh import replicated

        merged = self._concat(batches)
        if merged is None:
            merged = Batch([_empty_column(s.type) for s in node.symbols], 0)
        if node.exchange_type == "broadcast":
            # full build side on every local shard
            import jax

            sharding = replicated(self.mesh)
            cols = []
            for c in merged.columns:
                data, valid = c.to_numpy()
                cols.append(
                    Column(
                        c.type,
                        jax.device_put(data, sharding),
                        jax.device_put(valid, sharding),
                        c.dictionary,
                    )
                )
            return Batch(cols, merged.num_rows)
        if node.exchange_type == "hash":
            from trino_tpu.exec.fragments import FusedUnsupported

            keys = meta.get("keys") or []
            symbols = meta.get("symbols") or []
            if not keys or any(k not in symbols for k in keys):
                # co-partitioning is a correctness requirement for the
                # per-shard joins/combines — never silently degrade
                raise FusedUnsupported("hash source without key metadata")
            positions = [symbols.index(k) for k in keys]
            key_pairs = []
            for pos in positions:
                c = merged.columns[pos]
                data, valid = c.to_numpy()
                key_pairs.append((data, valid))
            khash, _ = J.hash_keys(key_pairs)
            dest = np.asarray(khash) % self.n
            parts = [
                _take_rows(merged, np.nonzero(dest == p)[0])
                for p in range(self.n)
            ]
            return self._assemble(parts, node.symbols)
        # single/gather: contiguous chunks
        rows = merged.num_rows
        chunk = max(1, -(-rows // self.n))
        parts = [
            _take_rows(merged, np.arange(lo, min(lo + chunk, rows)))
            for lo in range(0, self.n * chunk, chunk)
        ]
        return self._assemble(parts, node.symbols)


class SqlTask:
    """One task = one fragment execution on this node.

    Reference: ``execution/SqlTask.java`` + ``SqlTaskExecution.java``.
    """

    def __init__(self, task_id: str, engine, payload: dict, trace=None):
        self.task_id = task_id
        self.engine = engine
        self.state = TaskState.RUNNING
        # the node this task runs on (server/http.py sets engine.node_id);
        # delay-fault injection targets nodes by this identity
        self.node_id: Optional[str] = getattr(engine, "node_id", None)
        self.error: Optional[str] = None
        self.created = time.monotonic()  # interval math only (elapsed/reap)
        self.finished: Optional[float] = None  # monotonic, set on _run exit
        # (trace_id, parent_span_id) from the coordinator's X-Trino-Trace
        # header: parents this worker's task_execute span to the
        # dispatching attempt span across the process boundary
        self.trace = trace
        self.fragment_id = payload["fragment"]["id"]
        s = payload.get("session", {})
        self.session = Session(
            user=s.get("user", "worker"),
            catalog=s.get("catalog", "tpch"),
            schema=s.get("schema", "tiny"),
        )
        for k, v in s.get("properties", {}).items():
            self.session.properties[k] = v
        from trino_tpu.planner.sanity import validation_enabled
        from trino_tpu.planner.serde import fragment_from_json

        # whole-pipeline fusion: the payload may ship a fused-unit chain
        # (bottom-up, the task's fragment last) compiled as ONE program
        fused_payload = payload.get("fused_fragments")
        # TASK retry: reuse the attempt-1 fragment object (stable plan-node
        # identities) and its compiled programs; lock released in _run()
        self._frag_entry = _shared_fragment_entry(
            engine,
            task_id.rsplit(".", 2)[0],
            payload["fragment"],
            validation_enabled(self.session),
            payload_members=fused_payload,
        )
        if self._frag_entry is not None:
            self.fragment: PlanFragment = self._frag_entry["fragment"]
            self.fused_members = self._frag_entry.get("members")
        else:
            members = (
                [
                    fragment_from_json(
                        m, validate=validation_enabled(self.session)
                    )
                    for m in fused_payload
                ]
                if fused_payload
                else None
            )
            self.fragment = (
                members[-1]
                if members
                else fragment_from_json(
                    payload["fragment"],
                    validate=validation_enabled(self.session),
                )
            )
            self.fused_members = members
        self.splits: dict[str, list[dict]] = payload.get("splits", {})
        self.sources: dict[int, dict] = {
            int(k): v for k, v in payload.get("sources", {}).items()
        }
        self.n_output_partitions = payload.get("output_partitions", 1)
        # interpreter fallback runs single-node on this fragment
        self.session.properties["execution_mode"] = "local"
        try:
            buffer_bytes = int(self.session.get("exchange_buffer_bytes"))
        except (KeyError, TypeError, ValueError):
            buffer_bytes = 64 << 20
        # TASK retry: the coordinator asks for materialized (retained)
        # output so a retried consumer attempt can re-pull this stream
        self.buffer = OutputBuffer(
            self.n_output_partitions,
            max_buffered_bytes=buffer_bytes,
            retain=bool(payload.get("retain_output")),
        )
        # spooled exchange: the coordinator asks (payload["spool"]) for an
        # async durable copy of this task's retained output, so a consumer
        # can re-read it after this worker dies
        spool = payload.get("spool")
        if spool and self.buffer.retain:
            from trino_tpu.exchange.spool import SpoolWriter

            self.buffer.spool_writer = SpoolWriter(
                spool["uri"], task_id, spool.get("queryId", self.query_id)
            )
        from trino_tpu.ft.injection import FaultInjector

        self.injector = FaultInjector.from_session(self.session)
        # worker-side retryable classification of a FAILED state; None
        # while RUNNING/FINISHED (TaskFailure consumes this coordinator-side)
        self.retryable: Optional[bool] = None
        self.execution_path = "pending"
        self.stats: dict[str, Any] = {}
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # --- execution --------------------------------------------------------

    def _prefetch_sources(self) -> dict[int, list[Batch]]:
        """Pull every remote source exactly once (pages are freed on final
        ack, so a retry after a failed device attempt cannot re-pull)."""
        out: dict[int, list[Batch]] = {}
        threads = []
        errors: list[Exception] = []

        from trino_tpu.obs.trace import get_tracer

        # capture the task span context here: pull threads start fresh
        ctx = get_tracer().context()

        def pull(fid: int, src: dict):
            try:
                out[fid] = ExchangeClient.for_session(
                    self.session,
                    src["locations"],
                    src["partition"],
                    injector=self.injector,
                    trace=ctx,
                ).read_all()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        for fid, src in self.sources.items():
            t = threading.Thread(target=pull, args=(fid, src), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return out

    @property
    def query_id(self) -> str:
        """Task ids are ``{query_id}.{fragment}.{partition}``."""
        return self.task_id.rsplit(".", 2)[0]

    def _account(self, nbytes: int) -> None:
        """Per-task memory accounting against this node's pool, keyed by
        query id — the reservations workers report to the coordinator's
        ClusterMemoryManager (reference: per-task memory contexts rolling
        up to ``MemoryPool`` / ``ClusterMemoryManager.java:89``)."""
        if nbytes <= 0:
            return
        from trino_tpu.memory import ExceededMemoryLimitError

        if not self.engine.memory_pool.try_reserve(self.query_id, nbytes):
            raise ExceededMemoryLimitError(
                f"task {self.task_id}: node memory pool exhausted reserving "
                f"{nbytes} bytes"
            )
        self._reserved += nbytes

    def _run(self) -> None:
        from trino_tpu.obs.metrics import get_registry
        from trino_tpu.obs.trace import get_tracer

        tracer = get_tracer()
        span = tracer.start_span(
            "task_execute",
            trace_id=self.trace[0] if self.trace else None,
            parent_id=self.trace[1] if self.trace else None,
            attrs={"taskId": self.task_id, "stage": self.fragment_id},
        )
        self._reserved = 0
        try:
            with tracer.activate(span):
                prefetched = self._prefetch_sources()
                if self.injector is not None:
                    # crash AFTER the sources were pulled: a retried attempt
                    # must be able to re-pull them (retained buffers / unacked
                    # token windows make the replay idempotent)
                    from trino_tpu.ft.injection import task_site

                    site = task_site(self.task_id)
                    self.injector.maybe_crash_task(site)
                    # straggler manufacturing: fixed stall before execution
                    # on targeted slow nodes
                    self.injector.stall_task(site, self.node_id)
                from trino_tpu.memory import batch_nbytes

                in_bytes = sum(
                    batch_nbytes(b)
                    for batches in prefetched.values()
                    for b in batches
                )
                self._account(in_bytes)
                self.stats["input_bytes"] = int(in_bytes)
                result = None
                exec_t0 = time.monotonic()
                mode = self.session.get("worker_execution")
                if mode in ("fused", "fused_strict"):
                    result = self._try_fused(prefetched, strict=mode == "fused_strict")
                if result is None:
                    self.execution_path = "interpreter"
                    result = self._run_interpreted(prefetched)
                if self.injector is not None:
                    # multiplicative slowdown applied before the result is
                    # emitted: a speculative cancel can still abort this
                    # buffer while the "slow" attempt is mid-sleep
                    self.injector.slow_task(
                        site, self.node_id, time.monotonic() - exec_t0
                    )
                    if self.state != TaskState.RUNNING:
                        # cancelled mid-stall (speculative loser): never
                        # emit into the aborted buffer
                        return
                self._account(batch_nbytes(result.batch) if result.batch is not None else 0)
                self._emit(result)
            if self.state == TaskState.RUNNING:
                self.state = TaskState.FINISHED
        except Exception as e:  # noqa: BLE001
            from trino_tpu.ft.retry import is_retryable

            self.error = f"{e}\n{traceback.format_exc()}"
            self.retryable = is_retryable(e)
            if self.state == TaskState.RUNNING:
                # a cancelled task that then unwinds with an exception keeps
                # its cancelled state (the cancel is the cause, not the error)
                self.state = TaskState.FAILED
        finally:
            self.finished = time.monotonic()
            span.finish(
                status="OK" if self.state == TaskState.FINISHED else "ERROR",
                state=self.state,
                path=self.execution_path,
            )
            reg = get_registry()
            reg.counter(
                "trino_tpu_worker_tasks_total", state=self.state
            ).inc()
            reg.histogram(
                # fragment ids restart at 0 per plan: a bounded domain
                "trino_tpu_task_execute_ms", stage=str(self.fragment_id)  # lint: ignore[OBS001]
            ).observe((self.finished - self.created) * 1000.0)
            if self.injector is not None and self.injector.total_injected:
                self.stats["faults_injected"] = self.injector.total_injected
            self.buffer.set_complete()
            writer = self.buffer.spool_writer
            if writer is not None:
                if self.state == TaskState.FINISHED:
                    # publish the completion manifest before the task is
                    # observable as durable; a failure here just leaves the
                    # spool incomplete (lineage recovery covers the gap)
                    if writer.finish():
                        self.stats["spooled_bytes"] = writer.spooled_bytes
                else:
                    writer.abort()
            if self._reserved:
                self.engine.memory_pool.free(self.query_id, self._reserved)
            # one-shot handoff (atomic pop): tests drive _run() directly on
            # top of the constructor-started thread, and the entry lock
            # must release exactly once no matter how many times _run ends
            entry = self.__dict__.pop("_frag_entry", None)
            if entry is not None:
                entry["lock"].release()
            if self.injector is not None:
                # worker-death fault LAST: by now the terminal state is
                # set, the buffer is complete, and (on FINISHED) the spool
                # manifest published — the deterministic death models a
                # node crashing right after its task output became durable
                from trino_tpu.ft.injection import task_site

                self.injector.maybe_exit_worker(
                    task_site(self.task_id), self.node_id
                )

    def _try_fused(self, prefetched, strict: bool = False) -> Optional[Result]:
        """Fragment as one compiled program on worker-local devices; None
        means fall back to the interpreter.

        ``strict`` (session ``worker_execution=fused_strict``) fails the
        task instead of silently interpreting: a fused-path regression
        turns a strict suite red rather than slow (round-3 advisor: one
        swallowed exception could quietly degrade the whole cluster)."""
        import jax

        from trino_tpu.exec.fragments import FusedUnsupported, fragment_fusable

        members = self.fused_members
        for frag in members or [self.fragment]:
            if not fragment_fusable(frag):
                if strict:
                    raise FusedUnsupported(
                        f"fused_strict: fragment {frag.id} is not fusable"
                    )
                return None
        try:
            # a concurrent _run() completion may have popped the entry;
            # the fragment object itself stays valid either way
            entry = getattr(self, "_frag_entry", None)
            runner = FusedWorkerRunner(
                self.engine,
                self.session,
                self.fragment,
                programs=entry["programs"] if entry else None,
            )
            source_meta = {
                fid: {"keys": src.get("keys"), "symbols": src.get("symbols")}
                for fid, src in self.sources.items()
            }
            if members:
                result = runner.run_chain(
                    members,
                    self.splits,
                    prefetched,
                    source_meta,
                    stats_sink=self.stats,
                )
                self.execution_path = "fused-pipeline"
            else:
                result = runner.run(
                    self.splits, prefetched, source_meta, stats_sink=self.stats
                )
                self.execution_path = "fused"
            self.stats["dynamic_filters"] = len(
                runner.executor.dynamic_filters
            )
            self.stats["compile"] = dict(runner.executor.compile_stats)
            # device profiler + exchange counters ride the task status back
            # to the coordinator, which merges them per stage for the
            # distributed EXPLAIN ANALYZE / queryStats rollup
            dsnap = runner.executor.device_stats_snapshot()
            if dsnap:
                self.stats["deviceStats"] = dsnap
            self.stats["exchange"] = runner.executor.exchange_stats_snapshot()
            isnap = runner.executor.ingest_stats_snapshot()
            if isnap:
                self.stats["ingest"] = isnap
            return result
        except (FusedUnsupported, jax.errors.TracerArrayConversionError) as e:
            if strict:
                raise
            self.stats["fused_error"] = f"{type(e).__name__}: {e}"
            return None
        except Exception as e:  # noqa: BLE001
            # any other device-path failure (capacity retry exhaustion, XLA
            # errors): the interpreter fallback recomputes from the
            # prefetched sources — record why for observability
            if strict:
                raise
            self.stats["fused_error"] = f"{type(e).__name__}: {e}"
            return None

    def _run_interpreted(self, prefetched) -> Result:
        members = self.fused_members
        if members:
            # fused-unit fallback: interpret the chain bottom-up in this
            # one task, feeding each interior result to its consumer as a
            # prefetched source in wire (output_symbols) order — exactly
            # the pages the member would have shipped as its own task
            local = dict(prefetched)
            result = None
            for m in members:
                result = self._interpret_one(m, local)
                cols = [
                    result.batch.columns[result.layout[s.name]]
                    for s in m.root.output_symbols
                ]
                local[m.id] = [Batch(cols, result.batch.num_rows)]
            return result
        return self._interpret_one(self.fragment, prefetched)

    def _interpret_one(self, fragment: PlanFragment, prefetched) -> Result:
        executor = WorkerExecutor(
            self.engine.catalogs,
            self.session,
            self.splits,
            self.sources,
            prefetched=prefetched,
        )
        root = fragment.root
        if isinstance(root, P.Output):
            res_batch, _names = executor.execute(root)
            return Result(
                res_batch,
                {s.name: i for i, s in enumerate(root.output_symbols)},
            )
        return executor._exec(root)

    def _emit(self, result: Result) -> None:
        from trino_tpu.memory import batch_nbytes

        batch = result.batch.compact()
        # per-task output volume — the coordinator's per-stage rows /
        # exchange-bytes merge reads these off the final task status
        self.stats["output_rows"] = int(batch.num_rows)
        self.stats["output_bytes"] = int(batch_nbytes(batch))
        n = self.n_output_partitions
        ex = self.fragment.output_exchange
        if ex == "broadcast":
            for page in _paginate(batch):
                for p in range(n):
                    self.buffer.enqueue(p, page)
            return
        if ex == "hash" and n > 1:
            key_pairs = []
            for s in self.fragment.output_keys:
                c = batch.columns[result.layout[s.name]]
                key_pairs.append((c.data, c.valid_mask()))
            khash, _ = J.hash_keys(key_pairs)
            dest = np.asarray(khash) % n
            for p in range(n):
                idx = np.nonzero(dest == p)[0]
                part = _take_rows(batch, idx)
                for page in _paginate(part):
                    self.buffer.enqueue(p, page)
            return
        # single (or hash with one consumer): everything to partition 0
        for page in _paginate(batch):
            self.buffer.enqueue(0, page)

    # --- REST support -----------------------------------------------------

    def info(self) -> dict:
        return {
            "taskId": self.task_id,
            "state": self.state,
            "error": self.error,
            # worker-side classification for the coordinator's retry
            # policy; None unless FAILED
            "retryable": self.retryable,
            "fragment": self.fragment_id,
            # monotonic interval, frozen at completion (the coordinator's
            # per-stage sibling elapsed distribution reads this)
            "elapsed": (self.finished or time.monotonic()) - self.created,
            "executionPath": self.execution_path,
            "stats": self.stats,
        }

    def results(self, partition: int, token: int, max_wait: float) -> dict:
        pages, next_token, complete = self.buffer.get(partition, token, max_wait)
        # CANCELED counts as failed for consumers: abort() dropped pages, so
        # truncated output must never read as success. The same applies to a
        # FINISHED task whose buffer was aborted with undelivered pages
        # (cancel raced completion): report failed, not empty success.
        truncated = self.buffer.dropped_unacked
        canceled = self.state in (
            TaskState.CANCELED, TaskState.CANCELED_SPECULATIVE
        )
        return {
            "taskId": self.task_id,
            "pages": [base64.b64encode(p).decode() for p in pages],
            "token": next_token,
            "complete": complete
            and self.state == TaskState.FINISHED
            and not truncated,
            "failed": self.state == TaskState.FAILED or canceled or truncated,
            "error": self.error or (
                "task canceled" if canceled else
                ("task output aborted with undelivered pages" if truncated else None)
            ),
        }

    def cancel(self, speculative: bool = False) -> None:
        """Terminate a running task. ``speculative=True`` marks the loser
        of a hedged attempt pair: a sibling finished first, so this
        attempt's output is unwanted — abort the buffer so it can never
        double-deliver pages the winner already served."""
        if self.state == TaskState.RUNNING:
            self.state = (
                TaskState.CANCELED_SPECULATIVE if speculative
                else TaskState.CANCELED
            )
        # always release buffered pages (a finished task's final unacked
        # window would otherwise live as long as the registry entry)
        self.buffer.abort()


def _empty_column(t) -> Column:
    """Zero-row column for a type: wide DECIMAL uses (0, 2) hi/lo lanes,
    strings carry an empty dictionary (string kernels require one)."""
    from trino_tpu import types as T
    from trino_tpu.columnar import Dictionary

    if isinstance(t, T.DecimalType) and t.wide:
        return Column(t, np.zeros((0, 2), dtype=np.int64))
    return Column(
        t,
        np.zeros(0, dtype=t.storage_dtype),
        None,
        Dictionary([]) if T.is_string(t) else None,
    )


def _paginate(batch: Batch):
    """Serialize a batch as bounded pages (reference: PagesSerde splits at
    the output-operator page size); bounded pages are the unit of exchange
    backpressure."""
    if batch.num_rows == 0:
        return
    if batch.num_rows <= PAGE_ROWS:
        yield serialize_batch(batch)
        return
    # materialize each column once, then slice contiguously per page
    mats = [(c, *c.to_numpy()) for c in batch.columns]
    for lo in range(0, batch.num_rows, PAGE_ROWS):
        hi = min(lo + PAGE_ROWS, batch.num_rows)
        cols = [
            Column(
                c.type,
                data[lo:hi],
                None if valid[lo:hi].all() else valid[lo:hi],
                c.dictionary,
            )
            for c, data, valid in mats
        ]
        yield serialize_batch(Batch(cols, hi - lo))


def _take_rows(batch: Batch, idx: np.ndarray) -> Batch:
    cols = []
    for c in batch.columns:
        data, valid = c.to_numpy()
        cols.append(
            Column(
                c.type,
                data[idx],
                None if valid[idx].all() else valid[idx],
                c.dictionary,
            )
        )
    return Batch(cols, len(idx))


class SqlTaskManager:
    """Task registry (reference: SqlTaskManager.java:88 — terminal tasks
    are evicted after a retention window, like the reference's
    ``info-max-age`` pruning)."""

    TERMINAL_RETENTION = 240.0

    def __init__(self, engine):
        self.engine = engine
        self._tasks: dict[str, SqlTask] = {}
        self._lock = threading.Lock()

    def _reap(self) -> None:
        now = time.monotonic()
        for tid in [
            tid
            for tid, t in self._tasks.items()
            if t.state != TaskState.RUNNING
            and now - t.created > self.TERMINAL_RETENTION
        ]:
            self._tasks[tid].buffer.abort()
            del self._tasks[tid]

    def create_or_update(
        self, task_id: str, payload: dict, trace=None
    ) -> SqlTask:
        with self._lock:
            self._reap()
            task = self._tasks.get(task_id)
            if task is None:
                task = SqlTask(task_id, self.engine, payload, trace=trace)
                self._tasks[task_id] = task
            return task

    def get(self, task_id: str) -> Optional[SqlTask]:
        with self._lock:
            return self._tasks.get(task_id)

    def cancel(self, task_id: str, speculative: bool = False) -> bool:
        task = self.get(task_id)
        if task is None:
            return False
        task.cancel(speculative=speculative)
        return True

    def tasks(self) -> list[SqlTask]:
        with self._lock:
            return list(self._tasks.values())
