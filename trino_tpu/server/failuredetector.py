"""Heartbeat failure detection for worker nodes.

Reference: ``core/trino-main/.../failuredetector/HeartbeatFailureDetector.java:78``
— the coordinator periodically pings every discovered service; an
exponentially-decayed failure ratio above a threshold marks the node
failed, and schedulers exclude failed nodes. Recovery is automatic when
pings succeed again. (v356 has no mid-query retry — a lost worker fails
its queries; here ``trino_tpu/ft`` adds TASK/QUERY retry on top, and its
retry placement consults :meth:`HeartbeatFailureDetector.active_nodes`
to steer re-dispatched attempts away from sick workers.)
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional

DEFAULT_THRESHOLD = 0.1  # failure-ratio above this marks the node failed
DECAY_SECONDS = 30.0  # exponential decay horizon of the failure ratio


@dataclasses.dataclass
class NodeState:
    node_id: str
    uri: str
    decay_seconds: float = DECAY_SECONDS
    failure_ratio: float = 0.0
    last_update: float = 0.0
    last_seen: Optional[float] = None
    consecutive_failures: int = 0
    # EWMA of successful-ping round-trip latency; None until the first
    # success. Task placement uses it to steer hedges and recovery
    # re-dispatches away from the slowest healthy node.
    latency_ewma_ms: Optional[float] = None

    @property
    def known(self) -> bool:
        """Whether this node has ever been pinged. A registered-but-
        never-pinged node has no evidence either way; it must not be
        reported as healthy on the strength of its initial 0.0 ratio."""
        return self.last_update > 0.0

    def record(self, success: bool, now: float,
               latency_ms: Optional[float] = None) -> None:
        # exponential decay toward the new observation
        # (HeartbeatFailureDetector.Stats.DecayCounter)
        if self.last_update:
            dt = max(0.0, now - self.last_update)
            alpha = 2 ** (-dt / self.decay_seconds)
        else:
            alpha = 0.0
        observation = 0.0 if success else 1.0
        self.failure_ratio = alpha * self.failure_ratio + (1 - alpha) * observation
        self.last_update = now
        if success:
            self.last_seen = now
            self.consecutive_failures = 0
            if latency_ms is not None:
                self.latency_ewma_ms = (
                    latency_ms
                    if self.latency_ewma_ms is None
                    else 0.75 * self.latency_ewma_ms + 0.25 * latency_ms
                )
        else:
            self.consecutive_failures += 1


class HeartbeatFailureDetector:
    """Pings registered nodes with ``ping_fn(uri) -> bool`` on a cadence;
    ``active_nodes()`` is what schedulers consult."""

    def __init__(
        self,
        ping_fn: Callable[[str], bool],
        interval: float = 0.5,
        threshold: float = DEFAULT_THRESHOLD,
        decay_seconds: float = DECAY_SECONDS,
    ):
        self.ping_fn = ping_fn
        self.interval = interval
        self.threshold = threshold
        self.decay_seconds = decay_seconds
        self._nodes: dict[str, NodeState] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self, node_id: str, uri: str) -> None:
        with self._lock:
            self._nodes[node_id] = NodeState(node_id, uri, self.decay_seconds)

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def start(self) -> "HeartbeatFailureDetector":
        if self._thread is not None and self._thread.is_alive():
            return self  # already running
        # a restarted detector must not inherit the previous stop() — a
        # set event makes the new loop exit before its first ping
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.ping_all()

    def ping_all(self) -> None:
        with self._lock:
            nodes = list(self._nodes.values())
        now = time.time()
        for n in nodes:
            t0 = time.monotonic()
            try:
                ok = bool(self.ping_fn(n.uri))
            except Exception:  # noqa: BLE001 — any ping error is a failure
                ok = False
            n.record(
                ok, now, latency_ms=(time.monotonic() - t0) * 1000.0
            )

    def is_failed(self, node_id: str) -> bool:
        """Positive evidence of failure. A never-pinged node is NOT
        failed (no evidence) — but neither is it active; membership
        freshness (announce timeout) covers it until the first ping."""
        with self._lock:
            n = self._nodes.get(node_id)
        if n is None:
            return True
        return n.known and n.failure_ratio > self.threshold

    def latency_ms(self, node_id: str) -> float:
        """Ping-latency EWMA for placement ranking; 0.0 when unknown (a
        fresh node ranks neutral, preserving round-robin tie-breaks)."""
        with self._lock:
            n = self._nodes.get(node_id)
        if n is None or n.latency_ewma_ms is None:
            return 0.0
        return n.latency_ewma_ms

    def active_nodes(self) -> list[str]:
        """Nodes with positive evidence of health: pinged at least once
        and below the failure threshold. Retry placement uses this —
        never-pinged nodes are unknown, not healthy."""
        with self._lock:
            nodes = list(self._nodes.values())
        return [
            n.node_id
            for n in nodes
            if n.known and n.failure_ratio <= self.threshold
        ]

    def info(self) -> list[dict]:
        with self._lock:
            nodes = list(self._nodes.values())
        return [
            {
                "nodeId": n.node_id,
                "uri": n.uri,
                "failureRatio": round(n.failure_ratio, 4),
                "known": n.known,
                "failed": n.known and n.failure_ratio > self.threshold,
                "lastSeen": n.last_seen,
                "latencyEwmaMs": (
                    round(n.latency_ewma_ms, 3)
                    if n.latency_ewma_ms is not None
                    else None
                ),
            }
            for n in nodes
        ]
