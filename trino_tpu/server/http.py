"""HTTP server: client statement protocol + node endpoints.

Reference: ``dispatcher/QueuedStatementResource.java:93,171`` and
``server/protocol/ExecutingStatementResource.java:76,145`` (the two-phase
queued → executing nextUri protocol driven by
``client/trino-client/.../StatementClientV1.java:62,125,324``),
``QueryResource``, ``StatusResource``, ``ServerInfoResource`` and
``GracefulShutdownHandler.java:43`` (PUT /v1/info/state SHUTTING_DOWN).

Implementation: stdlib ``http.server`` (threaded), JSON wire format with
the reference's ``QueryResults`` field names and ``X-Trino-*`` headers so
protocol-compatible clients feel at home.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from decimal import Decimal
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from trino_tpu import types as T
from trino_tpu.config import Session
from trino_tpu.engine import Engine
from trino_tpu.server.querymanager import ManagedQuery, QueryManager
from trino_tpu.server.statemachine import QueryState

PAGE_ROWS = 4096  # rows per protocol page (reference: target result bytes)
PROTOCOL_HEADER = "X-Trino"
VERSION = "trino-tpu-0.1 (356-compatible)"


def _json_value(v: Any) -> Any:
    if isinstance(v, Decimal):
        return str(v)
    return v


class TrinoTpuServer:
    """Coordinator server wrapping Engine + QueryManager.

    The same class serves coordinator and (future multi-host) worker roles,
    mirroring the reference's single binary with ``coordinator=true/false``.
    """

    def __init__(
        self,
        engine: Optional[Engine] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        max_concurrent: int = 16,
        resource_groups=None,
        role: str = "coordinator",
        node_id: Optional[str] = None,
        discovery_uri: Optional[str] = None,
        spmd: bool = False,
        cluster_memory_limit_bytes: Optional[int] = None,
    ):
        from trino_tpu.obs.trace import InMemorySpanSink, get_tracer
        from trino_tpu.server.resourcegroups import ResourceGroupManager
        from trino_tpu.server.task import SqlTaskManager

        self.engine = engine or Engine()
        # registering a sink is what turns tracing ON for this process;
        # a bare Engine (no server) stays dark and pays nothing
        self.span_sink = InMemorySpanSink()
        get_tracer().add_sink(self.span_sink)
        self.role = role
        self.node_id = node_id or f"{role}-{port}"
        # tasks need the node identity for delay-fault targeting
        # (ft/injection.py is_slow_node) and task-span attribution
        self.engine.node_id = self.node_id
        self.discovery_uri = discovery_uri
        self.resource_groups = resource_groups or ResourceGroupManager()
        # every node can run tasks (reference: same binary, coordinator=true/false)
        self.task_manager = SqlTaskManager(self.engine)
        self.node_manager = None
        self.spmd = None
        if spmd:
            from trino_tpu.parallel.spmd import SpmdRunner

            self.spmd = SpmdRunner(self.engine)
            self.engine.spmd = self.spmd
        if role == "coordinator":
            from trino_tpu.server.cluster import ClusterNodeManager, ClusterScheduler

            self.node_manager = ClusterNodeManager()
            self.engine.cluster_scheduler = ClusterScheduler(
                self.engine, self.node_manager
            )
            if self.spmd is not None:
                self.engine.spmd_peers = lambda: [
                    n.uri for n in self.node_manager.active_nodes()
                ]
        self.cluster_memory_manager = None
        if role == "coordinator":
            from trino_tpu.memory import ClusterMemoryManager

            self.cluster_memory_manager = ClusterMemoryManager(
                self.engine.memory_pool,
                cluster_memory_limit_bytes or (64 << 30),
                kill_fn=lambda qid, msg: self.query_manager.kill(qid, msg),
            )
        # event-driven admission: queries queue as resource-group waiters
        # (no parked thread per QUEUED query) and run on a bounded pool
        self.query_manager = QueryManager(
            self.engine,
            max_concurrent,
            resource_groups=self.resource_groups,
        )
        self.start_time = time.time()
        self.state = "ACTIVE"  # ACTIVE | SHUTTING_DOWN (NodeState)
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.host, self.port = self.httpd.server_address[:2]
        if role == "coordinator":
            # where workers spool finished output buffers (the scheduler
            # passes this to tasks as payload["spool"]["uri"])
            self.engine.spool_base_uri = self.base_uri
        self._thread: Optional[threading.Thread] = None
        # live node info for system.runtime.nodes
        self.engine._runtime_nodes_fn = lambda: [
            ("coordinator", self.base_uri, VERSION, True, self.state)
        ]
        # live task registry for system.runtime.tasks (this node's
        # SqlTaskManager — on a coordinator that includes any local tasks)
        self.engine._runtime_tasks_fn = lambda: [
            t.info() for t in self.task_manager.tasks()
        ]

    # --- lifecycle --------------------------------------------------------

    def start(self) -> "TrinoTpuServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        if self.role == "worker" and self.discovery_uri:
            self._announce_thread = threading.Thread(
                target=self._announce_loop, daemon=True
            )
            self._announce_thread.start()
        return self

    def _announce_loop(self) -> None:
        """Periodic worker announcement to the coordinator's embedded
        discovery (reference: airlift discovery announcer)."""
        import urllib.request as _rq

        while self.state == "ACTIVE":
            if self.discovery_uri and not self.discovery_uri.startswith("@"):
                try:
                    from trino_tpu.server import auth

                    pool = self.engine.memory_pool
                    with pool._lock:
                        reservations = dict(pool._query_reserved)
                    body = json.dumps(
                        {
                            "nodeId": self.node_id,
                            "uri": self.base_uri,
                            "memoryInfo": {
                                "capacityBytes": pool.capacity,
                                "reservedBytes": sum(reservations.values()),
                                "queryReservations": reservations,
                            },
                        }
                    ).encode()
                    req = _rq.Request(
                        f"{self.discovery_uri}/v1/announce",
                        data=body,
                        method="PUT",
                        headers=auth.headers(),
                    )
                    _rq.urlopen(req, timeout=10)
                except Exception:  # noqa: BLE001 — coordinator may not be up yet
                    pass
            time.sleep(2.0)

    def stop(self) -> None:
        from trino_tpu.obs.trace import get_tracer

        self.state = "STOPPED"
        self.httpd.shutdown()
        self.httpd.server_close()
        self.query_manager.shutdown(wait=False)
        get_tracer().remove_sink(self.span_sink)

    def graceful_shutdown(self) -> None:
        """Drain, then stop (GracefulShutdownHandler.java:142).

        Coordinator: refuse new queries, wait for active ones.
        Worker decommission: refuse new tasks (task POST 503s while not
        ACTIVE), finish running tasks, force-publish every retained
        buffer's spool manifest so consumers can re-read the output after
        this process is gone, deregister from the coordinator, and exit —
        the rolling-restart path with zero query failures."""
        self.state = "SHUTTING_DOWN"
        drain = self._drain_worker if self.role == "worker" else self._drain
        threading.Thread(target=drain, daemon=True).start()

    def _drain(self) -> None:
        while any(
            not q.state.is_terminal() for q in self.query_manager.queries()
        ):
            time.sleep(0.05)
        self.stop()

    def _drain_worker(self, timeout: float = 120.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and any(
            t.state == "RUNNING" for t in self.task_manager.tasks()
        ):
            time.sleep(0.05)
        # force-spool retained buffers: a consumer stage that has not yet
        # pulled this worker's output reads it from the coordinator's
        # spool once we are gone (finish() is idempotent — tasks that
        # already published on FINISHED return their cached result).
        # A fused-unit task is no different: its single retained buffer
        # IS the unit-boundary output, so the whole unit stays readable
        for t in self.task_manager.tasks():
            writer = getattr(t.buffer, "spool_writer", None)
            if writer is not None and t.state == "FINISHED":
                try:
                    writer.finish(timeout=30.0)
                except Exception:  # noqa: BLE001 — best-effort
                    pass
        if self.discovery_uri and not self.discovery_uri.startswith("@"):
            import urllib.request as _rq

            from trino_tpu.server import auth

            try:
                req = _rq.Request(
                    f"{self.discovery_uri}/v1/announce/{self.node_id}",
                    method="DELETE",
                    headers=auth.headers(),
                )
                _rq.urlopen(req, timeout=10)
            except Exception:  # noqa: BLE001 — coordinator may be gone too
                pass
        # grace: let in-flight result GETs finish before the socket closes
        time.sleep(0.5)
        self.stop()

    @property
    def base_uri(self) -> str:
        return f"http://{self.host}:{self.port}"

    # --- protocol helpers -------------------------------------------------

    def query_results(self, q: ManagedQuery, phase: str, token: int) -> dict:
        state = q.state.get()
        uri = f"{self.base_uri}/v1/statement"
        out: dict[str, Any] = {
            "id": q.query_id,
            "infoUri": f"{self.base_uri}/v1/query/{q.query_id}",
            "warnings": [],
        }
        stats = {
            "state": state.value,
            "queued": state == QueryState.QUEUED,
            "scheduled": state
            in (QueryState.RUNNING, QueryState.FINISHING, QueryState.FINISHED),
            "nodes": 1,
            "elapsedTimeMillis": int(
                ((q.end_time or time.time()) - q.create_time) * 1000
            ),
            "peakMemoryBytes": q.result.peak_memory_bytes if q.result else 0,
        }
        out["stats"] = stats

        if state == QueryState.FAILED or state == QueryState.CANCELED:
            out["error"] = (q.error.to_json() if q.error else
                            {"message": "query failed", "errorCode": 65536,
                             "errorName": "GENERIC_INTERNAL_ERROR",
                             "errorType": "INTERNAL_ERROR"})
            return out

        if phase == "queued":
            if state in (QueryState.QUEUED, QueryState.PLANNING):
                out["nextUri"] = f"{uri}/queued/{q.query_id}/{q.slug}/{token}"
            else:
                out["nextUri"] = f"{uri}/executing/{q.query_id}/{q.slug}/0"
            return out

        # executing phase: page through buffered results
        if q.result is None:  # still running
            out["nextUri"] = f"{uri}/executing/{q.query_id}/{q.slug}/{token}"
            return out
        res = q.result
        out["columns"] = [
            {
                "name": n,
                "type": str(ty),
                "typeSignature": {"rawType": _raw_type(ty), "arguments": []},
            }
            for n, ty in zip(res.column_names, res.column_types)
        ]
        if res.update_type is not None:
            out["updateType"] = res.update_type
        if res.update_count is not None:
            out["updateCount"] = res.update_count
        lo = token * PAGE_ROWS
        hi = min(lo + PAGE_ROWS, len(res.rows))
        if lo < len(res.rows):
            out["data"] = [
                [_json_value(v) for v in row] for row in res.rows[lo:hi]
            ]
        if hi < len(res.rows):
            out["nextUri"] = f"{uri}/executing/{q.query_id}/{q.slug}/{token + 1}"
        else:
            out["partialCancelUri"] = None
        if res.set_session:
            out["_setSession"] = {k: v for k, v in res.set_session.items()}
        if res.added_prepare is not None:
            out["_addedPrepare"] = res.added_prepare
        if res.deallocated_prepare is not None:
            out["_deallocatedPrepare"] = res.deallocated_prepare
        if res.started_transaction_id:
            out["_startedTransaction"] = res.started_transaction_id
        if res.cleared_transaction:
            out["_clearedTransaction"] = True
        return out


def _raw_type(ty: T.SqlType) -> str:
    s = str(ty)
    return s.split("(")[0]


def _make_handler(server: TrinoTpuServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = VERSION

        # --- plumbing ----------------------------------------------------

        def log_message(self, fmt, *args):  # quiet
            pass

        def _send_json(self, obj: Any, status: int = 200, headers: Optional[dict] = None):
            body = json.dumps(obj).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _error(self, status: int, message: str):
            self._send_json({"error": message}, status)

        def _check_internal_auth(self) -> bool:
            from trino_tpu.server import auth

            path = urllib.parse.urlparse(self.path).path
            if auth.is_internal_path(path) and not auth.authorized(self.headers):
                self._error(401, "missing or invalid internal credential")
                return False
            return True

        def _send_no_content(self):
            # 204 must carry no body (RFC 9110); body bytes would desync
            # keep-alive connections
            self.send_response(204)
            self.end_headers()

        def _session_from_headers(self) -> Session:
            h = self.headers
            s = Session(
                user=h.get(f"{PROTOCOL_HEADER}-User", "anonymous"),
                catalog=h.get(f"{PROTOCOL_HEADER}-Catalog", "tpch"),
                schema=h.get(f"{PROTOCOL_HEADER}-Schema", "tiny"),
                source=h.get(f"{PROTOCOL_HEADER}-Source", ""),
            )
            raw = h.get(f"{PROTOCOL_HEADER}-Session", "")
            for part in raw.split(","):
                part = part.strip()
                if not part or "=" not in part:
                    continue
                k, v = part.split("=", 1)
                s.set(k.strip(), _decode_session_value(urllib.parse.unquote(v.strip())))
            txn = h.get(f"{PROTOCOL_HEADER}-Transaction-Id", "")
            if txn and txn.upper() != "NONE":
                # Validate against the TransactionManager: a bogus id would
                # make write paths skip the single-writer lock (reference
                # errors on unknown transaction ids).
                server.engine.transaction_manager.get(txn)  # raises if unknown
                s.properties["__txn"] = txn
            # prepared statements ride headers (the protocol is stateless):
            # X-Trino-Prepared-Statement: name=<urlencoded sql>[,name=...]
            raw = h.get(f"{PROTOCOL_HEADER}-Prepared-Statement", "")
            for part in raw.split(","):
                part = part.strip()
                if not part or "=" not in part:
                    continue
                k, v = part.split("=", 1)
                s.prepared[k.strip().lower()] = urllib.parse.unquote(v.strip())
            return s

        # --- routes ------------------------------------------------------

        def do_POST(self):
            if not self._check_internal_auth():
                return None
            path = urllib.parse.urlparse(self.path).path
            if path == "/v1/statement":
                if server.state != "ACTIVE":
                    return self._error(503, "server is shutting down")
                length = int(self.headers.get("Content-Length", 0))
                sql = self.rfile.read(length).decode()
                if not sql.strip():
                    return self._error(400, "SQL statement is empty")
                from trino_tpu.transaction import TransactionError

                try:
                    session = self._session_from_headers()
                except TransactionError as e:
                    return self._error(400, str(e))
                q = server.query_manager.create_query(sql, session)
                return self._send_json(server.query_results(q, "queued", 0))
            parts = [p for p in path.split("/") if p]
            if len(parts) == 3 and parts[:2] == ["v1", "task"]:
                # TaskResource.createOrUpdateTask (reference :127)
                if server.state != "ACTIVE":
                    # draining worker: refuse admission; the coordinator
                    # classifies the 503 retryable and re-dispatches the
                    # attempt to another node
                    return self._error(503, "worker is shutting down")
                from trino_tpu.obs.trace import TRACE_HEADER, parse_trace_header

                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length).decode())
                # coordinator attempt span context: the worker's
                # task_execute span parents to it across the process gap
                trace = parse_trace_header(self.headers.get(TRACE_HEADER))
                task = server.task_manager.create_or_update(
                    parts[2], payload, trace=trace
                )
                return self._send_json(task.info())
            if path == "/v1/write":
                # scaled-writer data plane: binary serialized batch in the
                # body, target table in query params; the connector appends
                # a part file on shared storage (reference: TableWriter
                # tasks under ScaledWriterScheduler)
                q = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
                length = int(self.headers.get("Content-Length", 0))
                payload = self.rfile.read(length)
                try:
                    from trino_tpu.serde import deserialize_batch

                    batch = deserialize_batch(payload)
                    conn = server.engine.catalogs.get(q["catalog"][0])
                    part = ""
                    if hasattr(conn, "insert_part"):
                        n, part = conn.insert_part(
                            q["schema"][0], q["table"][0], batch
                        )
                    else:
                        n = conn.insert(q["schema"][0], q["table"][0], batch)
                    # part name lets the coordinator roll back committed
                    # parts when a sibling scaled writer fails
                    return self._send_json({"rows": n, "part": part})
                except Exception as e:  # noqa: BLE001
                    return self._error(400, f"write failed: {e}")
            if path == "/v1/spmd":
                if server.spmd is None:
                    return self._error(400, "spmd mode not enabled")
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length).decode())
                return self._send_json(server.spmd.execute_remote(payload))
            if len(parts) == 3 and parts[:2] == ["v1", "spool"]:
                # spooled exchange: a worker POSTs one finished-output page
                # (raw bytes; idempotent per (task, partition, seq))
                from trino_tpu.exchange.spool import get_spool_store

                q = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
                length = int(self.headers.get("Content-Length", 0))
                page = self.rfile.read(length)
                store = get_spool_store(server.engine)
                accepted = store.put_page(
                    q.get("query", [""])[0],
                    parts[2],
                    int(q.get("partition", ["0"])[0]),
                    int(q.get("seq", ["0"])[0]),
                    page,
                )
                return self._send_json({"accepted": accepted})
            return self._error(404, f"unknown path: {path}")

        def do_GET(self):
            if not self._check_internal_auth():
                return None
            path = urllib.parse.urlparse(self.path).path
            parts = [p for p in path.split("/") if p]
            if path == "/v1/info":
                return self._send_json(
                    {
                        "nodeVersion": {"version": VERSION},
                        "environment": "tpu",
                        "coordinator": True,
                        "starting": False,
                        "uptime": f"{time.time() - server.start_time:.2f}s",
                    }
                )
            if path == "/v1/memory":
                if server.cluster_memory_manager is None:
                    return self._error(404, "not a coordinator")
                return self._send_json(server.cluster_memory_manager.info())
            if path == "/v1/info/state":
                return self._send_json(server.state)
            if path == "/v1/status":
                pool = server.engine.memory_pool
                return self._send_json(
                    {
                        "nodeId": "coordinator",
                        "nodeVersion": VERSION,
                        "state": server.state,
                        "coordinator": True,
                        "memoryInfo": {
                            "totalNodeMemory": pool.capacity,
                            "reservedBytes": pool.reserved,
                            "freeBytes": pool.free_bytes,
                        },
                        "queries": len(server.query_manager.queries()),
                        # system.runtime.queries-style admission breakdown
                        # (the knee is visible without running the bench)
                        "queryCounts": server.query_manager.state_counts(),
                        "resourceGroups": server.resource_groups.summary(),
                    }
                )
            if path in ("/ui", "/ui/", "/"):
                from trino_tpu.server.webui import PAGE

                body = PAGE.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return None
            if path == "/v1/resourceGroup":
                return self._send_json(server.resource_groups.info())
            if path == "/v1/task":
                return self._send_json(
                    [t.info() for t in server.task_manager.tasks()]
                )
            if len(parts) == 3 and parts[:2] == ["v1", "task"]:
                # task status, optional long-poll (?maxWait=seconds)
                task = server.task_manager.get(parts[2])
                if task is None:
                    return self._error(404, "task not found")
                qs = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
                max_wait = float(qs.get("maxWait", ["0"])[0])
                deadline = time.time() + max_wait
                while task.state == "RUNNING" and time.time() < deadline:
                    time.sleep(0.02)
                return self._send_json(task.info())
            if (
                len(parts) == 6
                and parts[:2] == ["v1", "task"]
                and parts[3] == "results"
            ):
                # GET /v1/task/{id}/results/{partition}/{token}[?maxWait=s]
                # (TaskResource.java:261 paged binary fetch)
                task = server.task_manager.get(parts[2])
                if task is None:
                    return self._error(404, "task not found")
                qs = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
                try:
                    max_wait = min(30.0, float(qs.get("maxWait", ["1.0"])[0]))
                except ValueError:
                    max_wait = 1.0
                if max_wait != max_wait:  # NaN guard
                    max_wait = 1.0
                return self._send_json(
                    task.results(int(parts[4]), int(parts[5]), max_wait=max_wait)
                )
            if (
                len(parts) == 6
                and parts[:2] == ["v1", "spool"]
                and parts[3] == "results"
            ):
                # GET /v1/spool/{taskId}/results/{partition}/{token} — the
                # exact task-results wire shape, so ExchangeClient pulls a
                # spool URI exactly like a live worker's buffer
                store = getattr(server.engine, "spool_store", None)
                out = (
                    store.read(parts[2], int(parts[4]), int(parts[5]))
                    if store is not None
                    else None
                )
                if out is None:
                    return self._error(404, "spooled task not found")
                return self._send_json(out)
            if path == "/v1/spool":
                store = getattr(server.engine, "spool_store", None)
                return self._send_json(
                    store.stats() if store is not None else {}
                )
            if path == "/v1/node":
                if server.node_manager is None:
                    return self._send_json([])
                return self._send_json(
                    {
                        "nodes": [n.to_json() for n in server.node_manager.all_nodes()],
                        "failureInfo": server.node_manager.failure_detector.info(),
                    }
                )
            if path == "/v1/metrics":
                # Prometheus text scrape (text format 0.0.4); ?format=json
                # returns the structured snapshot for bench/chaos embeds
                from trino_tpu.obs.metrics import get_registry

                qs = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
                if qs.get("format", [""])[0] == "json":
                    return self._send_json(get_registry().snapshot())
                body = get_registry().render_prometheus().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return None
            if path == "/v1/history":
                # per-fingerprint observed execution truth (obs/history.py):
                # one entry per store the engine resolved, most-recently-
                # used fingerprints first
                snap_fn = getattr(server.engine, "history_snapshot", None)
                return self._send_json(
                    snap_fn() if callable(snap_fn) else {"stores": []}
                )
            if path == "/v1/query":
                return self._send_json(
                    [q.info() for q in server.query_manager.queries()]
                )
            if (
                len(parts) == 4
                and parts[:2] == ["v1", "query"]
                and parts[3] == "timeline"
            ):
                # span dump for one trace (= query id). Workers hold spans
                # for queries they never registered, so 404 only when the
                # id is unknown to BOTH the query manager and the sink.
                spans = server.span_sink.spans_for(parts[2])
                if not spans and server.query_manager.get(parts[2]) is None:
                    return self._error(404, "query not found")
                return self._send_json({"queryId": parts[2], "spans": spans})
            if len(parts) == 3 and parts[:2] == ["v1", "query"]:
                q = server.query_manager.get(parts[2])
                if q is None:
                    return self._error(404, "query not found")
                return self._send_json(q.info())
            if len(parts) == 6 and parts[:2] == ["v1", "statement"]:
                phase, qid, slug, token = parts[2], parts[3], parts[4], parts[5]
                q = server.query_manager.get(qid)
                if q is None or q.slug != slug:
                    return self._error(404, "query not found")
                q.touch()
                max_wait = _parse_duration(
                    self.headers.get(f"{PROTOCOL_HEADER}-Max-Wait", "1s")
                )
                if phase == "queued":
                    q.state.wait_for(
                        lambda s: s not in (QueryState.QUEUED, QueryState.PLANNING),
                        max_wait,
                    )
                else:
                    from trino_tpu.server.statemachine import TERMINAL_QUERY_STATES

                    q.state.wait_for(
                        lambda s: q.result is not None or s in TERMINAL_QUERY_STATES,
                        max_wait,
                    )
                out = server.query_results(q, phase, int(token))
                headers = {}
                set_session = out.pop("_setSession", None)
                if set_session:
                    for k, v in set_session.items():
                        headers[f"{PROTOCOL_HEADER}-Set-Session"] = (
                            f"{k}={urllib.parse.quote(str(v))}"
                        )
                added = out.pop("_addedPrepare", None)
                if added:
                    headers[f"{PROTOCOL_HEADER}-Added-Prepare"] = (
                        f"{added[0]}={urllib.parse.quote(added[1])}"
                    )
                dealloc = out.pop("_deallocatedPrepare", None)
                if dealloc:
                    headers[f"{PROTOCOL_HEADER}-Deallocated-Prepare"] = dealloc
                started = out.pop("_startedTransaction", None)
                if started:
                    headers[f"{PROTOCOL_HEADER}-Started-Transaction-Id"] = started
                if out.pop("_clearedTransaction", None):
                    headers[f"{PROTOCOL_HEADER}-Clear-Transaction-Id"] = "true"
                return self._send_json(out, headers=headers)
            return self._error(404, f"unknown path: {path}")

        def do_DELETE(self):
            if not self._check_internal_auth():
                return None
            path = urllib.parse.urlparse(self.path).path
            parts = [p for p in path.split("/") if p]
            if len(parts) >= 5 and parts[:2] == ["v1", "statement"]:
                qid, slug = parts[3], parts[4]
                q = server.query_manager.get(qid)
                if q is None or q.slug != slug:  # slug = per-query secret
                    return self._error(404, "query not found")
                q.cancel()
                return self._send_no_content()
            if len(parts) == 3 and parts[:2] == ["v1", "query"]:
                if server.query_manager.cancel(parts[2]):
                    return self._send_no_content()
                return self._error(404, "query not found")
            if len(parts) == 3 and parts[:2] == ["v1", "task"]:
                # ?speculative=true marks a hedged-attempt loser: the state
                # machine records CANCELED_SPECULATIVE instead of CANCELED
                qs = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
                speculative = qs.get("speculative", [""])[0] == "true"
                if server.task_manager.cancel(parts[2], speculative=speculative):
                    return self._send_no_content()
                return self._error(404, "task not found")
            if len(parts) == 3 and parts[:2] == ["v1", "spool"]:
                # aborted spool write / cancelled attempt: drop its pages
                store = getattr(server.engine, "spool_store", None)
                if store is not None:
                    store.delete_task(parts[2])
                return self._send_no_content()
            if len(parts) == 3 and parts[:2] == ["v1", "announce"]:
                # worker decommission: deregister from discovery AND the
                # failure detector (a drained node must not be pinged or
                # counted failed afterwards)
                if server.node_manager is None:
                    return self._error(400, "not a coordinator")
                server.node_manager.decommission(parts[2])
                return self._send_no_content()
            return self._error(404, f"unknown path: {path}")

        def do_PUT(self):
            if not self._check_internal_auth():
                return None
            path = urllib.parse.urlparse(self.path).path
            if path == "/v1/discovery":
                # late discovery injection (SPMD boot: the coordinator's
                # HTTP port is unknown until every rank joins the mesh)
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length).decode())
                server.discovery_uri = body["uri"]
                return self._send_json({"ok": True})
            if path == "/v1/announce":
                # embedded discovery: workers announce themselves
                if server.node_manager is None:
                    return self._error(400, "not a coordinator")
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length).decode())
                server.node_manager.announce(body["nodeId"], body["uri"])
                if server.cluster_memory_manager is not None:
                    server.cluster_memory_manager.update(
                        body["nodeId"], body.get("memoryInfo")
                    )
                return self._send_json({"ok": True})
            if path == "/v1/info/state":
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length).decode().strip().strip('"')
                if body == "SHUTTING_DOWN":
                    server.graceful_shutdown()
                    return self._send_json({}, 200)
                return self._error(400, f"unsupported state: {body}")
            parts = [p for p in path.split("/") if p]
            if (
                len(parts) == 4
                and parts[:2] == ["v1", "spool"]
                and parts[3] == "complete"
            ):
                # spool completion manifest: {queryId, partitions: {p: n}}
                from trino_tpu.exchange.spool import get_spool_store

                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length).decode())
                store = get_spool_store(server.engine)
                ok = store.complete(
                    parts[2],
                    body.get("queryId", ""),
                    {
                        int(p): int(n)
                        for p, n in body.get("partitions", {}).items()
                    },
                )
                return self._send_json({"complete": ok})
            return self._error(404, f"unknown path: {path}")

    return Handler


def _decode_session_value(v: str) -> Any:
    low = v.lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        pass
    return v


def _parse_duration(text: str) -> float:
    text = text.strip().lower()
    for suffix, mult in (("ms", 0.001), ("s", 1.0), ("m", 60.0)):
        if text.endswith(suffix):
            try:
                return float(text[: -len(suffix)]) * mult
            except ValueError:
                return 1.0
    try:
        return float(text)
    except ValueError:
        return 1.0
