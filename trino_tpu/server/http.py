"""HTTP server: client statement protocol + node endpoints.

Reference: ``dispatcher/QueuedStatementResource.java:93,171`` and
``server/protocol/ExecutingStatementResource.java:76,145`` (the two-phase
queued → executing nextUri protocol driven by
``client/trino-client/.../StatementClientV1.java:62,125,324``),
``QueryResource``, ``StatusResource``, ``ServerInfoResource`` and
``GracefulShutdownHandler.java:43`` (PUT /v1/info/state SHUTTING_DOWN).

Implementation: a non-blocking ``selectors`` event loop
(``server/eventloop.py``) instead of a thread per connection, mirroring
the reference's async HTTP stack: idle ``nextUri`` pollers cost a parked
:class:`Responder` each, long-poll ``maxWait`` waits are loop timers +
state-machine listeners, and handler work that must block (engine
dispatch, task creation, spool IO) runs on a bounded ``_DispatchPool``
with completion callbacks back onto the loop.  The robustness layer on
top: per-tenant token-bucket rate limits, a global in-flight ceiling
(over-limit requests shed with ``503 + Retry-After`` and counted in
``trino_tpu_requests_shed_total{reason}``), client-abandonment reaping
(a query whose ``nextUri`` goes unpolled past ``client_timeout_s`` is
canceled and its admission slot freed), and byte-budgeted streaming
result pages with producer backpressure.
"""

from __future__ import annotations

import json
import math
import threading
import time
import urllib.parse
from decimal import Decimal
from typing import Any, Callable, Optional

from trino_tpu import types as T
from trino_tpu.config import ServerConfig, Session
from trino_tpu.engine import Engine
from trino_tpu.server.eventloop import (
    EventLoopHttpServer,
    Request,
    Responder,
    Response,
    TenantRateLimiter,
    json_response,
    parse_max_wait,
)
from trino_tpu.server.querymanager import (
    ManagedQuery,
    QueryManager,
    _DispatchPool,
)
from trino_tpu.server.statemachine import (
    QueryState,
    TERMINAL_QUERY_STATES,
)

PAGE_ROWS = 4096  # rows per protocol page (reference: target result bytes)
PROTOCOL_HEADER = "X-Trino"
VERSION = "trino-tpu-0.1 (356-compatible)"

# task/spool long-polls re-check on the loop at this cadence instead of
# parking a thread in the buffer's condition wait
_TASK_POLL_S = 0.015


def _json_value(v: Any) -> Any:
    if isinstance(v, Decimal):
        return str(v)
    return v


class TrinoTpuServer:
    """Coordinator server wrapping Engine + QueryManager.

    The same class serves coordinator and (future multi-host) worker roles,
    mirroring the reference's single binary with ``coordinator=true/false``.
    """

    def __init__(
        self,
        engine: Optional[Engine] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        max_concurrent: int = 16,
        resource_groups=None,
        role: str = "coordinator",
        node_id: Optional[str] = None,
        discovery_uri: Optional[str] = None,
        spmd: bool = False,
        cluster_memory_limit_bytes: Optional[int] = None,
        server_config: Optional[ServerConfig] = None,
    ):
        from trino_tpu.obs.trace import InMemorySpanSink, get_tracer
        from trino_tpu.server.resourcegroups import ResourceGroupManager
        from trino_tpu.server.task import SqlTaskManager

        self.engine = engine or Engine()
        self.server_config = server_config or ServerConfig()
        # registering a sink is what turns tracing ON for this process;
        # a bare Engine (no server) stays dark and pays nothing
        self.span_sink = InMemorySpanSink()
        get_tracer().add_sink(self.span_sink)
        self.role = role
        self.node_id = node_id or f"{role}-{port}"
        # tasks need the node identity for delay-fault targeting
        # (ft/injection.py is_slow_node) and task-span attribution
        self.engine.node_id = self.node_id
        self.discovery_uri = discovery_uri
        self.resource_groups = resource_groups or ResourceGroupManager()
        # every node can run tasks (reference: same binary, coordinator=true/false)
        self.task_manager = SqlTaskManager(self.engine)
        self.node_manager = None
        self.spmd = None
        if spmd:
            from trino_tpu.parallel.spmd import SpmdRunner

            self.spmd = SpmdRunner(self.engine)
            self.engine.spmd = self.spmd
        if role == "coordinator":
            from trino_tpu.server.cluster import ClusterNodeManager, ClusterScheduler

            self.node_manager = ClusterNodeManager()
            self.engine.cluster_scheduler = ClusterScheduler(
                self.engine, self.node_manager
            )
            if self.spmd is not None:
                self.engine.spmd_peers = lambda: [
                    n.uri for n in self.node_manager.active_nodes()
                ]
        self.cluster_memory_manager = None
        if role == "coordinator":
            from trino_tpu.memory import ClusterMemoryManager

            self.cluster_memory_manager = ClusterMemoryManager(
                self.engine.memory_pool,
                cluster_memory_limit_bytes or (64 << 30),
                kill_fn=lambda qid, msg: self.query_manager.kill(qid, msg),
            )
        # event-driven admission: queries queue as resource-group waiters
        # (no parked thread per QUEUED query) and run on a bounded pool
        self.query_manager = QueryManager(
            self.engine,
            max_concurrent,
            resource_groups=self.resource_groups,
        )
        self.start_time = time.time()
        self.state = "ACTIVE"  # ACTIVE | SHUTTING_DOWN (NodeState)
        cfg = self.server_config
        self.httpd = EventLoopHttpServer(
            host,
            port,
            self._handle_request,
            max_connections=cfg.max_connections,
            read_timeout_s=cfg.read_timeout_s,
            idle_timeout_s=cfg.idle_timeout_s,
            write_timeout_s=cfg.write_timeout_s,
            on_shed=lambda reason: self._count_shed(reason),
        )
        self.host, self.port = self.httpd.server_address[:2]
        # bounded workers for handler stages that must block (engine
        # dispatch, SqlTask creation, spool/connector IO) — the loop
        # thread itself never blocks
        self._front_pool = _DispatchPool(cfg.blocking_pool_size, name="http")
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._rate_limiter = TenantRateLimiter(
            cfg.tenant_rate_limit_qps, cfg.tenant_rate_limit_burst
        )
        if role == "coordinator":
            # where workers spool finished output buffers (the scheduler
            # passes this to tasks as payload["spool"]["uri"])
            self.engine.spool_base_uri = self.base_uri
        self._announce_thread: Optional[threading.Thread] = None
        # shutdown sentinel for the announce thread: stop() sets it, so the
        # thread exits immediately instead of finishing a sleep that can be
        # a 10s backoff (and the state-flag check alone can't interrupt)
        self._announce_stop = threading.Event()
        # live node info for system.runtime.nodes
        self.engine._runtime_nodes_fn = lambda: [
            ("coordinator", self.base_uri, VERSION, True, self.state)
        ]
        # live task registry for system.runtime.tasks (this node's
        # SqlTaskManager — on a coordinator that includes any local tasks)
        self.engine._runtime_tasks_fn = lambda: [
            t.info() for t in self.task_manager.tasks()
        ]

    # --- lifecycle --------------------------------------------------------

    def start(self) -> "TrinoTpuServer":
        self.httpd.start()
        interval = min(
            1.0, max(0.05, self.server_config.client_timeout_s / 4.0)
        )
        self.httpd.loop.call_later(interval, self._housekeep, interval)
        if self.role == "worker" and self.discovery_uri:
            self._announce_thread = threading.Thread(
                target=self._announce_loop, daemon=True
            )
            self._announce_thread.start()
        return self

    def _housekeep(self, interval: float) -> None:
        """Periodic loop-side maintenance: reap queries whose client
        vanished (unpolled past client_timeout_s) and publish edge gauges."""
        if self.state == "STOPPED":
            return
        try:
            self.query_manager.expire_abandoned(
                self.server_config.client_timeout_s
            )
        except Exception:  # noqa: BLE001 — maintenance must not die
            pass
        try:
            from trino_tpu.obs.metrics import get_registry

            reg = get_registry()
            reg.gauge("trino_tpu_http_open_connections").set(
                self.httpd.connection_count
            )
            reg.gauge("trino_tpu_http_inflight_requests").set(self._inflight)
        except Exception:  # noqa: BLE001
            pass
        self.httpd.loop.call_later(interval, self._housekeep, interval)

    def _announce_loop(self) -> None:
        """Periodic worker announcement to the coordinator's embedded
        discovery (reference: airlift discovery announcer). Failures back
        off exponentially (deterministic jitter) instead of hammering a
        coordinator that is not up yet."""
        import urllib.request as _rq

        from trino_tpu.ft.retry import Backoff

        backoff = Backoff(initial_ms=500.0, max_ms=10_000.0, seed=0)
        failures = 0
        while self.state == "ACTIVE" and not self._announce_stop.is_set():
            delay = 2.0
            if self.discovery_uri and not self.discovery_uri.startswith("@"):
                try:
                    from trino_tpu.server import auth

                    pool = self.engine.memory_pool
                    with pool._lock:
                        reservations = dict(pool._query_reserved)
                    body = json.dumps(
                        {
                            "nodeId": self.node_id,
                            "uri": self.base_uri,
                            "memoryInfo": {
                                "capacityBytes": pool.capacity,
                                "reservedBytes": sum(reservations.values()),
                                "queryReservations": reservations,
                            },
                        }
                    ).encode()
                    req = _rq.Request(
                        f"{self.discovery_uri}/v1/announce",
                        data=body,
                        method="PUT",
                        headers=auth.headers(),
                    )
                    _rq.urlopen(
                        req, timeout=self.server_config.http_request_timeout_s
                    )
                    failures = 0
                except Exception:  # noqa: BLE001 — coordinator may not be up yet
                    failures += 1
                    delay = backoff.delay(min(failures, 8))
            if self._announce_stop.wait(delay):
                return

    def stop(self) -> None:
        from trino_tpu.obs.trace import get_tracer

        self.state = "STOPPED"
        self._announce_stop.set()
        self.httpd.close()
        self._front_pool.shutdown()
        self.query_manager.shutdown(wait=False)
        get_tracer().remove_sink(self.span_sink)

    def graceful_shutdown(self) -> None:
        """Drain, then stop (GracefulShutdownHandler.java:142).

        Coordinator: refuse new queries (shed 503), wait for active ones.
        Worker decommission: refuse new tasks (task POST 503s while not
        ACTIVE), finish running tasks, force-publish every retained
        buffer's spool manifest so consumers can re-read the output after
        this process is gone, deregister from the coordinator, and exit —
        the rolling-restart path with zero query failures."""
        self.state = "SHUTTING_DOWN"
        drain = self._drain_worker if self.role == "worker" else self._drain
        threading.Thread(target=drain, daemon=True).start()

    def _drain(self) -> None:
        while any(
            not q.state.is_terminal() for q in self.query_manager.queries()
        ):
            time.sleep(0.05)
        # grace: let clients pull the final result pages of queries that
        # just reached a terminal state before the socket closes
        time.sleep(self.server_config.drain_grace_s)
        self.stop()

    def _drain_worker(self, timeout: Optional[float] = None) -> None:
        cfg = self.server_config
        if timeout is None:
            timeout = cfg.drain_timeout_s
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and any(
            t.state == "RUNNING" for t in self.task_manager.tasks()
        ):
            time.sleep(0.05)
        # force-spool retained buffers: a consumer stage that has not yet
        # pulled this worker's output reads it from the coordinator's
        # spool once we are gone (finish() is idempotent — tasks that
        # already published on FINISHED return their cached result).
        # A fused-unit task is no different: its single retained buffer
        # IS the unit-boundary output, so the whole unit stays readable
        for t in self.task_manager.tasks():
            writer = getattr(t.buffer, "spool_writer", None)
            if writer is not None and t.state == "FINISHED":
                try:
                    writer.finish(timeout=cfg.spool_finish_timeout_s)
                except Exception:  # noqa: BLE001 — best-effort
                    pass
        if self.discovery_uri and not self.discovery_uri.startswith("@"):
            import urllib.request as _rq

            from trino_tpu.server import auth

            try:
                req = _rq.Request(
                    f"{self.discovery_uri}/v1/announce/{self.node_id}",
                    method="DELETE",
                    headers=auth.headers(),
                )
                _rq.urlopen(req, timeout=cfg.http_request_timeout_s)
            except Exception:  # noqa: BLE001 — coordinator may be gone too
                pass
        # grace: let in-flight result GETs finish before the socket closes
        time.sleep(cfg.drain_grace_s)
        self.stop()

    @property
    def base_uri(self) -> str:
        return f"http://{self.host}:{self.port}"

    # --- protocol helpers -------------------------------------------------

    def query_results(self, q: ManagedQuery, phase: str, token: int) -> dict:
        state = q.state.get()
        uri = f"{self.base_uri}/v1/statement"
        out: dict[str, Any] = {
            "id": q.query_id,
            "infoUri": f"{self.base_uri}/v1/query/{q.query_id}",
            "warnings": [],
        }
        stats = {
            "state": state.value,
            "queued": state == QueryState.QUEUED,
            "scheduled": state
            in (QueryState.RUNNING, QueryState.FINISHING, QueryState.FINISHED),
            "nodes": 1,
            "elapsedTimeMillis": int(
                ((q.end_time or time.time()) - q.create_time) * 1000
            ),
            "peakMemoryBytes": q.result.peak_memory_bytes if q.result else 0,
        }
        out["stats"] = stats

        if state == QueryState.FAILED or state == QueryState.CANCELED:
            out["error"] = (q.error.to_json() if q.error else
                            {"message": "query failed", "errorCode": 65536,
                             "errorName": "GENERIC_INTERNAL_ERROR",
                             "errorType": "INTERNAL_ERROR"})
            return out

        if phase == "queued":
            if state in (QueryState.QUEUED, QueryState.PLANNING):
                out["nextUri"] = f"{uri}/queued/{q.query_id}/{q.slug}/{token}"
            else:
                out["nextUri"] = f"{uri}/executing/{q.query_id}/{q.slug}/0"
            return out

        # executing phase: page through buffered results
        if q.result is None:  # still running
            out["nextUri"] = f"{uri}/executing/{q.query_id}/{q.slug}/{token}"
            return out
        res = q.result
        out["columns"] = [
            {
                "name": n,
                "type": str(ty),
                "typeSignature": {"rawType": _raw_type(ty), "arguments": []},
            }
            for n, ty in zip(res.column_names, res.column_types)
        ]
        if res.update_type is not None:
            out["updateType"] = res.update_type
        if res.update_count is not None:
            out["updateCount"] = res.update_count
        budget = int(self.server_config.result_page_max_bytes or 0)
        if budget > 0:
            # streaming pager: pages cut on demand by byte budget; acked
            # pages are freed, so peak serving buffer stays bounded
            pager = q.result_pager(budget, PAGE_ROWS)
            rows, more = pager.page(token)
            if rows is not None:
                out["data"] = [
                    [_json_value(v) for v in row] for row in rows
                ]
            if more:
                out["nextUri"] = (
                    f"{uri}/executing/{q.query_id}/{q.slug}/{token + 1}"
                )
            else:
                out["partialCancelUri"] = None
        else:
            # legacy fixed-row paging over the materialized result
            lo = token * PAGE_ROWS
            hi = min(lo + PAGE_ROWS, len(res.rows))
            if lo < len(res.rows):
                out["data"] = [
                    [_json_value(v) for v in row] for row in res.rows[lo:hi]
                ]
            if hi < len(res.rows):
                out["nextUri"] = (
                    f"{uri}/executing/{q.query_id}/{q.slug}/{token + 1}"
                )
            else:
                out["partialCancelUri"] = None
        if res.set_session:
            out["_setSession"] = {k: v for k, v in res.set_session.items()}
        if res.added_prepare is not None:
            out["_addedPrepare"] = res.added_prepare
        if res.deallocated_prepare is not None:
            out["_deallocatedPrepare"] = res.deallocated_prepare
        if res.started_transaction_id:
            out["_startedTransaction"] = res.started_transaction_id
        if res.cleared_transaction:
            out["_clearedTransaction"] = True
        return out

    # --- serving edge: shedding + offload ---------------------------------

    def _count_shed(self, reason: str) -> None:
        try:
            from trino_tpu.obs.metrics import get_registry

            get_registry().counter(
                "trino_tpu_requests_shed_total", reason=reason
            ).inc()
        except Exception:  # noqa: BLE001
            pass

    def _shed(
        self,
        responder: Responder,
        reason: str,
        message: str,
        retry_after_s: Optional[float] = None,
    ) -> None:
        """503 the request. Overload sheds carry Retry-After (clients
        back off and retry); drain sheds do not (this server is going
        away — retrying it is pointless)."""
        self._count_shed(reason)
        headers = None
        if retry_after_s is not None:
            headers = {"Retry-After": str(max(1, math.ceil(retry_after_s)))}
        responder.respond(
            json_response({"error": message}, 503, headers=headers)
        )

    def _offload(
        self,
        responder: Responder,
        work: Callable[[], Response],
        ceiling: bool = True,
    ) -> None:
        """Run ``work`` on the blocking pool, responding with its result.

        ``ceiling=True`` (external, client-facing requests) enforces the
        global in-flight ceiling and sheds the excess; internal cluster
        traffic (tasks, spool, announce) bypasses the ceiling — shedding
        it would fail queries that were already admitted."""
        cfg = self.server_config
        with self._inflight_lock:
            if ceiling and self._inflight >= cfg.max_inflight_requests:
                shed = True
            else:
                self._inflight += 1
                shed = False
        if shed:
            return self._shed(
                responder,
                "inflight",
                "too many requests in flight",
                retry_after_s=cfg.shed_retry_after_s,
            )
        self._offload_submit(responder, work)

    def _offload_submit(
        self, responder: Responder, work: Callable[[], Response]
    ) -> None:
        def run() -> None:
            resp: Optional[Response] = None
            try:
                resp = work()
            except Exception as e:  # noqa: BLE001
                resp = json_response({"error": f"internal error: {e}"}, 500)
            finally:
                with self._inflight_lock:
                    self._inflight -= 1
            responder.respond(resp)

        try:
            self._front_pool.submit(run)
        except RuntimeError:  # pool shut down mid-flight
            with self._inflight_lock:
                self._inflight -= 1
            self._shed(responder, "draining", "server is shutting down")

    # --- request handling (loop thread) -----------------------------------

    def _handle_request(self, request: Request, responder: Responder) -> None:
        from trino_tpu.server import auth

        parsed = urllib.parse.urlparse(request.target)
        path = parsed.path
        if auth.is_internal_path(path) and not auth.authorized(request.headers):
            responder.respond(
                json_response(
                    {"error": "missing or invalid internal credential"}, 401
                )
            )
            return
        try:
            self._route(request, responder, path, parsed)
        except Exception as e:  # noqa: BLE001 — a route bug must not kill the loop
            responder.respond(
                json_response({"error": f"internal error: {e}"}, 500)
            )

    def _route(
        self,
        request: Request,
        responder: Responder,
        path: str,
        parsed,
    ) -> None:
        method = request.method
        parts = [p for p in path.split("/") if p]
        qs = urllib.parse.parse_qs(parsed.query)
        if method == "POST":
            return self._route_post(request, responder, path, parts, qs)
        if method == "GET":
            return self._route_get(request, responder, path, parts, qs)
        if method == "DELETE":
            return self._route_delete(request, responder, path, parts, qs)
        if method == "PUT":
            return self._route_put(request, responder, path, parts, qs)
        responder.respond(
            json_response({"error": f"unsupported method: {method}"}, 405)
        )

    # --- POST -------------------------------------------------------------

    def _route_post(self, request, responder, path, parts, qs) -> None:
        if path == "/v1/statement":
            if self.state != "ACTIVE":
                return self._shed(
                    responder, "draining", "server is shutting down"
                )
            user = request.headers.get(
                f"{PROTOCOL_HEADER}-User", "anonymous"
            ) or "anonymous"
            retry_in = self._rate_limiter.try_acquire(user)
            if retry_in > 0:
                return self._shed(
                    responder,
                    "tenant_rate_limit",
                    f"rate limit exceeded for user '{user}'",
                    retry_after_s=retry_in,
                )

            def create() -> Response:
                sql = request.body.decode()
                if not sql.strip():
                    return json_response(
                        {"error": "SQL statement is empty"}, 400
                    )
                from trino_tpu.transaction import TransactionError

                try:
                    session = _session_from_headers(
                        self.engine, request.headers
                    )
                except TransactionError as e:
                    return json_response({"error": str(e)}, 400)
                q = self.query_manager.create_query(sql, session)
                return json_response(self.query_results(q, "queued", 0))

            return self._offload(responder, create)
        if len(parts) == 3 and parts[:2] == ["v1", "task"]:
            # TaskResource.createOrUpdateTask (reference :127)
            if self.state != "ACTIVE":
                # draining worker: refuse admission; the coordinator
                # classifies the 503 retryable and re-dispatches the
                # attempt to another node
                return self._shed(
                    responder, "draining", "worker is shutting down"
                )
            from trino_tpu.obs.trace import TRACE_HEADER, parse_trace_header

            trace = parse_trace_header(request.headers.get(TRACE_HEADER))

            def create_task() -> Response:
                payload = json.loads(request.body.decode())
                task = self.task_manager.create_or_update(
                    parts[2], payload, trace=trace
                )
                return json_response(task.info())

            return self._offload(responder, create_task, ceiling=False)
        if path == "/v1/write":
            # scaled-writer data plane: binary serialized batch in the
            # body, target table in query params; the connector appends
            # a part file on shared storage (reference: TableWriter
            # tasks under ScaledWriterScheduler)
            def write() -> Response:
                try:
                    from trino_tpu.serde import deserialize_batch

                    batch = deserialize_batch(request.body)
                    conn = self.engine.catalogs.get(qs["catalog"][0])
                    part = ""
                    if hasattr(conn, "insert_part"):
                        n, part = conn.insert_part(
                            qs["schema"][0], qs["table"][0], batch
                        )
                    else:
                        n = conn.insert(qs["schema"][0], qs["table"][0], batch)
                    # part name lets the coordinator roll back committed
                    # parts when a sibling scaled writer fails
                    return json_response({"rows": n, "part": part})
                except Exception as e:  # noqa: BLE001
                    return json_response({"error": f"write failed: {e}"}, 400)

            return self._offload(responder, write, ceiling=False)
        if path == "/v1/spmd":
            if self.spmd is None:
                return responder.respond(
                    json_response({"error": "spmd mode not enabled"}, 400)
                )

            def run_spmd() -> Response:
                payload = json.loads(request.body.decode())
                return json_response(self.spmd.execute_remote(payload))

            return self._offload(responder, run_spmd, ceiling=False)
        if len(parts) == 3 and parts[:2] == ["v1", "spool"]:
            # spooled exchange: a worker POSTs one finished-output page
            # (raw bytes; idempotent per (task, partition, seq))
            def put_page() -> Response:
                from trino_tpu.exchange.spool import get_spool_store

                store = get_spool_store(self.engine)
                accepted = store.put_page(
                    qs.get("query", [""])[0],
                    parts[2],
                    int(qs.get("partition", ["0"])[0]),
                    int(qs.get("seq", ["0"])[0]),
                    request.body,
                )
                return json_response({"accepted": accepted})

            return self._offload(responder, put_page, ceiling=False)
        responder.respond(json_response({"error": f"unknown path: {path}"}, 404))

    # --- GET --------------------------------------------------------------

    def _route_get(self, request, responder, path, parts, qs) -> None:
        if path == "/v1/info":
            return responder.respond(json_response(
                {
                    "nodeVersion": {"version": VERSION},
                    "environment": "tpu",
                    "coordinator": True,
                    "starting": False,
                    "uptime": f"{time.time() - self.start_time:.2f}s",
                }
            ))
        if path == "/v1/memory":
            if self.cluster_memory_manager is None:
                return responder.respond(
                    json_response({"error": "not a coordinator"}, 404)
                )
            return responder.respond(
                json_response(self.cluster_memory_manager.info())
            )
        if path == "/v1/info/state":
            return responder.respond(json_response(self.state))
        if path == "/v1/status":
            pool = self.engine.memory_pool
            return responder.respond(json_response(
                {
                    "nodeId": "coordinator",
                    "nodeVersion": VERSION,
                    "state": self.state,
                    "coordinator": True,
                    "memoryInfo": {
                        "totalNodeMemory": pool.capacity,
                        "reservedBytes": pool.reserved,
                        "freeBytes": pool.free_bytes,
                    },
                    "queries": len(self.query_manager.queries()),
                    # system.runtime.queries-style admission breakdown
                    # (the knee is visible without running the bench)
                    "queryCounts": self.query_manager.state_counts(),
                    "resourceGroups": self.resource_groups.summary(),
                }
            ))
        if path in ("/ui", "/ui/", "/"):
            from trino_tpu.server.webui import PAGE

            return responder.respond(Response(
                200, PAGE.encode(), "text/html; charset=utf-8"
            ))
        if path == "/v1/resourceGroup":
            return responder.respond(
                json_response(self.resource_groups.info())
            )
        if path == "/v1/task":
            return responder.respond(json_response(
                [t.info() for t in self.task_manager.tasks()]
            ))
        if len(parts) == 3 and parts[:2] == ["v1", "task"]:
            # task status, optional long-poll (?maxWait=seconds) — a loop
            # timer re-checks instead of parking a thread
            task = self.task_manager.get(parts[2])
            if task is None:
                return responder.respond(
                    json_response({"error": "task not found"}, 404)
                )
            max_wait = parse_max_wait(qs.get("maxWait", ["0"])[0], default=0.0)
            deadline = time.monotonic() + max_wait
            return self._task_status_poll(responder, task, deadline)
        if (
            len(parts) == 6
            and parts[:2] == ["v1", "task"]
            and parts[3] == "results"
        ):
            # GET /v1/task/{id}/results/{partition}/{token}[?maxWait=s]
            # (TaskResource.java:261 paged binary fetch)
            task = self.task_manager.get(parts[2])
            if task is None:
                return responder.respond(
                    json_response({"error": "task not found"}, 404)
                )
            max_wait = parse_max_wait(
                qs.get("maxWait", ["1.0"])[0], default=1.0
            )
            deadline = time.monotonic() + max_wait
            return self._task_results_poll(
                responder, task, int(parts[4]), int(parts[5]), deadline
            )
        if (
            len(parts) == 6
            and parts[:2] == ["v1", "spool"]
            and parts[3] == "results"
        ):
            # GET /v1/spool/{taskId}/results/{partition}/{token} — the
            # exact task-results wire shape, so ExchangeClient pulls a
            # spool URI exactly like a live worker's buffer
            def read_spool() -> Response:
                store = getattr(self.engine, "spool_store", None)
                out = (
                    store.read(parts[2], int(parts[4]), int(parts[5]))
                    if store is not None
                    else None
                )
                if out is None:
                    return json_response(
                        {"error": "spooled task not found"}, 404
                    )
                return json_response(out)

            return self._offload(responder, read_spool, ceiling=False)
        if path == "/v1/spool":
            store = getattr(self.engine, "spool_store", None)
            return responder.respond(json_response(
                store.stats() if store is not None else {}
            ))
        if path == "/v1/node":
            if self.node_manager is None:
                return responder.respond(json_response([]))
            return responder.respond(json_response(
                {
                    "nodes": [
                        n.to_json() for n in self.node_manager.all_nodes()
                    ],
                    "failureInfo": (
                        self.node_manager.failure_detector.info()
                    ),
                }
            ))
        if path == "/v1/metrics":
            # Prometheus text scrape (text format 0.0.4); ?format=json
            # returns the structured snapshot for bench/chaos embeds
            from trino_tpu.obs.metrics import get_registry

            if qs.get("format", [""])[0] == "json":
                return responder.respond(
                    json_response(get_registry().snapshot())
                )
            return responder.respond(Response(
                200,
                get_registry().render_prometheus().encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            ))
        if path == "/v1/history":
            # per-fingerprint observed execution truth (obs/history.py):
            # one entry per store the engine resolved, most-recently-
            # used fingerprints first
            snap_fn = getattr(self.engine, "history_snapshot", None)
            return responder.respond(json_response(
                snap_fn() if callable(snap_fn) else {"stores": []}
            ))
        if path == "/v1/cache":
            # semantic result cache snapshot (trino_tpu/cache): entries,
            # byte budget, hit/miss/eviction/maintenance counters. Brief
            # lock only — same loop-thread discipline as /v1/metrics.
            rc = getattr(self.engine, "result_cache", None)
            return responder.respond(json_response(
                rc.snapshot() if rc is not None else {"entries": []}
            ))
        if path == "/v1/slo":
            # SLO regression sentinel (obs/slo.py): currently-regressed
            # fingerprints with magnitudes + process counters. Brief lock
            # only — same loop-thread discipline as /v1/metrics.
            from trino_tpu.obs.slo import get_sentinel

            return responder.respond(json_response(get_sentinel().snapshot()))
        if path == "/v1/query":
            return responder.respond(json_response(
                [q.info() for q in self.query_manager.queries()]
            ))
        if (
            len(parts) == 4
            and parts[:2] == ["v1", "query"]
            and parts[3] == "timeline"
        ):
            # span dump for one trace (= query id). Workers hold spans
            # for queries they never registered, so 404 only when the
            # id is unknown to BOTH the query manager and the sink.
            spans = self.span_sink.spans_for(parts[2])
            if not spans and self.query_manager.get(parts[2]) is None:
                return responder.respond(
                    json_response({"error": "query not found"}, 404)
                )
            return responder.respond(
                json_response({"queryId": parts[2], "spans": spans})
            )
        if (
            len(parts) == 4
            and parts[:2] == ["v1", "query"]
            and parts[3] == "flight"
        ):
            # flight-journal replay for one query (obs/flight.py). A
            # restarted coordinator serves the pre-crash journal via
            # ?dir= (its in-memory query registry is gone, so no 404
            # gating on the query manager). Replay flushes + reads
            # files — offloaded off the loop thread.
            qid = parts[2]
            directory = qs.get("dir", [""])[0]

            def read_flight() -> Response:
                from trino_tpu.obs import flight as flight_mod

                events = flight_mod.replay_known(qid, directory or None)
                if not events and self.query_manager.get(qid) is None:
                    return json_response(
                        {"error": "no flight records for query"}, 404
                    )
                return json_response({"queryId": qid, "events": events})

            return self._offload(responder, read_flight, ceiling=False)
        if len(parts) == 3 and parts[:2] == ["v1", "query"]:
            q = self.query_manager.get(parts[2])
            if q is None:
                return responder.respond(
                    json_response({"error": "query not found"}, 404)
                )
            return responder.respond(json_response(q.info()))
        if len(parts) == 6 and parts[:2] == ["v1", "statement"]:
            phase, qid, slug, token = parts[2], parts[3], parts[4], parts[5]
            q = self.query_manager.get(qid)
            if q is None or q.slug != slug:
                return responder.respond(
                    json_response({"error": "query not found"}, 404)
                )
            return self._statement_poll(
                request, responder, q, phase, int(token)
            )
        responder.respond(json_response({"error": f"unknown path: {path}"}, 404))

    # --- DELETE -----------------------------------------------------------

    def _route_delete(self, request, responder, path, parts, qs) -> None:
        if len(parts) >= 5 and parts[:2] == ["v1", "statement"]:
            qid, slug = parts[3], parts[4]
            q = self.query_manager.get(qid)
            if q is None or q.slug != slug:  # slug = per-query secret
                return responder.respond(
                    json_response({"error": "query not found"}, 404)
                )
            q.cancel()
            return responder.respond(Response(204))
        if len(parts) == 3 and parts[:2] == ["v1", "query"]:
            if self.query_manager.cancel(parts[2]):
                return responder.respond(Response(204))
            return responder.respond(
                json_response({"error": "query not found"}, 404)
            )
        if len(parts) == 3 and parts[:2] == ["v1", "task"]:
            # ?speculative=true marks a hedged-attempt loser: the state
            # machine records CANCELED_SPECULATIVE instead of CANCELED
            speculative = qs.get("speculative", [""])[0] == "true"
            if self.task_manager.cancel(parts[2], speculative=speculative):
                return responder.respond(Response(204))
            return responder.respond(
                json_response({"error": "task not found"}, 404)
            )
        if len(parts) == 3 and parts[:2] == ["v1", "spool"]:
            # aborted spool write / cancelled attempt: drop its pages
            store = getattr(self.engine, "spool_store", None)
            if store is not None:
                store.delete_task(parts[2])
            return responder.respond(Response(204))
        if len(parts) == 3 and parts[:2] == ["v1", "announce"]:
            # worker decommission: deregister from discovery AND the
            # failure detector (a drained node must not be pinged or
            # counted failed afterwards)
            if self.node_manager is None:
                return responder.respond(
                    json_response({"error": "not a coordinator"}, 400)
                )
            self.node_manager.decommission(parts[2])
            return responder.respond(Response(204))
        responder.respond(json_response({"error": f"unknown path: {path}"}, 404))

    # --- PUT --------------------------------------------------------------

    def _route_put(self, request, responder, path, parts, qs) -> None:
        if path == "/v1/discovery":
            # late discovery injection (SPMD boot: the coordinator's
            # HTTP port is unknown until every rank joins the mesh)
            body = json.loads(request.body.decode())
            self.discovery_uri = body["uri"]
            return responder.respond(json_response({"ok": True}))
        if path == "/v1/announce":
            # embedded discovery: workers announce themselves
            if self.node_manager is None:
                return responder.respond(
                    json_response({"error": "not a coordinator"}, 400)
                )
            body = json.loads(request.body.decode())
            self.node_manager.announce(body["nodeId"], body["uri"])
            if self.cluster_memory_manager is not None:
                self.cluster_memory_manager.update(
                    body["nodeId"], body.get("memoryInfo")
                )
            return responder.respond(json_response({"ok": True}))
        if path == "/v1/info/state":
            body = request.body.decode().strip().strip('"')
            if body == "SHUTTING_DOWN":
                self.graceful_shutdown()
                return responder.respond(json_response({}, 200))
            return responder.respond(
                json_response({"error": f"unsupported state: {body}"}, 400)
            )
        if (
            len(parts) == 4
            and parts[:2] == ["v1", "spool"]
            and parts[3] == "complete"
        ):
            # spool completion manifest: {queryId, partitions: {p: n}}
            def complete() -> Response:
                from trino_tpu.exchange.spool import get_spool_store

                body = json.loads(request.body.decode())
                store = get_spool_store(self.engine)
                ok = store.complete(
                    parts[2],
                    body.get("queryId", ""),
                    {
                        int(p): int(n)
                        for p, n in body.get("partitions", {}).items()
                    },
                )
                return json_response({"complete": ok})

            return self._offload(responder, complete, ceiling=False)
        responder.respond(json_response({"error": f"unknown path: {path}"}, 404))

    # --- long-polls (loop-driven, no parked threads) ----------------------

    def _statement_poll(
        self,
        request: Request,
        responder: Responder,
        q: ManagedQuery,
        phase: str,
        token: int,
    ) -> None:
        """Statement nextUri GET: park the responder on the query's state
        machine. A state transition satisfying the phase predicate (or
        the maxWait timer) responds; no thread waits anywhere."""
        q.touch()
        max_wait = parse_max_wait(
            _parse_duration(
                request.headers.get(f"{PROTOCOL_HEADER}-Max-Wait", "1s")
                or "1s"
            ),
            default=1.0,
        )
        if phase == "queued":
            def pred(s) -> bool:
                return s not in (QueryState.QUEUED, QueryState.PLANNING)
        else:
            def pred(s) -> bool:
                return q.result is not None or s in TERMINAL_QUERY_STATES

        loop = self.httpd.loop

        def finish() -> None:
            # one-shot via responder; both the listener and the timer may
            # race here — remove/cancel are idempotent
            timer.cancel()
            q.state.remove_listener(listener)
            if responder.done:
                return
            q.touch()
            try:
                out = self.query_results(q, phase, token)
            except Exception as e:  # noqa: BLE001
                responder.respond(
                    json_response({"error": f"internal error: {e}"}, 500)
                )
                return
            responder.respond(_statement_response(out))

        timer = loop.call_later(max_wait, finish)

        def listener(s) -> None:
            if pred(s):
                loop.call_soon(finish)

        q.state.add_listener(listener)

    def _task_status_poll(self, responder, task, deadline: float) -> None:
        if responder.done or not responder.connected:
            return
        if task.state != "RUNNING" or time.monotonic() >= deadline:
            return responder.respond(json_response(task.info()))
        self.httpd.loop.call_later(
            0.02, self._task_status_poll, responder, task, deadline
        )

    def _task_results_poll(
        self, responder, task, partition: int, token: int, deadline: float
    ) -> None:
        if responder.done or not responder.connected:
            return
        # max_wait=0 makes the buffer read non-blocking: pages below the
        # token are acked, available pages return immediately
        out = task.results(partition, token, max_wait=0.0)
        if (
            out.get("pages")
            or out.get("complete")
            or out.get("failed")
            or time.monotonic() >= deadline
        ):
            return responder.respond(json_response(out))
        self.httpd.loop.call_later(
            _TASK_POLL_S,
            self._task_results_poll,
            responder, task, partition, token, deadline,
        )


def _statement_response(out: dict) -> Response:
    """Pop the session-mutation fields into their response headers."""
    headers: dict[str, str] = {}
    set_session = out.pop("_setSession", None)
    if set_session:
        for k, v in set_session.items():
            headers[f"{PROTOCOL_HEADER}-Set-Session"] = (
                f"{k}={urllib.parse.quote(str(v))}"
            )
    added = out.pop("_addedPrepare", None)
    if added:
        headers[f"{PROTOCOL_HEADER}-Added-Prepare"] = (
            f"{added[0]}={urllib.parse.quote(added[1])}"
        )
    dealloc = out.pop("_deallocatedPrepare", None)
    if dealloc:
        headers[f"{PROTOCOL_HEADER}-Deallocated-Prepare"] = dealloc
    started = out.pop("_startedTransaction", None)
    if started:
        headers[f"{PROTOCOL_HEADER}-Started-Transaction-Id"] = started
    if out.pop("_clearedTransaction", None):
        headers[f"{PROTOCOL_HEADER}-Clear-Transaction-Id"] = "true"
    return json_response(out, headers=headers)


def _raw_type(ty: T.SqlType) -> str:
    s = str(ty)
    return s.split("(")[0]


def _session_from_headers(engine: Engine, h) -> Session:
    s = Session(
        user=h.get(f"{PROTOCOL_HEADER}-User", "anonymous"),
        catalog=h.get(f"{PROTOCOL_HEADER}-Catalog", "tpch"),
        schema=h.get(f"{PROTOCOL_HEADER}-Schema", "tiny"),
        source=h.get(f"{PROTOCOL_HEADER}-Source", ""),
    )
    raw = h.get(f"{PROTOCOL_HEADER}-Session", "") or ""
    for part in raw.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        k, v = part.split("=", 1)
        s.set(k.strip(), _decode_session_value(urllib.parse.unquote(v.strip())))
    txn = h.get(f"{PROTOCOL_HEADER}-Transaction-Id", "") or ""
    if txn and txn.upper() != "NONE":
        # Validate against the TransactionManager: a bogus id would
        # make write paths skip the single-writer lock (reference
        # errors on unknown transaction ids).
        engine.transaction_manager.get(txn)  # raises if unknown
        s.properties["__txn"] = txn
    # prepared statements ride headers (the protocol is stateless):
    # X-Trino-Prepared-Statement: name=<urlencoded sql>[,name=...]
    raw = h.get(f"{PROTOCOL_HEADER}-Prepared-Statement", "") or ""
    for part in raw.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        k, v = part.split("=", 1)
        s.prepared[k.strip().lower()] = urllib.parse.unquote(v.strip())
    return s


def _decode_session_value(v: str) -> Any:
    low = v.lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        pass
    return v


def _parse_duration(text: str) -> float:
    text = text.strip().lower()
    for suffix, mult in (("ms", 0.001), ("s", 1.0), ("m", 60.0)):
        if text.endswith(suffix):
            try:
                return float(text[: -len(suffix)]) * mult
            except ValueError:
                return 1.0
    try:
        return float(text)
    except ValueError:
        return 1.0
