"""Hierarchical resource groups: admission control and query queueing.

Reference: ``core/trino-main/.../execution/resourcegroups/`` —
``InternalResourceGroup.java`` (hierarchy, hard concurrency + queue caps,
fair/weighted-fair/fifo scheduling), ``InternalResourceGroupManager``,
selector-based group resolution and the file-based configuration format of
``plugin/trino-resource-group-managers``
(``resource_groups.json``: rootGroups + selectors).

Queries queue *before* execution (dispatcher tier, L7). Two admission
styles share one waiter queue:

- ``admit()`` — legacy blocking call: parks the calling thread on an
  Event until a slot frees (DispatchManager → ResourceGroupManager.submit
  with a thread per query).
- ``submit(user, source, ready)`` — event-driven: returns immediately
  with ``(group, admitted_now)``; when queued, the ``ready`` callback
  fires later — outside the manager lock — once a slot frees (or with a
  QueryQueueFullError when the queue wait expires). No thread is parked
  while a query waits, so thousands of queued queries cost thousands of
  waiter objects, not thousands of stacks.
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Optional


class QueryQueueFullError(Exception):
    """Reference error code QUERY_QUEUE_FULL."""


class _Waiter:
    """One queued admission. Either a blocking Event (``admit()``) or an
    event-driven callback (``submit()``). Queue membership and the
    ``admitted`` flag are guarded by the manager lock; callbacks are
    always invoked OUTSIDE it."""

    __slots__ = ("group", "enq_mono", "deadline", "event", "callback",
                 "admitted", "peak_hbm_hint")

    def __init__(
        self,
        group: "ResourceGroup",
        enq_mono: float,
        deadline: float,
        event: Optional[threading.Event] = None,
        callback: Optional[
            Callable[["ResourceGroup", Optional[Exception]], None]
        ] = None,
        peak_hbm_hint: int = 0,
    ):
        self.group = group
        self.enq_mono = enq_mono  # monotonic: queue-wait SLO accounting
        self.deadline = deadline  # monotonic absolute expiry
        self.event = event
        self.callback = callback
        self.admitted = False
        # observed peak-HBM bytes from the query-history store (0 =
        # unknown): a waiter whose programs won't fit CURRENT device
        # headroom is skipped over — not head-of-line blocking — until
        # memory frees or its queue wait expires
        self.peak_hbm_hint = peak_hbm_hint


@dataclasses.dataclass
class GroupConfig:
    name: str
    max_queued: int = 100
    hard_concurrency_limit: int = 10
    scheduling_weight: int = 1
    scheduling_policy: str = "fair"  # fair | weighted_fair | fifo
    subgroups: list["GroupConfig"] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Selector:
    """Maps (user, source) to a group path. ``${USER}`` expands."""

    group: str
    user_pattern: Optional[str] = None
    source_pattern: Optional[str] = None

    def matches(self, user: str, source: str) -> bool:
        if self.user_pattern and not re.fullmatch(self.user_pattern, user or ""):
            return False
        if self.source_pattern and not re.fullmatch(self.source_pattern, source or ""):
            return False
        return True

    def resolve(self, user: str) -> str:
        return self.group.replace("${USER}", user or "unknown")


class ResourceGroup:
    """One node of the hierarchy. Thread-safe via the manager's lock."""

    def __init__(
        self,
        config: GroupConfig,
        parent: Optional["ResourceGroup"],
        lock,
        dynamic: bool = False,
    ):
        self.config = config
        self.parent = parent
        self._lock = lock
        self.dynamic = dynamic  # ${USER}-template subgroup: evicted when idle
        self.running = 0
        self.queue: deque = deque()  # waiting admissions (_Waiter)
        self.children: dict[str, ResourceGroup] = {}
        for sub in config.subgroups:
            self.children[sub.name] = ResourceGroup(sub, self, lock)
        self.total_admitted = 0
        self.total_queued_time = 0.0

    @property
    def full_name(self) -> str:
        if self.parent is None:
            return self.config.name
        return f"{self.parent.full_name}.{self.config.name}"

    def _can_run_locked(self) -> bool:
        g: Optional[ResourceGroup] = self
        while g is not None:
            if g.running >= g.config.hard_concurrency_limit:
                return False
            g = g.parent
        return True

    def _start_locked(self) -> None:
        g: Optional[ResourceGroup] = self
        while g is not None:
            g.running += 1
            g = g.parent
        self.total_admitted += 1

    def _finish_locked(self) -> None:
        g: Optional[ResourceGroup] = self
        while g is not None:
            g.running = max(0, g.running - 1)
            g = g.parent

    def _queued_count_locked(self) -> int:
        n = len(self.queue)
        for c in self.children.values():
            n += c._queued_count_locked()
        return n

    def info(self) -> dict:
        return {
            "id": self.full_name,
            "state": "FULL" if self.running >= self.config.hard_concurrency_limit else "CAN_RUN",
            "runningQueries": self.running,
            "queuedQueries": len(self.queue),
            "hardConcurrencyLimit": self.config.hard_concurrency_limit,
            "maxQueued": self.config.max_queued,
            "schedulingPolicy": self.config.scheduling_policy,
            "totalAdmitted": self.total_admitted,
            "totalQueuedTimeMs": int(self.total_queued_time * 1000),
            "subGroups": [c.info() for c in self.children.values()],
        }


class ResourceGroupManager:
    """Selector resolution + blocking admission (InternalResourceGroupManager).

    ``configure(root_groups, selectors)`` mirrors resource_groups.json.
    Without configuration, a permissive default group applies.
    """

    def __init__(self, max_wait_seconds: float = 60.0):
        self._lock = threading.Lock()
        self.roots: dict[str, ResourceGroup] = {}
        self.selectors: list[Selector] = []
        self.max_wait_seconds = max_wait_seconds
        # Deterministic expiry: a one-shot daemon timer armed for the
        # earliest callback-waiter deadline, so queue-timeout rejection
        # fires on time even when no other query finishes.
        self._reap_timer: Optional[threading.Timer] = None
        self._reap_at = float("inf")
        self.configure(
            [GroupConfig("global", max_queued=1000, hard_concurrency_limit=100)],
            [Selector(group="global")],
        )

    def configure(self, root_groups: list[GroupConfig], selectors: list[Selector]):
        with self._lock:
            self.roots = {
                g.name: ResourceGroup(g, None, self._lock) for g in root_groups
            }
            self.selectors = list(selectors)

    @classmethod
    def from_config(cls, config: dict) -> "ResourceGroupManager":
        """Build from the JSON shape of resource_groups.json."""

        def group(d: dict) -> GroupConfig:
            return GroupConfig(
                name=d["name"],
                max_queued=d.get("maxQueued", 100),
                hard_concurrency_limit=d.get("hardConcurrencyLimit", 10),
                scheduling_weight=d.get("schedulingWeight", 1),
                scheduling_policy=d.get("schedulingPolicy", "fair"),
                subgroups=[group(s) for s in d.get("subGroups", [])],
            )

        mgr = cls()
        mgr.configure(
            [group(g) for g in config.get("rootGroups", [])],
            [
                Selector(
                    group=s["group"],
                    user_pattern=s.get("user"),
                    source_pattern=s.get("source"),
                )
                for s in config.get("selectors", [])
            ],
        )
        return mgr

    # --- resolution -------------------------------------------------------

    def _resolve(self, user: str, source: str) -> ResourceGroup:
        for sel in self.selectors:
            if sel.matches(user, source):
                path = sel.resolve(user).split(".")
                with self._lock:
                    g = self.roots.get(path[0])
                    if g is None:
                        continue
                    for part in path[1:]:
                        if part not in g.children:
                            # dynamic per-user subgroup (template expansion)
                            g.children[part] = ResourceGroup(
                                GroupConfig(
                                    part,
                                    max_queued=g.config.max_queued,
                                    hard_concurrency_limit=g.config.hard_concurrency_limit,
                                ),
                                g,
                                self._lock,
                                dynamic=True,
                            )
                        g = g.children[part]
                    return g
        raise QueryQueueFullError("no resource group matches this query")

    # --- admission --------------------------------------------------------

    def admit(self, user: str, source: str = "") -> ResourceGroup:
        """Blocks until a slot is available. Raises when the queue is full
        or the wait times out. (Thread-parking path; ``submit()`` is the
        event-driven equivalent.)"""
        from trino_tpu.server.eventloop import assert_not_loop_thread

        assert_not_loop_thread("ResourceGroupManager.admit")
        group = self._resolve(user, source)
        now = time.monotonic()
        with self._lock:
            if group._can_run_locked() and not group.queue:
                group._start_locked()
                self._publish_locked()
                return group
            if len(group.queue) >= group.config.max_queued:
                raise QueryQueueFullError(
                    f"Too many queued queries for '{group.full_name}'"
                )
            waiter = _Waiter(
                group, now, now + self.max_wait_seconds,
                event=threading.Event(),
            )
            group.queue.append(waiter)
            self._publish_locked()
        if not waiter.event.wait(self.max_wait_seconds):
            with self._lock:
                if waiter.admitted:
                    return group  # admitted concurrently with the timeout
                group.queue.remove(waiter)
                self._publish_locked()
            raise QueryQueueFullError(
                f"Query exceeded maximum queue wait for '{group.full_name}'"
            )
        return group

    def submit(
        self,
        user: str,
        source: str = "",
        ready: Optional[
            Callable[[ResourceGroup, Optional[Exception]], None]
        ] = None,
        peak_hbm_hint: int = 0,
    ) -> tuple[ResourceGroup, bool]:
        """Event-driven admission: never parks the calling thread.

        Returns ``(group, True)`` when a slot was free, else enqueues a
        callback waiter and returns ``(group, False)``;
        ``ready(group, None)`` fires once a slot frees, or
        ``ready(group, QueryQueueFullError)`` when the queue wait
        expires. Callbacks run outside the manager lock (on whichever
        thread released the slot). Raises immediately when the queue is
        full or no selector matches. ``peak_hbm_hint`` (observed bytes
        from the query-history store) additionally gates admission on
        live device headroom: a query known to need more HBM than is
        currently free queues instead of failing at compile.
        """
        group = self._resolve(user, source)
        now = time.monotonic()
        timed_out: list[_Waiter] = []
        err: Optional[QueryQueueFullError] = None
        admitted = False
        with self._lock:
            self._collect_expired_locked(timed_out)
            if (
                group._can_run_locked()
                and not group.queue
                and self._hbm_fits(peak_hbm_hint)
            ):
                group._start_locked()
                admitted = True
            elif len(group.queue) >= group.config.max_queued:
                err = QueryQueueFullError(
                    f"Too many queued queries for '{group.full_name}'"
                )
            else:
                group.queue.append(_Waiter(
                    group, now, now + self.max_wait_seconds, callback=ready,
                    peak_hbm_hint=peak_hbm_hint,
                ))
                self._schedule_reap_locked()
            self._publish_locked()
        self._fire_timeouts(timed_out)
        if err is not None:
            raise err
        return group, admitted

    def finish(self, group: ResourceGroup) -> None:
        fired: list[_Waiter] = []
        timed_out: list[_Waiter] = []
        with self._lock:
            group._finish_locked()
            self._collect_expired_locked(timed_out)
            self._wake_next_locked(group, fired)
            self._evict_idle_dynamic_locked(group)
            self._publish_locked()
        for w in fired:
            try:
                w.callback(w.group, None)
            except Exception:  # noqa: BLE001 — a bad callback must not
                pass  # strand other finishers
        self._fire_timeouts(timed_out)

    def abandon(
        self,
        group: ResourceGroup,
        callback: Callable[[ResourceGroup, Optional[Exception]], None],
    ) -> bool:
        """Remove a not-yet-admitted callback waiter (client abandoned the
        query before it got a slot). Returns True if a waiter was removed;
        False means it was already admitted, expired, or never queued."""
        with self._lock:
            for w in list(group.queue):
                if w.callback is callback and not w.admitted:
                    group.queue.remove(w)
                    self._publish_locked()
                    return True
        return False

    def _schedule_reap_locked(self) -> None:
        """Arm (or re-arm) the expiry timer for the earliest callback-waiter
        deadline. Caller holds the manager lock."""
        earliest = float("inf")

        def walk(g: ResourceGroup) -> None:
            nonlocal earliest
            for w in g.queue:
                if w.callback is not None and w.deadline < earliest:
                    earliest = w.deadline
            for c in g.children.values():
                walk(c)

        for root in self.roots.values():
            walk(root)
        if earliest == float("inf"):
            return
        if self._reap_timer is not None and self._reap_at <= earliest + 1e-3:
            return  # already armed early enough
        if self._reap_timer is not None:
            self._reap_timer.cancel()
        delay = max(0.0, earliest - time.monotonic()) + 0.005
        timer = threading.Timer(delay, self._reap_now)
        timer.daemon = True
        timer.start()
        self._reap_timer = timer
        self._reap_at = earliest

    def _reap_now(self) -> None:
        timed_out: list[_Waiter] = []
        with self._lock:
            self._reap_timer = None
            self._reap_at = float("inf")
            self._collect_expired_locked(timed_out)
            self._schedule_reap_locked()
            self._publish_locked()
        self._fire_timeouts(timed_out)

    def _collect_expired_locked(self, out: list) -> None:
        """Remove callback waiters whose deadline passed. The armed reap
        timer (``_schedule_reap_locked``) makes expiry deterministic;
        submit/finish activity still reaps opportunistically so a stale
        timer is never load-bearing. Event waiters time themselves out —
        their parked thread owns removal."""
        now = time.monotonic()

        def walk(g: ResourceGroup) -> None:
            for w in [w for w in g.queue
                      if w.callback is not None and now > w.deadline]:
                g.queue.remove(w)
                out.append(w)
            for c in list(g.children.values()):
                walk(c)

        for root in self.roots.values():
            walk(root)

    def _fire_timeouts(self, waiters: list) -> None:
        for w in waiters:
            try:
                w.callback(w.group, QueryQueueFullError(
                    "Query exceeded maximum queue wait for "
                    f"'{w.group.full_name}'"
                ))
            except Exception:  # noqa: BLE001
                pass

    # --- observability ----------------------------------------------------

    def _publish_locked(self) -> None:
        """Queue-depth and running gauges per group on /v1/metrics."""
        from trino_tpu.obs.metrics import get_registry

        reg = get_registry()

        def walk(g: ResourceGroup) -> None:
            reg.gauge(
                "trino_tpu_resource_group_queued", group=g.full_name
            ).set(len(g.queue))
            reg.gauge(
                "trino_tpu_resource_group_running", group=g.full_name
            ).set(g.running)
            for c in g.children.values():
                walk(c)

        for root in self.roots.values():
            walk(root)

    def summary(self) -> dict:
        """Flat ``{group: {queuedQueries, runningQueries}}`` snapshot —
        the ``system.runtime.queries``-style admission breakdown."""
        out: dict[str, dict] = {}
        with self._lock:

            def walk(g: ResourceGroup) -> None:
                out[g.full_name] = {
                    "queuedQueries": len(g.queue),
                    "runningQueries": g.running,
                }
                for c in g.children.values():
                    walk(c)

            for root in self.roots.values():
                walk(root)
        return out

    def _evict_idle_dynamic_locked(self, group: ResourceGroup) -> None:
        """Drop idle ${USER}-template subgroups so distinct users don't
        grow the tree without bound (reference: disabled-group eviction)."""
        g: Optional[ResourceGroup] = group
        while g is not None and g.parent is not None:
            if g.dynamic and g.running == 0 and not g.queue and not g.children:
                g.parent.children.pop(g.config.name, None)
            g = g.parent

    def _wake_next_locked(
        self, group: ResourceGroup, fired: list
    ) -> None:
        """Wake queued queries anywhere in the hierarchy that can now run.
        fair/fifo: FIFO within a group; weighted_fair: highest
        weight/(running+1) subgroup first (WeightedFairQueue analog)."""
        self._wake_in_subtree_locked(self._root_of(group), fired)

    def _root_of(self, g: ResourceGroup) -> ResourceGroup:
        while g.parent is not None:
            g = g.parent
        return g

    def _wake_in_subtree_locked(
        self, g: ResourceGroup, fired: list
    ) -> None:
        while True:
            picked = self._pick_candidate_locked(g)
            if picked is None:
                return
            candidate, w = picked
            candidate.queue.remove(w)
            candidate._start_locked()
            w.admitted = True
            waited = time.monotonic() - w.enq_mono
            candidate.total_queued_time += waited
            self._observe_wait(candidate, waited)
            if w.event is not None:
                w.event.set()
            else:
                fired.append(w)  # callback: invoked by finish(), unlocked

    def _observe_wait(self, group: ResourceGroup, waited_s: float) -> None:
        from trino_tpu.obs.metrics import get_registry

        get_registry().histogram(
            "trino_tpu_resource_group_queue_wait_ms", group=group.full_name
        ).observe(waited_s * 1000.0)

    def _pick_candidate_locked(
        self, g: ResourceGroup
    ) -> Optional[tuple[ResourceGroup, _Waiter]]:
        """(group, waiter) next in line, honoring HBM hints: within a
        group, the first FIFO waiter whose observed peak-HBM fits current
        device headroom wins — an over-headroom waiter is skipped over
        (never head-of-line blocking) and retried on the next wake; if it
        never fits, the queue-wait expiry reaps it."""
        if not g._can_run_locked():
            return None
        for w in g.queue:
            if self._hbm_fits(w.peak_hbm_hint):
                return g, w
        kids = [c for c in g.children.values() if c._queued_count_locked() > 0]
        if not kids:
            return None
        if g.config.scheduling_policy == "weighted_fair":
            kids.sort(
                key=lambda c: -(c.config.scheduling_weight / (c.running + 1))
            )
        for c in kids:
            found = self._pick_candidate_locked(c)
            if found is not None:
                return found
        return None

    @staticmethod
    def _hbm_fits(peak_hbm_hint: int) -> bool:
        """Does a program with this observed peak footprint fit the
        device's CURRENT free HBM? Hint 0 (no history) and backends
        without memory accounting always admit."""
        if not peak_hbm_hint:
            return True
        try:
            from trino_tpu.ingest import hbm_headroom_ok

            return hbm_headroom_ok(0, peak_hbm_hint=int(peak_hbm_hint))
        except Exception:  # noqa: BLE001 — accounting must never wedge
            return True

    def info(self) -> list[dict]:
        with self._lock:
            return [g.info() for g in self.roots.values()]
