"""Coordinator cluster layer: discovery, remote tasks, stage scheduling.

Reference:
- ``metadata/DiscoveryNodeManager.java:68,148`` — worker membership via the
  embedded discovery service (workers announce; coordinator polls). Here
  workers PUT /v1/announce on the coordinator and re-announce periodically.
- ``failuredetector/HeartbeatFailureDetector.java:78,91-120`` — the
  existing detector (server/failuredetector.py) monitors announced workers;
  failed nodes are excluded from scheduling.
- ``server/remotetask/HttpRemoteTask.java:103,317`` — coordinator-side
  proxy of a worker task: POST TaskUpdateRequest, long-poll status.
- ``execution/scheduler/SqlQueryScheduler.java:112,538`` +
  ``SqlStageExecution.java:384`` — stage tree scheduling. Fragment task
  counts: SOURCE/HASH fragments get one task per live worker (splits
  round-robin, FIXED_HASH partitions by index), SINGLE fragments one task;
  the root fragment executes on the coordinator, pulling child output over
  the same HTTP exchange (``server/protocol/Query.java:117``).
"""

from __future__ import annotations

import itertools
import json
import threading
import time
import urllib.request
from typing import Optional

from trino_tpu.config import Session
from trino_tpu.exec.local import ExecutionError, Result
from trino_tpu.planner import plan as P
from trino_tpu.planner.fragmenter import (
    HASH,
    SINGLE,
    SOURCE,
    PlanFragment,
    SubPlan,
    fragment_plan,
)

_task_counter = itertools.count(1)


class WorkerNode:
    def __init__(self, node_id: str, uri: str):
        self.node_id = node_id
        self.uri = uri.rstrip("/")
        self.last_announce = time.time()

    def to_json(self) -> dict:
        return {
            "nodeId": self.node_id,
            "uri": self.uri,
            "lastAnnounceSecondsAgo": round(time.time() - self.last_announce, 3),
        }


class ClusterNodeManager:
    """Announce-based membership + failure-detector exclusion."""

    def __init__(self, announce_timeout: float = 30.0, ping_interval: float = 2.0):
        self._nodes: dict[str, WorkerNode] = {}
        self._lock = threading.Lock()
        self.announce_timeout = announce_timeout
        from trino_tpu.server.failuredetector import HeartbeatFailureDetector

        def ping(uri: str) -> bool:
            with urllib.request.urlopen(f"{uri}/v1/info", timeout=5) as r:
                return r.status == 200

        self.failure_detector = HeartbeatFailureDetector(
            ping, interval=ping_interval
        )
        self._started = False

    def announce(self, node_id: str, uri: str) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                self._nodes[node_id] = WorkerNode(node_id, uri)
                self.failure_detector.register(node_id, uri)
            else:
                node.last_announce = time.time()
                node.uri = uri.rstrip("/")
        if not self._started:
            self._started = True
            try:
                self.failure_detector.start()
            except Exception:  # pragma: no cover - detector is advisory
                pass

    def remove(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def all_nodes(self) -> list[WorkerNode]:
        with self._lock:
            return list(self._nodes.values())

    def active_nodes(self) -> list[WorkerNode]:
        """Announced recently AND not flagged by the failure detector
        (scheduling exclusion, reference NodeScheduler + failure detector)."""
        now = time.time()
        with self._lock:
            nodes = list(self._nodes.values())
        return [
            n
            for n in nodes
            if now - n.last_announce < self.announce_timeout
            and not self.failure_detector.is_failed(n.node_id)
        ]



class NodeScheduler:
    """Node-selection policy for task placement.

    Reference: ``execution/scheduler/NodeScheduler.java`` +
    ``UniformNodeSelector.java`` — tasks go to the least-loaded active
    nodes (tracked coordinator-side per in-flight task) rather than blind
    round-robin, so a straggling worker stops attracting new work.
    """

    def __init__(self, node_manager: "ClusterNodeManager"):
        self.node_manager = node_manager
        self._assigned: dict[str, int] = {}  # node_id -> in-flight tasks
        self._lock = threading.Lock()

    def select(self, nodes: list["WorkerNode"], count: int) -> list["WorkerNode"]:
        """Pick ``count`` placements (repeats allowed when count > nodes),
        each time choosing the node with the fewest in-flight tasks.

        Selection IS reservation: ``_assigned`` is bumped here, under the
        same lock, so two fragments scheduling concurrently see each
        other's placements instead of both dog-piling the least-loaded
        node. Callers release via :meth:`release` when the task finishes
        (or fails to start)."""
        out: list[WorkerNode] = []
        with self._lock:
            for _ in range(count):
                best = min(
                    nodes,
                    key=lambda n: (self._assigned.get(n.node_id, 0), n.node_id),
                )
                self._assigned[best.node_id] = (
                    self._assigned.get(best.node_id, 0) + 1
                )
                out.append(best)
        return out

    def acquire(self, node: "WorkerNode") -> None:
        with self._lock:
            self._assigned[node.node_id] = self._assigned.get(node.node_id, 0) + 1

    def release(self, node: "WorkerNode") -> None:
        with self._lock:
            v = self._assigned.get(node.node_id, 0) - 1
            if v <= 0:
                self._assigned.pop(node.node_id, None)
            else:
                self._assigned[node.node_id] = v


def phased_order(sub: "SubPlan") -> list["PlanFragment"]:
    """Fragment launch order under the phased policy.

    Reference: ``execution/scheduler/PhasedExecutionSchedule.java`` —
    producers launch before consumers (our baseline bottom-up already
    guarantees that), and among one join's feeding fragments the BUILD
    side (the join's right subtree) launches before the PROBE side, so
    probe tasks never sit on a worker waiting for a build that has not
    even been scheduled.
    """
    out: list[PlanFragment] = []

    def build_side_fragments(frag: PlanFragment) -> set[int]:
        """Fragment ids referenced from any join's right (build) subtree."""
        build: set[int] = set()

        def mark(node, in_build: bool):
            if isinstance(node, P.RemoteSource):
                if in_build:
                    build.add(node.fragment_id)
                return
            if isinstance(node, P.Join):
                mark(node.left, in_build)
                mark(node.right, True)
                return
            for s in node.sources:
                mark(s, in_build)

        mark(frag.root, False)
        return build

    def rec(sp: "SubPlan"):
        build_ids = build_side_fragments(sp.fragment)
        ordered = sorted(
            sp.children,
            key=lambda c: 0 if c.fragment.id in build_ids else 1,
        )
        for c in ordered:
            rec(c)
        out.append(sp.fragment)

    rec(sub)
    return out


class HttpRemoteTask:
    """Coordinator-side handle of one worker task."""

    def __init__(self, node: WorkerNode, task_id: str, payload: dict):
        self.node = node
        self.task_id = task_id
        self.payload = payload
        self.uri = f"{node.uri}/v1/task/{task_id}"

    def start(self) -> None:
        from trino_tpu.server import auth

        body = json.dumps(self.payload).encode()
        req = urllib.request.Request(
            self.uri, data=body, method="POST", headers=auth.headers()
        )
        req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=30) as r:
            json.loads(r.read().decode())

    def status(self, max_wait: float = 0.0) -> dict:
        from trino_tpu.server import auth

        uri = self.uri + (f"?maxWait={max_wait}" if max_wait else "")
        req = urllib.request.Request(uri, headers=auth.headers())
        with urllib.request.urlopen(req, timeout=max(30, max_wait + 10)) as r:
            return json.loads(r.read().decode())

    def cancel(self) -> None:
        from trino_tpu.server import auth

        req = urllib.request.Request(
            self.uri, method="DELETE", headers=auth.headers()
        )
        try:
            urllib.request.urlopen(req, timeout=10)
        except Exception:  # noqa: BLE001 - best-effort
            pass


class ClusterScheduler:
    """Schedules a fragmented plan over the worker set and gathers output.

    One scheduler per coordinator; one `execute` per query.
    """

    def __init__(self, engine, node_manager: ClusterNodeManager):
        self.engine = engine
        self.node_manager = node_manager
        self.node_scheduler = NodeScheduler(node_manager)

    def execute(self, plan: P.PlanNode, session: Session):
        """Returns (Batch, column_names)."""
        sub = fragment_plan(plan)
        nodes = self.node_manager.active_nodes()
        if not nodes:
            raise ExecutionError("no active workers in the cluster")
        n = len(nodes)
        query_id = f"cq{next(_task_counter)}"

        fragments = {f.id: f for f in sub.all_fragments()}
        # execution policy: all-at-once launches in simple bottom-up order;
        # phased launches join build sides before their probes
        # (AllAtOnceExecutionPolicy vs PhasedExecutionPolicy)
        if session.get("execution_policy") == "phased":
            order = phased_order(sub)
        else:
            order = self._bottom_up(sub)

        # task counts per fragment (root runs on the coordinator)
        task_counts: dict[int, int] = {}
        for frag in order:
            if frag.id == sub.fragment.id:
                task_counts[frag.id] = 0  # coordinator
            elif frag.partitioning.kind in (SOURCE, HASH):
                task_counts[frag.id] = n
            else:
                task_counts[frag.id] = 1

        consumer_of: dict[int, int] = {}
        for frag in order:
            for fid in frag.source_fragment_ids:
                consumer_of[fid] = frag.id

        remote_tasks: dict[int, list[HttpRemoteTask]] = {}
        session_json = {
            "user": session.user,
            "catalog": session.catalog,
            "schema": session.schema,
            "properties": {
                k: v
                for k, v in session.properties.items()
                if isinstance(v, (str, int, float, bool))
                and k not in ("execution_mode",)
            },
        }
        try:
            for frag in order:
                if frag.id == sub.fragment.id:
                    continue
                remote_tasks[frag.id] = self._schedule_fragment(
                    query_id,
                    frag,
                    nodes,
                    task_counts,
                    consumer_of,
                    remote_tasks,
                    session_json,
                    fragments,
                )
            return self._execute_root(
                sub.fragment, session, remote_tasks, task_counts
            )
        except Exception:
            for tasks in remote_tasks.values():
                for t in tasks:
                    t.cancel()
            raise
        finally:
            for tasks in remote_tasks.values():
                for t in tasks:
                    self.node_scheduler.release(t.node)

    # --- stage scheduling -------------------------------------------------

    def _bottom_up(self, sub: SubPlan) -> list[PlanFragment]:
        out: list[PlanFragment] = []

        def rec(sp: SubPlan):
            for c in sp.children:
                rec(c)
            out.append(sp.fragment)

        rec(sub)
        return out

    def _sources_payload(
        self,
        frag: PlanFragment,
        partition: int,
        remote_tasks: dict[int, list[HttpRemoteTask]],
        fragments: dict[int, PlanFragment],
    ) -> dict:
        sources = {}
        for fid in frag.source_fragment_ids:
            tasks = remote_tasks[fid]
            producer = fragments.get(fid)
            entry = {
                "locations": [t.uri for t in tasks],
                "partition": partition,
            }
            if producer is not None and producer.output_exchange == "hash":
                # workers re-partition hash-exchanged rows over their local
                # devices; ship the partition keys and the wire column order
                entry["keys"] = [s.name for s in producer.output_keys]
                entry["symbols"] = [
                    s.name for s in producer.root.output_symbols
                ]
            sources[str(fid)] = entry
        return sources

    def _schedule_fragment(
        self,
        query_id: str,
        frag: PlanFragment,
        nodes: list[WorkerNode],
        task_counts: dict[int, int],
        consumer_of: dict[int, int],
        remote_tasks: dict[int, list[HttpRemoteTask]],
        session_json: dict,
        fragments: dict[int, PlanFragment],
    ) -> list[HttpRemoteTask]:
        from trino_tpu.planner.serde import fragment_to_json

        n_tasks = task_counts[frag.id]
        consumer = consumer_of.get(frag.id)
        output_partitions = max(
            1, task_counts.get(consumer, 1) if consumer is not None else 1
        )
        # split assignment for SOURCE fragments (enumerated on the
        # coordinator during scheduling, reference SplitManager timing)
        split_assignment: list[dict[str, list[dict]]] = [
            {} for _ in range(max(n_tasks, 1))
        ]
        if frag.partitioning.kind == SOURCE:
            for node in P.walk_plan(frag.root):
                if isinstance(node, P.TableScan):
                    connector = self.engine.catalogs.get(node.catalog)
                    splits = connector.get_splits(
                        node.schema,
                        node.table,
                        target_splits=max(n_tasks, 1) * 4,
                        constraint=node.constraint,
                    )
                    key = f"{node.catalog}.{node.schema}.{node.table}"
                    for i, s in enumerate(splits):
                        split_assignment[i % max(n_tasks, 1)].setdefault(
                            key, []
                        ).append(
                            {
                                "table": s.table,
                                "index": s.index,
                                "total": s.total,
                                "info": s.info,
                            }
                        )
        frag_json = fragment_to_json(frag)
        tasks: list[HttpRemoteTask] = []
        placements = self.node_scheduler.select(nodes, n_tasks)
        try:
            for p in range(n_tasks):
                payload = {
                    "session": session_json,
                    "fragment": frag_json,
                    "splits": split_assignment[p],
                    "sources": self._sources_payload(
                        frag, p, remote_tasks, fragments
                    ),
                    "output_partitions": output_partitions,
                }
                task = HttpRemoteTask(
                    placements[p], f"{query_id}.{frag.id}.{p}", payload
                )
                task.start()  # select() already reserved the slot
                tasks.append(task)
        except Exception:
            # a mid-fragment failure leaves these tasks outside
            # remote_tasks, so the query-level release never sees them:
            # cancel started tasks and release EVERY reserved placement
            # (started or not) to keep the load counters honest
            for t in tasks:
                t.cancel()
            for node in placements:
                self.node_scheduler.release(node)
            raise
        return tasks

    # --- root fragment on the coordinator --------------------------------

    def _execute_root(
        self,
        frag: PlanFragment,
        session: Session,
        remote_tasks: dict[int, list[HttpRemoteTask]],
        task_counts: dict[int, int],
    ):
        from trino_tpu.server.task import WorkerExecutor

        sources = {
            fid: {"locations": [t.uri for t in tasks], "partition": 0}
            for fid, tasks in remote_tasks.items()
            if fid in frag.source_fragment_ids
        }
        local_session = Session(
            user=session.user, catalog=session.catalog, schema=session.schema
        )
        for k, v in session.properties.items():
            if k != "execution_mode":
                local_session.properties[k] = v
        executor = WorkerExecutor(self.engine.catalogs, local_session, {}, sources)
        root = frag.root
        if isinstance(root, P.Output):
            batch, names = executor.execute(root)
        else:
            res = executor._exec(root)
            batch = res.batch.compact()
            names = [s.name for s in root.output_symbols]
        # surface any worker failure even if results looked complete
        for tasks in remote_tasks.values():
            for t in tasks:
                st = t.status()
                if st.get("state") == "FAILED":
                    raise ExecutionError(
                        f"task {st.get('taskId')} failed: {st.get('error')}"
                    )
        return batch, names
