"""Coordinator cluster layer: discovery, remote tasks, stage scheduling.

Reference:
- ``metadata/DiscoveryNodeManager.java:68,148`` — worker membership via the
  embedded discovery service (workers announce; coordinator polls). Here
  workers PUT /v1/announce on the coordinator and re-announce periodically.
- ``failuredetector/HeartbeatFailureDetector.java:78,91-120`` — the
  existing detector (server/failuredetector.py) monitors announced workers;
  failed nodes are excluded from scheduling.
- ``server/remotetask/HttpRemoteTask.java:103,317`` — coordinator-side
  proxy of a worker task: POST TaskUpdateRequest, long-poll status.
- ``execution/scheduler/SqlQueryScheduler.java:112,538`` +
  ``SqlStageExecution.java:384`` — stage tree scheduling. Fragment task
  counts: SOURCE/HASH fragments get one task per live worker (splits
  round-robin, FIXED_HASH partitions by index), SINGLE fragments one task;
  the root fragment executes on the coordinator, pulling child output over
  the same HTTP exchange (``server/protocol/Query.java:117``).
"""

from __future__ import annotations

import itertools
import json
import threading
import time
import urllib.request
from typing import Any, Optional

from trino_tpu.config import Session
from trino_tpu.events import StageCompletedEvent, TaskCompletedEvent
from trino_tpu.exec.local import ExecutionError, Result
from trino_tpu.obs.metrics import get_registry, percentile
from trino_tpu.obs.trace import TRACE_HEADER, format_trace_header, get_tracer
from trino_tpu.planner import plan as P
from trino_tpu.planner.fragmenter import (
    HASH,
    SINGLE,
    SOURCE,
    FusedFragment,
    PlanFragment,
    SubPlan,
    filtered_broadcast_fids,
    fragment_plan,
    fuse_groups,
    partitioned_join_pairs,
)

_task_counter = itertools.count(1)


class WorkerNode:
    def __init__(self, node_id: str, uri: str):
        self.node_id = node_id
        self.uri = uri.rstrip("/")
        self.last_announce = time.time()

    def to_json(self) -> dict:
        return {
            "nodeId": self.node_id,
            "uri": self.uri,
            "lastAnnounceSecondsAgo": round(time.time() - self.last_announce, 3),
        }


class ClusterNodeManager:
    """Announce-based membership + failure-detector exclusion."""

    def __init__(self, announce_timeout: float = 30.0, ping_interval: float = 2.0):
        self._nodes: dict[str, WorkerNode] = {}
        self._lock = threading.Lock()
        self.announce_timeout = announce_timeout
        from trino_tpu.server.failuredetector import HeartbeatFailureDetector

        def ping(uri: str) -> bool:
            with urllib.request.urlopen(f"{uri}/v1/info", timeout=5) as r:
                return r.status == 200

        self.failure_detector = HeartbeatFailureDetector(
            ping, interval=ping_interval
        )
        self._started = False

    def announce(self, node_id: str, uri: str) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                self._nodes[node_id] = WorkerNode(node_id, uri)
                self.failure_detector.register(node_id, uri)
            else:
                node.last_announce = time.time()
                new_uri = uri.rstrip("/")
                if node.uri != new_uri:
                    # restarted worker, same identity, new port: the
                    # detector must ping the NEW uri (and forget the dead
                    # port's failure history) or the fresh process would
                    # be flagged failed on its predecessor's evidence
                    node.uri = new_uri
                    self.failure_detector.register(node_id, new_uri)
        if not self._started:
            self._started = True
            try:
                self.failure_detector.start()
            except Exception:  # pragma: no cover - detector is advisory
                pass

    def remove(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def decommission(self, node_id: str) -> None:
        """Drained-worker deregistration (DELETE /v1/announce/{nodeId}):
        drop from membership AND the failure detector, so a cleanly
        departed node neither attracts placements nor gets pinged and
        counted as failed."""
        self.remove(node_id)
        self.failure_detector.unregister(node_id)

    def all_nodes(self) -> list[WorkerNode]:
        with self._lock:
            return list(self._nodes.values())

    def active_nodes(self) -> list[WorkerNode]:
        """Announced recently AND not flagged by the failure detector
        (scheduling exclusion, reference NodeScheduler + failure detector)."""
        now = time.time()
        with self._lock:
            nodes = list(self._nodes.values())
        return [
            n
            for n in nodes
            if now - n.last_announce < self.announce_timeout
            and not self.failure_detector.is_failed(n.node_id)
        ]



class NodeScheduler:
    """Node-selection policy for task placement.

    Reference: ``execution/scheduler/NodeScheduler.java`` +
    ``UniformNodeSelector.java`` — tasks go to the least-loaded active
    nodes (tracked coordinator-side per in-flight task) rather than blind
    round-robin, so a straggling worker stops attracting new work.
    """

    def __init__(self, node_manager: "ClusterNodeManager"):
        self.node_manager = node_manager
        self._assigned: dict[str, int] = {}  # node_id -> in-flight tasks
        self._lock = threading.Lock()

    def select(self, nodes: list["WorkerNode"], count: int) -> list["WorkerNode"]:
        """Pick ``count`` placements (repeats allowed when count > nodes),
        each time choosing the node with the fewest in-flight tasks.

        Selection IS reservation: ``_assigned`` is bumped here, under the
        same lock, so two fragments scheduling concurrently see each
        other's placements instead of both dog-piling the least-loaded
        node. Callers release via :meth:`release` when the task finishes
        (or fails to start).

        Among equally-loaded nodes, the failure detector's ping-latency
        EWMA breaks the tie (slow node last); nodes without latency
        evidence rank neutral (0.0), preserving the node-id round-robin."""
        det = getattr(self.node_manager, "failure_detector", None)
        lat = getattr(det, "latency_ms", None)
        out: list[WorkerNode] = []
        with self._lock:
            for _ in range(count):
                best = min(
                    nodes,
                    key=lambda n: (
                        self._assigned.get(n.node_id, 0),
                        lat(n.node_id) if lat is not None else 0.0,
                        n.node_id,
                    ),
                )
                self._assigned[best.node_id] = (
                    self._assigned.get(best.node_id, 0) + 1
                )
                out.append(best)
        return out

    def acquire(self, node: "WorkerNode") -> None:
        with self._lock:
            self._assigned[node.node_id] = self._assigned.get(node.node_id, 0) + 1

    def release(self, node: "WorkerNode") -> None:
        with self._lock:
            v = self._assigned.get(node.node_id, 0) - 1
            if v <= 0:
                self._assigned.pop(node.node_id, None)
            else:
                self._assigned[node.node_id] = v


def phased_order(sub: "SubPlan") -> list["PlanFragment"]:
    """Fragment launch order under the phased policy.

    Reference: ``execution/scheduler/PhasedExecutionSchedule.java`` —
    producers launch before consumers (our baseline bottom-up already
    guarantees that), and among one join's feeding fragments the BUILD
    side (the join's right subtree) launches before the PROBE side, so
    probe tasks never sit on a worker waiting for a build that has not
    even been scheduled.
    """
    out: list[PlanFragment] = []

    def build_side_fragments(frag: PlanFragment) -> set[int]:
        """Fragment ids referenced from any join's right (build) subtree."""
        build: set[int] = set()

        def mark(node, in_build: bool):
            if isinstance(node, P.RemoteSource):
                if in_build:
                    build.add(node.fragment_id)
                return
            if isinstance(node, P.Join):
                mark(node.left, in_build)
                mark(node.right, True)
                return
            for s in node.sources:
                mark(s, in_build)

        mark(frag.root, False)
        return build

    def rec(sp: "SubPlan"):
        build_ids = build_side_fragments(sp.fragment)
        ordered = sorted(
            sp.children,
            key=lambda c: 0 if c.fragment.id in build_ids else 1,
        )
        for c in ordered:
            rec(c)
        out.append(sp.fragment)

    rec(sub)
    return out


class HttpRemoteTask:
    """Coordinator-side handle of one worker task.

    Request timeouts come from the session (``http_request_timeout_s``)
    and each call retries through transient failures — including injected
    HTTP drops — with deterministic backoff. Injection sites are keyed by
    ``fragment.partition[+attempt]`` (the task id minus the per-run query
    counter) so chaos runs replay exactly.
    """

    def __init__(
        self,
        node: WorkerNode,
        task_id: str,
        payload: dict,
        timeout: float = 30.0,
        http_retries: int = 3,
        injector=None,
        backoff=None,
    ):
        from trino_tpu.ft.retry import Backoff

        self.node = node
        self.task_id = task_id
        self.payload = payload
        self.uri = f"{node.uri}/v1/task/{task_id}"
        self.timeout = timeout
        self.http_retries = max(1, int(http_retries))
        self.injector = injector
        self.backoff = backoff or Backoff()
        # set instead of raising when a TASK-retry dispatch fails to start
        self.start_error: Optional[str] = None
        # observability: the dispatch attempt's span + propagation context
        # ((trace_id, span_id) rides X-Trino-Trace so the worker's
        # task_execute span parents to this attempt), last observed status
        # for the end-of-query finalize pass, and attempt ordinal
        self.trace = None
        self.span = None
        self.attempt = 1
        self.last_status: Optional[dict] = None
        self._obs_done = False
        # hedged execution: dispatch time feeds the straggler detector;
        # speculative marks a duplicate (hedge) attempt of a straggler
        self.started_mono: Optional[float] = None
        self.speculative = False
        # recovery: set on lineage re-executions of a dead producer
        # (rendered like speculative attempts in the waterfall)
        self.recovered = False

    def _site_target(self) -> str:
        # "cq7.3.0r1" -> "3.0r1": stable across runs, fresh per attempt
        return self.task_id.split(".", 1)[-1]

    def _request(
        self,
        op: str,
        method: str,
        uri: str,
        body: Optional[bytes] = None,
        timeout: Optional[float] = None,
        parse: bool = True,
    ):
        from trino_tpu.ft.retry import is_retryable
        from trino_tpu.server import auth

        last: Optional[Exception] = None
        for attempt in range(1, self.http_retries + 1):
            try:
                if self.injector is not None:
                    site = self.injector.http_site(
                        op, self._site_target(), attempt
                    )
                    self.injector.delay_http(site)
                    self.injector.maybe_drop_http(site)
                req = urllib.request.Request(
                    uri, data=body, method=method, headers=auth.headers()
                )
                if body is not None:
                    req.add_header("Content-Type", "application/json")
                header = format_trace_header(self.trace)
                if header is not None:
                    req.add_header(TRACE_HEADER, header)
                with urllib.request.urlopen(
                    req, timeout=timeout or self.timeout
                ) as r:
                    return json.loads(r.read().decode()) if parse else None
            except Exception as e:  # noqa: BLE001
                last = e
                if not is_retryable(e) or attempt >= self.http_retries:
                    raise
                time.sleep(self.backoff.delay(attempt))
        raise last  # pragma: no cover — loop always returns or raises

    def start(self) -> None:
        self.started_mono = time.monotonic()
        self._request(
            "start", "POST", self.uri, body=json.dumps(self.payload).encode()
        )

    def elapsed_ms(self) -> float:
        """Wall time since dispatch (0 before start)."""
        if self.started_mono is None:
            return 0.0
        return (time.monotonic() - self.started_mono) * 1000.0

    def status(self, max_wait: float = 0.0) -> dict:
        uri = self.uri + (f"?maxWait={max_wait}" if max_wait else "")
        st = self._request(
            "status", "GET", uri, timeout=max(self.timeout, max_wait + 10)
        )
        self.last_status = st
        return st

    def cancel(self, speculative: bool = False) -> None:
        uri = self.uri + ("?speculative=true" if speculative else "")
        try:
            self._request("cancel", "DELETE", uri, timeout=10, parse=False)
        except Exception:  # noqa: BLE001 - best-effort
            pass


class SpoolHandle:
    """Stand-in for a dead producer whose completed output now serves
    from the coordinator's spool store.

    Quacks like :class:`HttpRemoteTask` everywhere the scheduler touches
    producer tasks: ``.uri`` points at ``/v1/spool/{taskId}`` (whose
    results route speaks the task-results wire shape, so consumers'
    ``ExchangeClient`` pulls it unchanged), ``status()`` is always
    FINISHED, ``cancel()`` is a no-op (spool lifetime belongs to the
    query, not the attempt). Its node is a dummy the NodeScheduler never
    reserved — the query-level release is a harmless no-op."""

    def __init__(self, base_uri: str, task_id: str):
        self.task_id = task_id
        self.uri = f"{base_uri.rstrip('/')}/v1/spool/{task_id}"
        self.node = WorkerNode("__spool__", base_uri)
        self.payload: dict = {}
        self.last_status = {"state": "FINISHED", "spool": True}
        self.start_error: Optional[str] = None
        self.trace = None
        self.span = None
        self.attempt = 1
        self.speculative = False
        self.recovered = True
        self._obs_done = True  # the recovery span already closed
        self.started_mono: Optional[float] = None

    def start(self) -> None:  # pragma: no cover — never dispatched
        pass

    def status(self, max_wait: float = 0.0) -> dict:
        return self.last_status

    def cancel(self, speculative: bool = False) -> None:
        pass

    def elapsed_ms(self) -> float:
        return 0.0


class ClusterScheduler:
    """Schedules a fragmented plan over the worker set and gathers output.

    One scheduler per coordinator; one `execute` per query.

    Retry policies (``retry_policy`` session property, reference: Trino's
    fault-tolerant execution / ``io.trino.execution.RetryPolicy``):

    - NONE: v356 semantics — pipelined stages, any task failure fails the
      query (now with a *classified* retryable/fatal error).
    - TASK: stage-barrier execution over retained (materialized) task
      output. Each fragment's tasks must finish before consumers launch;
      a failed attempt is re-dispatched to a different worker with
      exponential backoff + deterministic jitter, bounded by
      ``task_retry_attempts``. Placement consults the failure detector's
      ``active_nodes()`` so sick workers do not attract retries.
    - QUERY is handled a level up (server/querymanager.py): the whole
      statement re-runs on a fresh attempt salt.
    """

    def __init__(self, engine, node_manager: ClusterNodeManager):
        self.engine = engine
        self.node_manager = node_manager
        self.node_scheduler = NodeScheduler(node_manager)

    def _http_opts(self, session: Session) -> dict:
        """Per-query HTTP tuning + chaos hooks for remote-task calls."""
        from trino_tpu.ft.injection import FaultInjector
        from trino_tpu.ft.retry import Backoff

        try:
            timeout = float(session.get("http_request_timeout_s"))
            retries = int(session.get("http_retry_attempts"))
        except KeyError:
            timeout, retries = 30.0, 3
        return {
            "timeout": timeout,
            "http_retries": retries,
            "injector": FaultInjector.from_session(session),
            "backoff": Backoff.from_session(session),
        }

    def execute(
        self,
        plan: P.PlanNode,
        session: Session,
        stats_sink=None,
        query_id: Optional[str] = None,
    ):
        """Returns (Batch, column_names). ``stats_sink`` (dict) receives
        retry/attempt counters plus a per-stage ``stages`` rollup for
        query stats and /v1/query."""
        from trino_tpu.ft.retry import (
            RetryPolicy,
            SpeculationConfig,
            SpoolConfig,
        )

        tracer = get_tracer()
        with tracer.span("fragment"):
            sub = fragment_plan(plan)
        nodes = self.node_manager.active_nodes()
        if not nodes:
            raise ExecutionError("no active workers in the cluster")
        n = len(nodes)
        query_id = query_id or f"cq{next(_task_counter)}"
        policy = RetryPolicy.from_session(session)
        stats = stats_sink if stats_sink is not None else {}
        stats.setdefault("retry_policy", policy)
        stats.setdefault("task_retries", 0)
        stats.setdefault("task_attempts", {})
        stats.setdefault("speculative_attempts", 0)
        stats.setdefault("speculative_wins", 0)
        http = self._http_opts(session)

        fragments = {f.id: f for f in sub.all_fragments()}
        # execution policy: all-at-once launches in simple bottom-up order;
        # phased launches join build sides before their probes
        # (AllAtOnceExecutionPolicy vs PhasedExecutionPolicy)
        if session.get("execution_policy") == "phased":
            order = phased_order(sub)
        else:
            order = self._bottom_up(sub)

        # whole-pipeline fusion: chains of eligible fragments collapse
        # into single-task stage-groups — ONE task POST runs the whole
        # chain as one compiled program on one worker's local mesh
        # (in-jit collectives cannot cross worker process boundaries, so
        # a fused unit trades cross-worker fan-out for zero interior
        # dispatch round-trips). Speculation/retry operate on the unit
        # task. Spooled exchange coexists: the unit's only materialized
        # outputs are its unit-boundary buffers, so those are the spool
        # pages — recovery then works at unit granularity (re-point a
        # complete unit spool, or re-execute the lost unit atomically).
        units_members: dict[int, list[PlanFragment]] = {}
        unit_root_of: dict[int, int] = {}
        units_fused: dict[int, FusedFragment] = {}
        if (
            bool(session.get("pipeline_fusion"))
            and str(session.get("worker_execution")).startswith("fused")
        ):
            from trino_tpu.exec.fragments import fragment_fusable

            units = fuse_groups(
                sub,
                fusable=fragment_fusable,
                max_fragments=max(
                    1, int(session.get("fusion_max_fragments"))
                ),
                # selective broadcast builds keep the dynamic-filter
                # boundary (worker-side DF needs the materialized build)
                blocked=(
                    frozenset(filtered_broadcast_fids(sub))
                    if bool(session.get("enable_dynamic_filtering"))
                    else frozenset()
                ),
                skew_pairs=(
                    partitioned_join_pairs(sub)
                    if bool(session.get("skew_handling"))
                    else ()
                ),
                include_root=False,  # the root runs on the coordinator
                broadcast_links=bool(session.get("dense_join")),
            )
            for u in units:
                if isinstance(u, FusedFragment):
                    units_members[u.id] = list(u.fragments)
                    units_fused[u.id] = u
                    for m in u.fragments:
                        unit_root_of[m.id] = u.id

        # task counts per fragment (root runs on the coordinator)
        task_counts: dict[int, int] = {}
        for frag in order:
            if frag.id == sub.fragment.id:
                task_counts[frag.id] = 0  # coordinator
            elif frag.id in units_members:
                task_counts[frag.id] = 1  # one fused program, one worker
            elif unit_root_of.get(frag.id, frag.id) != frag.id:
                task_counts[frag.id] = 0  # interior: rides its unit task
            elif frag.partitioning.kind in (SOURCE, HASH):
                task_counts[frag.id] = n
            else:
                task_counts[frag.id] = 1

        consumer_of: dict[int, int] = {}
        for frag in order:
            for fid in frag.source_fragment_ids:
                # producers feeding a fused unit's interior address the
                # unit's single task: partition counts follow the unit
                consumer_of[fid] = unit_root_of.get(frag.id, frag.id)

        remote_tasks: dict[int, list[HttpRemoteTask]] = {}
        session_json = {
            "user": session.user,
            "catalog": session.catalog,
            "schema": session.schema,
            "properties": {
                k: v
                for k, v in session.properties.items()
                if isinstance(v, (str, int, float, bool))
                and k not in ("execution_mode",)
            },
        }
        # per-execute observability state (the scheduler instance is shared
        # across concurrent queries, so nothing goes on ``self``):
        # stage spans stay open until the query finalizes, ``elapsed``
        # collects FINISHED sibling-task wall times per stage for the
        # p50/p99 rollup, ``stage_start`` is monotonic per stage. The
        # speculation budget (max concurrent hedges) is per QUERY, shared
        # across this execute's stage barriers via spec_active.
        spec = SpeculationConfig.from_session(session)
        obs: dict = {
            "stage_spans": {},
            "elapsed": {},
            "stage_start": {},
            "spec": spec,
            "spec_budget": spec.budget(sum(task_counts.values())),
            "spec_active": 0,
        }
        # spooled exchange + lineage recovery (only under TASK retry: both
        # extend the retained-buffer materialized exchange). ``store`` is
        # the coordinator-hosted spool; ``rc`` is the recovery context the
        # heal paths thread through — which producer ran where, how to
        # rebuild source URIs, and where the spool lives.
        spool_cfg = SpoolConfig.from_session(session)
        spool_base = getattr(self.engine, "spool_base_uri", None)
        store = None
        spool_payload = None
        if spool_cfg.enabled and policy == RetryPolicy.TASK and spool_base:
            from trino_tpu.exchange.spool import get_spool_store

            store = get_spool_store(
                self.engine, spool_cfg.spool_dir, spool_cfg.max_bytes
            )
            spool_payload = {"uri": spool_base, "queryId": query_id}
        rc = None
        if policy == RetryPolicy.TASK:
            stats.setdefault("recovered_tasks", 0)
            stats.setdefault("recovered_levels", {})
        if policy == RetryPolicy.TASK and spool_cfg.enabled:
            # dead-producer recovery (spool re-point / lineage
            # re-execution) is part of the opt-in spooled-exchange mode:
            # without it, a retry keeps the plain PR-6 semantics — no
            # liveness probes of upstream producers on the retry path
            rc = {
                "query_id": query_id,
                "fragments": fragments,
                "remote_tasks": remote_tasks,
                "session": session,
                "http": http,
                "stats": stats,
                "store": store,
                "base_uri": spool_base,
                "lineage_seq": itertools.count(1),
                "obs": obs,
                "units": units_fused,
            }
        ok = False
        try:
            for frag in order:
                if frag.id == sub.fragment.id:
                    continue
                if unit_root_of.get(frag.id, frag.id) != frag.id:
                    continue  # fused-unit interior: rides its unit's task
                members = units_members.get(frag.id)
                if rc is not None:
                    # lineage heal: a producer whose node left the cluster
                    # since its barrier is recovered (spool re-point or
                    # re-execution) BEFORE this consumer's source URIs are
                    # baked into its payloads
                    self._heal_sources(frag, rc)
                obs["stage_start"][frag.id] = time.monotonic()
                stage_span = tracer.start_span(
                    "stage",
                    attrs={
                        "stage": frag.id,
                        "tasks": task_counts[frag.id],
                        **(
                            {"fusedFragments": len(members)}
                            if members is not None
                            else {}
                        ),
                    },
                )
                obs["stage_spans"][frag.id] = stage_span
                remote_tasks[frag.id] = self._schedule_fragment(
                    query_id,
                    frag,
                    nodes,
                    task_counts,
                    consumer_of,
                    remote_tasks,
                    session_json,
                    fragments,
                    policy=policy,
                    http=http,
                    stage_span=stage_span,
                    spool=spool_payload,
                    members=members,
                )
                if policy == RetryPolicy.TASK:
                    # stage barrier: producers must FINISH (with retained
                    # output) before consumers launch, so a consumer only
                    # ever sees the surviving attempt's URIs and retained
                    # pages stay re-pullable by retried consumers
                    self._await_fragment(
                        query_id, frag, remote_tasks[frag.id],
                        session, stats, http,
                        stage_span=stage_span, obs=obs, rc=rc,
                    )
            obs["stage_start"][sub.fragment.id] = time.monotonic()
            root_span = tracer.start_span(
                "stage",
                attrs={
                    "stage": sub.fragment.id,
                    "tasks": 0,
                    "coordinator": True,
                },
            )
            obs["stage_spans"][sub.fragment.id] = root_span
            with tracer.activate(root_span):
                result = self._execute_root(
                    sub.fragment, session, remote_tasks, task_counts, policy,
                    rc=rc,
                )
            ok = True
            if policy == RetryPolicy.TASK:
                # retained buffers never free on ack; release them now
                for tasks in remote_tasks.values():
                    for t in tasks:
                        t.cancel()
            return result
        except Exception:
            for tasks in remote_tasks.values():
                for t in tasks:
                    t.cancel()
            raise
        finally:
            if store is not None:
                # the query is done either way: record what got spooled,
                # then free the spool (results already left the cluster)
                stats["spooled_bytes"] = store.query_bytes(query_id)
                store.delete_query(query_id)
            # close attempt/stage spans, fire stage/task events, and build
            # stats["stages"] BEFORE releasing nodes — the caller reads
            # ``stats`` right after execute() returns
            self._finalize_query(
                query_id, stats, remote_tasks, task_counts, obs, ok
            )
            for tasks in remote_tasks.values():
                for t in tasks:
                    self.node_scheduler.release(t.node)

    # --- stage scheduling -------------------------------------------------

    def _bottom_up(self, sub: SubPlan) -> list[PlanFragment]:
        out: list[PlanFragment] = []

        def rec(sp: SubPlan):
            for c in sp.children:
                rec(c)
            out.append(sp.fragment)

        rec(sub)
        return out

    def _sources_payload(
        self,
        frag: PlanFragment,
        partition: int,
        remote_tasks: dict[int, list[HttpRemoteTask]],
        fragments: dict[int, PlanFragment],
        exclude: frozenset = frozenset(),
    ) -> dict:
        sources = {}
        for fid in frag.source_fragment_ids:
            if fid in exclude:
                continue  # in-unit producer: handed off inside the program
            tasks = remote_tasks[fid]
            producer = fragments.get(fid)
            entry = {
                "locations": [t.uri for t in tasks],
                "partition": partition,
            }
            if producer is not None and producer.output_exchange == "hash":
                # workers re-partition hash-exchanged rows over their local
                # devices; ship the partition keys and the wire column order
                entry["keys"] = [s.name for s in producer.output_keys]
                entry["symbols"] = [
                    s.name for s in producer.root.output_symbols
                ]
            sources[str(fid)] = entry
        return sources

    def _schedule_fragment(
        self,
        query_id: str,
        frag: PlanFragment,
        nodes: list[WorkerNode],
        task_counts: dict[int, int],
        consumer_of: dict[int, int],
        remote_tasks: dict[int, list[HttpRemoteTask]],
        session_json: dict,
        fragments: dict[int, PlanFragment],
        policy: str = "NONE",
        http: Optional[dict] = None,
        stage_span=None,
        spool: Optional[dict] = None,
        members: Optional[list[PlanFragment]] = None,
    ) -> list[HttpRemoteTask]:
        from trino_tpu.ft.retry import RetryPolicy, is_retryable
        from trino_tpu.planner.serde import fragment_to_json

        http = http or {}

        n_tasks = task_counts[frag.id]
        consumer = consumer_of.get(frag.id)
        output_partitions = max(
            1, task_counts.get(consumer, 1) if consumer is not None else 1
        )
        # a fused unit's task evaluates every member fragment, so its
        # splits and remote sources span the whole member list
        member_ids = frozenset(m.id for m in members) if members else frozenset()
        scan_frags = members if members else [frag]
        # split assignment for SOURCE fragments (enumerated on the
        # coordinator during scheduling, reference SplitManager timing)
        split_assignment: list[dict[str, list[dict]]] = [
            {} for _ in range(max(n_tasks, 1))
        ]
        scans: dict[str, tuple[P.TableScan, Any]] = {}
        for sf in scan_frags:
            if sf.partitioning.kind != SOURCE and not members:
                continue
            for node in P.walk_plan(sf.root):
                if isinstance(node, P.TableScan):
                    key = f"{node.catalog}.{node.schema}.{node.table}"
                    if key in scans:
                        # two member scans of one table share the split
                        # list on the wire: widen to unconstrained when
                        # their pruning constraints disagree, so neither
                        # scan misses splits (predicates still apply
                        # in-program — the constraint is advisory)
                        if scans[key][1] != node.constraint:
                            scans[key] = (scans[key][0], None)
                        continue
                    scans[key] = (node, node.constraint)
        for key, (node, constraint) in scans.items():
            connector = self.engine.catalogs.get(node.catalog)
            splits = connector.get_splits(
                node.schema,
                node.table,
                target_splits=max(n_tasks, 1) * 4,
                constraint=constraint,
            )
            for i, s in enumerate(splits):
                split_assignment[i % max(n_tasks, 1)].setdefault(
                    key, []
                ).append(
                    {
                        "table": s.table,
                        "index": s.index,
                        "total": s.total,
                        "info": s.info,
                    }
                )
        frag_json = fragment_to_json(frag)
        tasks: list[HttpRemoteTask] = []
        # membership can shrink between execute()'s snapshot and this
        # fragment's turn (node died or drained during an earlier stage
        # barrier): place on the currently-live subset when one exists.
        # task_counts stay as planned — fewer nodes just take more tasks.
        live = {x.node_id for x in self.node_manager.active_nodes()}
        candidates = [x for x in nodes if x.node_id in live] or nodes
        placements = self.node_scheduler.select(candidates, n_tasks)
        try:
            for p in range(n_tasks):
                sources: dict = {}
                for sf in scan_frags:
                    sources.update(
                        self._sources_payload(
                            sf, p, remote_tasks, fragments, exclude=member_ids
                        )
                    )
                payload = {
                    "session": session_json,
                    "fragment": frag_json,
                    "splits": split_assignment[p],
                    "sources": sources,
                    "output_partitions": output_partitions,
                    # materialized exchange: retained pages survive acks so
                    # a retried consumer attempt can re-pull them
                    "retain_output": policy == RetryPolicy.TASK,
                }
                if members is not None:
                    # whole chain ships with the task: the worker compiles
                    # the members into one program instead of N fragments
                    payload["fused_fragments"] = [
                        fragment_to_json(m) for m in members
                    ]
                if spool is not None:
                    # async durable copy: the worker spools finished pages
                    # to the coordinator so output survives its death
                    payload["spool"] = spool
                task = HttpRemoteTask(
                    placements[p], f"{query_id}.{frag.id}.{p}", payload, **http
                )
                att = get_tracer().start_span(
                    "task_attempt",
                    trace_id=getattr(stage_span, "trace_id", None),
                    parent_id=getattr(stage_span, "span_id", None),
                    attrs={
                        "taskId": task.task_id,
                        "stage": frag.id,
                        "worker": placements[p].node_id,
                        "attempt": 1,
                    },
                )
                task.span = att
                # rides X-Trino-Trace so the worker's task_execute span
                # parents to this dispatch attempt
                task.trace = att.context()
                if policy == RetryPolicy.TASK:
                    # a dispatch failure is just attempt 1 failing: defer
                    # to the stage barrier, which retries it elsewhere
                    try:
                        task.start()
                    except Exception as e:  # noqa: BLE001
                        if not is_retryable(e):
                            raise
                        task.start_error = str(e)
                else:
                    task.start()  # select() already reserved the slot
                tasks.append(task)
        except Exception:
            # a mid-fragment failure leaves these tasks outside
            # remote_tasks, so the query-level release never sees them:
            # cancel started tasks and release EVERY reserved placement
            # (started or not) to keep the load counters honest
            for t in tasks:
                t.cancel()
            for node in placements:
                self.node_scheduler.release(node)
            raise
        return tasks

    # --- stage barrier + task retry (retry_policy=TASK) -------------------

    def _prune_slowest(self, candidates: list[WorkerNode]) -> list[WorkerNode]:
        """Drop the slowest healthy node from hedge/retry placement when
        its ping-latency EWMA is far off the fastest's (over both 2x the
        fastest AND fastest + 25ms — absolute floor so sub-millisecond
        jitter on a quiet loopback cluster never triggers it). Hedges and
        recovery re-dispatches exist to dodge slowness; landing them on
        the known-slowest node defeats the point."""
        if len(candidates) < 2:
            return candidates
        det = getattr(self.node_manager, "failure_detector", None)
        lat_fn = getattr(det, "latency_ms", None)
        if lat_fn is None:
            return candidates
        lats = {n.node_id: lat_fn(n.node_id) for n in candidates}
        known = [v for v in lats.values() if v > 0.0]
        if len(known) < 2:
            return candidates
        fastest, slowest = min(known), max(known)
        if slowest > max(2.0 * fastest, fastest + 25.0):
            keep = [n for n in candidates if lats[n.node_id] < slowest]
            if keep:
                return keep
        return candidates

    def _retry_node(self, exclude: str) -> WorkerNode:
        """Placement for a re-dispatched attempt: prefer a *different*
        worker with positive health evidence from the failure detector
        (avoiding the slowest of them); fall back to any active node
        (single-worker clusters retry in place rather than fail).
        ``select()`` reserves the slot."""
        active = self.node_manager.active_nodes()
        healthy = set(self.node_manager.failure_detector.active_nodes())
        candidates = [
            n for n in active
            if n.node_id != exclude and (not healthy or n.node_id in healthy)
        ]
        candidates = self._prune_slowest(candidates)
        if not candidates:
            candidates = [n for n in active if n.node_id != exclude] or active
        if not candidates:
            raise ExecutionError("no active workers available for task retry")
        return self.node_scheduler.select(candidates, 1)[0]

    def _speculation_node(self, exclude: str) -> Optional[WorkerNode]:
        """Placement for a hedged attempt: a *different* healthy node
        (never the slowest of them), or None (unlike retries, a hedge on
        the straggler's own node is pointless — skip hedging instead).
        ``select()`` reserves the slot; the caller must release on every
        hedge outcome."""
        active = self.node_manager.active_nodes()
        healthy = set(self.node_manager.failure_detector.active_nodes())
        candidates = [
            n for n in active
            if n.node_id != exclude and (not healthy or n.node_id in healthy)
        ]
        candidates = self._prune_slowest(candidates)
        if not candidates:
            candidates = [n for n in active if n.node_id != exclude]
        if not candidates:
            return None
        return self.node_scheduler.select(candidates, 1)[0]

    # --- lineage recovery (spooled exchange, worker death) -----------------

    def _producer_alive(self, t, probe: bool) -> bool:
        """Is this finished producer's retained output still reachable?
        Membership first (cheap); with ``probe`` also one live status GET
        — membership lags a fresh SIGKILL by several detector cycles, but
        the dead socket refuses instantly."""
        if isinstance(t, SpoolHandle):
            return True  # already durable on the coordinator
        active = {n.node_id for n in self.node_manager.active_nodes()}
        if t.node.node_id not in active:
            return False
        if not probe:
            return True
        try:
            st = t.status(max_wait=0.0)
        except Exception:  # noqa: BLE001 — unreachable == lost output
            return False
        return st.get("state") == "FINISHED"

    def _source_fids(self, frag, rc) -> tuple:
        """The producer fragment ids ``frag`` actually pulls from. For a
        fused-unit root that is the unit's *external* sources — every
        member's out-of-unit producer — because interior links are in-jit
        collectives with no tasks of their own. Everything else pulls its
        plain ``source_fragment_ids``."""
        unit = (rc.get("units") or {}).get(getattr(frag, "id", None))
        if unit is not None:
            return unit.external_source_ids
        return tuple(getattr(frag, "source_fragment_ids", ()) or ())

    def _rebuild_sources(self, frag, partition: int, rc: dict) -> dict:
        """Source URIs for a (re)dispatched attempt of ``frag``, rebuilt
        from the current remote_tasks — which may now hold spool handles
        or recovered attempts. Unit-aware: a fused unit's sources span
        all members, with in-unit links excluded."""
        unit = (rc.get("units") or {}).get(getattr(frag, "id", None))
        if unit is not None:
            sources: dict = {}
            for m in unit.fragments:
                sources.update(
                    self._sources_payload(
                        m, partition, rc["remote_tasks"], rc["fragments"],
                        exclude=unit.member_ids,
                    )
                )
            return sources
        return self._sources_payload(
            frag, partition, rc["remote_tasks"], rc["fragments"]
        )

    def _heal_sources(self, frag, rc, probe: bool = False) -> bool:
        """Recover every dead producer feeding ``frag``: spool re-point
        when the task's output spooled completely (level=task), else
        re-execute just that producer — recursively healing ITS sources
        first (level=lineage, or level=fused when the producer is a
        whole fused unit re-run atomically). Returns whether anything was
        recovered (callers then rebuild consumer source URIs from
        remote_tasks). Fused-unit consumers heal the unit's *external*
        sources — interior members have no tasks to heal."""
        if rc is None:
            return False
        healed = False
        for fid in self._source_fids(frag, rc):
            tasks = rc["remote_tasks"].get(fid)
            if not tasks:
                continue
            for idx in range(len(tasks)):
                if self._producer_alive(tasks[idx], probe):
                    continue
                self._recover_task(fid, idx, rc, probe=probe)
                healed = True
        return healed

    def _recover_task(self, fid: int, idx: int, rc: dict,
                      probe: bool = False) -> None:
        """Recover one lost producer task. Tier 1 (level=task): its spool
        is complete — swap a :class:`SpoolHandle` into remote_tasks so
        consumers read the durable copy; no re-execution at all (a fused
        unit's spool holds its unit-boundary output buffers, so the
        whole unit re-points as one handle). Tier 2: re-run only this
        producer on a healthy node, healing its own sources first —
        level=lineage for a plain fragment, level=fused when the lost
        producer is a fused unit re-executed atomically."""
        tasks = rc["remote_tasks"][fid]
        old = tasks[idx]
        store = rc.get("store")
        if (
            store is not None
            and rc.get("base_uri")
            and store.is_complete(old.task_id)
        ):
            self._spool_repoint(fid, idx, rc)
            return
        frag = rc["fragments"].get(fid)
        if frag is not None:
            # the producer's own inputs may have died with the same node:
            # heal them first so the re-execution pulls live sources
            self._heal_sources(frag, rc, probe=probe)
        self._run_recovery_task(fid, idx, rc)

    def _spool_repoint(self, fid: int, idx: int, rc: dict) -> None:
        """Swap a :class:`SpoolHandle` over a lost-but-fully-spooled
        attempt in remote_tasks (level=task — zero re-execution). The
        caller has already established ``store.is_complete(task_id)``."""
        tasks = rc["remote_tasks"][fid]
        old = tasks[idx]
        stats = rc["stats"]
        stage_span = (rc.get("obs") or {}).get("stage_spans", {}).get(fid)
        handle = SpoolHandle(rc["base_uri"], old.task_id)
        handle.payload = old.payload
        handle.attempt = getattr(old, "attempt", 1)
        tasks[idx] = handle
        self.node_scheduler.release(old.node)
        get_registry().counter(
            "trino_tpu_recovered_tasks_total", level="task"
        ).inc()
        stats["recovered_tasks"] = stats.get("recovered_tasks", 0) + 1
        levels = stats.setdefault("recovered_levels", {})
        levels["task"] = levels.get("task", 0) + 1
        # synthetic zero-length attempt span: the waterfall shows the
        # recovery point without pretending work re-ran
        span = get_tracer().start_span(
            "task_attempt",
            trace_id=getattr(stage_span, "trace_id", None),
            parent_id=getattr(stage_span, "span_id", None),
            attrs={
                "taskId": old.task_id,
                "stage": fid,
                "worker": "__spool__",
                "attempt": handle.attempt,
                "recovered": True,
                "spool": True,
                "fused": (rc.get("units") or {}).get(fid) is not None,
            },
        )
        span.finish(status="OK", state="FINISHED")

    def _run_recovery_task(self, fid: int, idx: int, rc: dict,
                           max_attempts: int = 3) -> None:
        """Re-execute one lost producer task to completion (lineage tier).
        Runs synchronously — recovery sits on a consumer's critical path
        anyway. Task ids take an ``l{k}`` suffix (fresh injection sites,
        distinct from ``r``etries and ``s``peculation). A fused unit
        re-executes atomically — its payload still carries the whole
        member chain, the worker re-traces through the fused program
        cache — and counts at level=fused (``{qid}.{unit}.{i}l{k}``)."""
        from trino_tpu.ft.retry import (
            TaskFailure,
            TaskRetriesExhausted,
            is_retryable,
        )

        tasks = rc["remote_tasks"][fid]
        frag = rc["fragments"].get(fid)
        session = rc["session"]
        stats = rc["stats"]
        try:
            budget_s = float(session.get("exchange_timeout_s"))
        except KeyError:
            budget_s = 300.0
        stage_span = (rc.get("obs") or {}).get("stage_spans", {}).get(fid)
        is_unit = (rc.get("units") or {}).get(fid) is not None
        level = "fused" if is_unit else "lineage"
        exclude = tasks[idx].node.node_id
        last_error: Optional[str] = None
        for _ in range(max_attempts):
            old = tasks[idx]
            k = next(rc["lineage_seq"])
            node = self._retry_node(exclude=exclude)
            new_id = f"{rc['query_id']}.{fid}.{idx}l{k}"
            payload = dict(old.payload)
            if frag is not None:
                # sources rebuilt NOW: they may point at spool handles or
                # other just-recovered attempts
                payload["sources"] = self._rebuild_sources(frag, idx, rc)
            task = HttpRemoteTask(node, new_id, payload, **rc["http"])
            task.attempt = getattr(old, "attempt", 1) + 1
            task.recovered = True
            att = get_tracer().start_span(
                "task_attempt",
                trace_id=getattr(stage_span, "trace_id", None),
                parent_id=getattr(stage_span, "span_id", None),
                attrs={
                    "taskId": new_id,
                    "stage": fid,
                    "worker": node.node_id,
                    "attempt": task.attempt,
                    "recovered": True,
                    "lineage": True,
                    "fused": is_unit,
                },
            )
            task.span = att
            task.trace = att.context()
            # swap in before start(): query-level cleanup releases whatever
            # sits in remote_tasks; the dead attempt's slot frees here
            tasks[idx] = task
            self.node_scheduler.release(old.node)
            deadline = time.monotonic() + budget_s
            failed_st: Optional[dict] = None
            try:
                task.start()
                while True:
                    st = task.status(max_wait=1.0)
                    state = st.get("state")
                    if state == "FINISHED":
                        self._finish_attempt(
                            rc["query_id"], fid, task, st, rc.get("obs")
                        )
                        get_registry().counter(
                            "trino_tpu_recovered_tasks_total", level=level
                        ).inc()
                        stats["recovered_tasks"] = (
                            stats.get("recovered_tasks", 0) + 1
                        )
                        levels = stats.setdefault("recovered_levels", {})
                        levels[level] = levels.get(level, 0) + 1
                        return
                    if state == "FAILED":
                        r = st.get("retryable")
                        if r is not None and not bool(r):
                            self._finish_attempt(
                                rc["query_id"], fid, task, st, rc.get("obs")
                            )
                            raise TaskFailure(
                                new_id, node.node_id, st.get("error"),
                                retryable=False,
                            )
                        failed_st, last_error = st, st.get("error")
                        break
                    if time.monotonic() > deadline:
                        last_error = (
                            f"lineage recovery exceeded {budget_s}s budget"
                        )
                        failed_st = {"state": "FAILED", "error": last_error}
                        break
            except TaskFailure:
                raise
            except Exception as e:  # noqa: BLE001
                if not is_retryable(e):
                    raise
                last_error = str(e)
                failed_st = {"state": "FAILED", "error": last_error}
            task.cancel()
            self._finish_attempt(
                rc["query_id"], fid, task, failed_st, rc.get("obs")
            )
            exclude = node.node_id
        raise TaskRetriesExhausted(
            f"{rc['query_id']}.{fid}.{idx}",
            exclude,
            f"lineage recovery failed: {last_error}",
            max_attempts,
        )

    def _await_fragment(
        self,
        query_id: str,
        frag: PlanFragment,
        tasks: list[HttpRemoteTask],
        session: Session,
        stats: dict,
        http: dict,
        stage_span=None,
        obs: Optional[dict] = None,
        rc: Optional[dict] = None,
    ) -> None:
        """Block until every task of ``frag`` is FINISHED, re-dispatching
        failed attempts (``{qid}.{frag}.{p}`` -> ``...{p}r{k}``) to other
        workers with backoff, bounded by ``task_retry_attempts``.

        Speculation (``speculation=true``): once enough siblings have
        finished, a running attempt whose elapsed exceeds
        ``max(floor, multiplier * p99_of_completed_siblings)`` gets ONE
        duplicate (hedge) attempt (``...{p}s{k}``) on a different healthy
        node. First finisher wins and is swapped into ``tasks`` — under
        the stage barrier consumers only ever read the winner's URI, so
        the loser (cancelled with ``?speculative=true``, which aborts its
        output buffer) can never double-deliver pages. Concurrent hedges
        are capped per query by ``speculation_max_fraction``.

        Mutates ``tasks`` in place so consumers scheduled afterwards see
        the surviving attempt's URIs. Raises :class:`TaskFailure` for a
        fatal error, :class:`TaskRetriesExhausted` when the budget is
        spent (QUERY retry may still apply a level up)."""
        from trino_tpu.ft.retry import (
            Backoff,
            TaskFailure,
            TaskRetriesExhausted,
            is_retryable,
        )

        try:
            max_attempts = max(1, int(session.get("task_retry_attempts")))
        except KeyError:
            max_attempts = 4
        try:
            stage_budget = float(session.get("exchange_timeout_s"))
        except KeyError:
            stage_budget = 300.0
        backoff = http.get("backoff") or Backoff.from_session(session)
        reg = get_registry()
        spec = (obs or {}).get("spec")
        attempts = [1] * len(tasks)
        # per-attempt deadline: a hung-but-responsive worker must not
        # stall the stage barrier forever — overrun counts as a
        # retryable attempt failure (monotonic: wall-clock jumps must not
        # spuriously expire the budget)
        deadlines = [time.monotonic() + stage_budget] * len(tasks)
        pending = set(range(len(tasks)))
        hedges: dict[int, HttpRemoteTask] = {}

        def _spec_counter(outcome: str) -> None:
            reg.counter(
                "trino_tpu_speculative_attempts_total", outcome=outcome
            ).inc()

        def _drop_hedge(i: int, h: HttpRemoteTask, st: dict,
                        outcome: str) -> None:
            """Resolve a hedge that did NOT win: cancel, release its node,
            close its attempt span, free budget."""
            hedges.pop(i, None)
            h.cancel(speculative=True)
            self.node_scheduler.release(h.node)
            self._finish_attempt(query_id, frag.id, h, st, obs)
            if obs is not None:
                obs["spec_active"] = max(0, obs.get("spec_active", 1) - 1)
            _spec_counter(outcome)

        def _dispatch_hedge(i: int, t: HttpRemoteTask, node: WorkerNode,
                            extra_attrs: dict) -> None:
            """Launch one hedge of ``tasks[i]`` on ``node`` (whose slot
            ``_speculation_node`` already reserved) and register it in
            ``hedges``. Shared by the straggler detector and the
            queued-task hedging path."""
            hedge_id = f"{query_id}.{frag.id}.{i}s{attempts[i]}"
            hedge = HttpRemoteTask(node, hedge_id, t.payload, **http)
            hedge.attempt = attempts[i]
            hedge.speculative = True
            att = get_tracer().start_span(
                "task_attempt",
                trace_id=getattr(stage_span, "trace_id", None),
                parent_id=getattr(stage_span, "span_id", None),
                attrs={
                    "taskId": hedge_id,
                    "stage": frag.id,
                    "worker": node.node_id,
                    "attempt": attempts[i],
                    "speculative": True,
                    "hedgeOf": t.task_id,
                    **extra_attrs,
                },
            )
            hedge.span = att
            hedge.trace = att.context()
            stats["speculative_attempts"] = (
                stats.get("speculative_attempts", 0) + 1
            )
            obs["spec_active"] = obs.get("spec_active", 0) + 1
            hedges[i] = hedge
            try:
                hedge.start()
            except Exception as e:  # noqa: BLE001
                if not is_retryable(e):
                    raise
                hedge.start_error = str(e)

        try:
            while pending:
                for i in sorted(pending):
                    t = tasks[i]
                    if t.start_error is not None:
                        # QUEUED-but-undispatched hedging: an attempt whose
                        # POST never landed is hedged immediately on a
                        # different healthy node (no straggler threshold —
                        # there is nothing running to outwait). The queued
                        # twin is cancelled when the hedge promotes, one
                        # poll round later.
                        if (
                            i not in hedges
                            and spec is not None
                            and spec.enabled
                            and obs is not None
                            and obs.get("spec_active", 0)
                            < obs.get("spec_budget", 0)
                            # promotion bumps attempts[i]; the cap keeps a
                            # cluster-wide dispatch outage from ping-ponging
                            # hedge->promote->hedge forever
                            and attempts[i] < max_attempts
                        ):
                            node = self._speculation_node(
                                exclude=t.node.node_id
                            )
                            if node is not None:
                                _dispatch_hedge(i, t, node, {"queued": True})
                                continue
                        failure, retryable = t.start_error, True
                        fail_st = {"state": "FAILED", "error": failure}
                    elif time.monotonic() > deadlines[i]:
                        failure = f"task attempt exceeded {stage_budget}s stage budget"
                        retryable = True
                        fail_st = {"state": "FAILED", "error": failure}
                    else:
                        try:
                            # a hedged straggler gets a short poll: the
                            # 1s long-poll would delay noticing the hedge
                            # finishing first by a full status round
                            st = t.status(
                                max_wait=0.05 if i in hedges else 1.0
                            )
                        except Exception as e:  # noqa: BLE001
                            if not is_retryable(e):
                                raise
                            # worker unreachable through all HTTP retries:
                            # treat the attempt as lost
                            failure, retryable = f"unreachable: {e}", True
                            fail_st = {"state": "FAILED", "error": failure}
                        else:
                            state = st.get("state")
                            if state == "FINISHED":
                                h = hedges.get(i)
                                if h is not None:
                                    # primary beat its hedge: the loser's
                                    # buffer is aborted before consumers
                                    # ever learn its URI
                                    _drop_hedge(
                                        i, h,
                                        {
                                            "state": "CANCELED_SPECULATIVE",
                                            "elapsed": h.elapsed_ms() / 1000.0,
                                        },
                                        outcome="cancelled",
                                    )
                                self._finish_attempt(query_id, frag.id, t, st, obs)
                                pending.discard(i)
                                continue
                            if state != "FAILED":
                                continue  # still queued/running
                            failure = st.get("error")
                            r = st.get("retryable")
                            retryable = True if r is None else bool(r)
                            fail_st = st
                    self._finish_attempt(query_id, frag.id, t, fail_st, obs)
                    if not retryable:
                        raise TaskFailure(
                            t.task_id, t.node.node_id, failure, retryable=False
                        )
                    if (
                        rc is not None
                        and rc.get("store") is not None
                        and rc.get("base_uri")
                        and rc["store"].is_complete(t.task_id)
                    ):
                        # stage-barrier spool re-point: the attempt (e.g. a
                        # single-task fused unit whose worker was killed
                        # right after finishing) is lost but its output
                        # spooled completely — the durable copy IS the
                        # attempt's output, so swap in a SpoolHandle and
                        # close the slot without re-running anything
                        h = hedges.get(i)
                        if h is not None:
                            _drop_hedge(
                                i, h, {"state": "CANCELED_SPECULATIVE"},
                                outcome="cancelled",
                            )
                        t.cancel()
                        self._spool_repoint(frag.id, i, rc)
                        pending.discard(i)
                        continue
                    h = hedges.pop(i, None)
                    if h is not None:
                        # the primary died while its hedge is in flight:
                        # promote the hedge instead of dispatching a fresh
                        # retry (the duplicate work is already running)
                        t.cancel()
                        self.node_scheduler.release(t.node)
                        attempts[i] += 1
                        stats.setdefault("task_attempts", {})[
                            f"{query_id}.{frag.id}.{i}"
                        ] = attempts[i]
                        if obs is not None:
                            obs["spec_active"] = max(
                                0, obs.get("spec_active", 1) - 1
                            )
                        tasks[i] = h
                        deadlines[i] = time.monotonic() + stage_budget
                        continue
                    if attempts[i] >= max_attempts:
                        raise TaskRetriesExhausted(
                            t.task_id, t.node.node_id, failure, attempts[i]
                        )
                    # release the failed attempt, back off, re-dispatch
                    t.cancel()
                    self.node_scheduler.release(t.node)
                    time.sleep(backoff.delay(attempts[i]))
                    payload = t.payload
                    if rc is not None:
                        # the failure may be the symptom of a dead
                        # producer: probe this fragment's sources, recover
                        # lost ones (spool re-point / lineage
                        # re-execution), and rebuild the source URIs the
                        # retry will pull — remote_tasks may now hold
                        # spool handles or recovered attempts
                        self._heal_sources(frag, rc, probe=True)
                        payload = dict(t.payload)
                        payload["sources"] = self._rebuild_sources(frag, i, rc)
                    node = self._retry_node(exclude=t.node.node_id)
                    attempts[i] += 1
                    base = f"{query_id}.{frag.id}.{i}"
                    new_id = f"{base}r{attempts[i] - 1}"
                    stats["task_retries"] = stats.get("task_retries", 0) + 1
                    stats.setdefault("task_attempts", {})[base] = attempts[i]
                    reg.counter("trino_tpu_task_retries_total").inc()
                    retry = HttpRemoteTask(node, new_id, payload, **http)
                    retry.attempt = attempts[i]
                    att = get_tracer().start_span(
                        "task_attempt",
                        trace_id=getattr(stage_span, "trace_id", None),
                        parent_id=getattr(stage_span, "span_id", None),
                        attrs={
                            "taskId": new_id,
                            "stage": frag.id,
                            "worker": node.node_id,
                            "attempt": attempts[i],
                            "retry": True,
                        },
                    )
                    retry.span = att
                    retry.trace = att.context()
                    # swap in before start(): the query-level cleanup releases
                    # whatever sits in ``tasks``, and the old node is released
                    tasks[i] = retry
                    deadlines[i] = time.monotonic() + stage_budget
                    try:
                        retry.start()
                    except Exception as e:  # noqa: BLE001
                        if not is_retryable(e):
                            raise
                        retry.start_error = str(e)

                # --- hedge polling: first finisher wins -------------------
                for i, h in list(hedges.items()):
                    if i not in pending:
                        continue
                    if h.start_error is not None:
                        hst = {"state": "FAILED", "error": h.start_error}
                    else:
                        try:
                            hst = h.status(max_wait=0.0)
                        except Exception as e:  # noqa: BLE001
                            if not is_retryable(e):
                                raise
                            hst = {"state": "FAILED", "error": f"unreachable: {e}"}
                    state = hst.get("state")
                    if state == "FINISHED":
                        # hedge wins: swap it in as the surviving attempt and
                        # speculatively cancel the straggling primary (its
                        # buffer aborts, so it can never deliver a page)
                        primary = tasks[i]
                        hedges.pop(i)
                        primary.cancel(speculative=True)
                        self._finish_attempt(
                            query_id, frag.id, primary,
                            {
                                "state": "CANCELED_SPECULATIVE",
                                "elapsed": primary.elapsed_ms() / 1000.0,
                            },
                            obs,
                        )
                        self.node_scheduler.release(primary.node)
                        tasks[i] = h
                        if obs is not None:
                            obs["spec_active"] = max(
                                0, obs.get("spec_active", 1) - 1
                            )
                        stats["speculative_wins"] = (
                            stats.get("speculative_wins", 0) + 1
                        )
                        _spec_counter("won")
                        _spec_counter("cancelled")  # the loser's cancel
                        self._finish_attempt(query_id, frag.id, h, hst, obs)
                        pending.discard(i)
                    elif state == "FAILED":
                        # hedge died on its own; the primary keeps running
                        # (no retry of a hedge — it was a bet, not a need)
                        _drop_hedge(i, h, hst, outcome="lost")

                # --- straggler detection -> hedge dispatch ----------------
                if (
                    spec is not None
                    and spec.enabled
                    and pending
                    and obs is not None
                    and obs.get("spec_active", 0) < obs.get("spec_budget", 0)
                ):
                    samples = obs["elapsed"].get(frag.id, [])
                    if (
                        len(tasks) == 1
                        and len(samples) < getattr(spec, "min_completed", 1)
                        and tasks[0].payload.get("fused_fragments")
                    ):
                        # a fused unit is a single-task stage — it has no
                        # siblings to threshold against, so borrow the
                        # query-wide completed-attempt samples (earlier
                        # stages/units of this query) for the p99
                        samples = [
                            v for vs in obs["elapsed"].values() for v in vs
                        ]
                    threshold = spec.threshold_ms(samples)
                    if threshold is not None:
                        for i in sorted(pending):
                            if i in hedges:
                                continue
                            t = tasks[i]
                            if (
                                t.start_error is not None
                                or t.elapsed_ms() <= threshold
                            ):
                                continue
                            node = self._speculation_node(
                                exclude=t.node.node_id
                            )
                            if node is None:
                                continue  # no distinct healthy node
                            _dispatch_hedge(
                                i, t, node,
                                {"thresholdMs": round(threshold, 1)},
                            )
                            if obs["spec_active"] >= obs["spec_budget"]:
                                break
        finally:
            # a raising exit (fatal failure, retries exhausted) leaves
            # hedges in flight; they are not in ``tasks``, so the
            # query-level cleanup would never cancel or release them
            for i, h in list(hedges.items()):
                _drop_hedge(
                    i, h, {"state": "CANCELED_SPECULATIVE"}, outcome="cancelled"
                )

    # --- per-attempt / per-query observability rollup ---------------------

    def _finish_attempt(
        self,
        query_id: str,
        frag_id: int,
        t: HttpRemoteTask,
        st: Optional[dict],
        obs: Optional[dict],
    ) -> None:
        """Close one dispatch attempt: span, counters, sibling-elapsed
        sample, TaskCompletedEvent. Idempotent per attempt — the stage
        barrier, _first_failed_status, and the end-of-query finalize can
        each observe the same task."""
        if t._obs_done:
            return
        t._obs_done = True
        st = st or {}
        state = st.get("state") or "UNKNOWN"
        elapsed_ms = float(st.get("elapsed") or 0.0) * 1000.0
        reg = get_registry()
        reg.counter("trino_tpu_tasks_total", state=state).inc()
        if state == "FINISHED":
            # sibling elapsed within a stage feeds the p50/p99 rollup the
            # speculation detector thresholds on
            if obs is not None:
                obs["elapsed"].setdefault(frag_id, []).append(elapsed_ms)
            reg.histogram(
                # fragment ids restart at 0 per plan: a bounded domain
                "trino_tpu_task_elapsed_ms", stage=str(frag_id)  # lint: ignore[OBS001]
            ).observe(elapsed_ms)
        if t.span is not None:
            attrs = {"state": state, "elapsedMs": elapsed_ms}
            if t.speculative:
                attrs["speculative"] = True
            if getattr(t, "recovered", False):
                attrs["recovered"] = True
            if st.get("error"):
                attrs["error"] = st.get("error")
            # a speculatively-cancelled loser is not an error: a sibling
            # simply finished first (rendered distinctly in the waterfall)
            if state == "FINISHED":
                status = "OK"
            elif state == "CANCELED_SPECULATIVE":
                status = "CANCELED"
            else:
                status = "ERROR"
            t.span.finish(status=status, **attrs)
        listeners = getattr(self.engine, "event_listeners", None)
        if listeners is not None:
            listeners.fire_task_completed(
                TaskCompletedEvent(
                    query_id=query_id,
                    stage_id=frag_id,
                    task_id=t.task_id,
                    worker=t.node.node_id,
                    state=state,
                    attempt=t.attempt,
                    elapsed_ms=elapsed_ms,
                    error_message=st.get("error"),
                    speculative=t.speculative,
                )
            )

    def _finalize_query(
        self,
        query_id: str,
        stats: dict,
        remote_tasks: dict[int, list[HttpRemoteTask]],
        task_counts: dict[int, int],
        obs: dict,
        ok: bool,
    ) -> None:
        """End-of-query rollup (runs on success AND failure, tracer on or
        off): close remaining attempt spans, close stage spans, observe
        stage metrics, fire stage events, and build ``stats['stages']``
        (elapsedMs + sibling task p50/p99) for queryStats."""
        for fid, tasks in remote_tasks.items():
            for t in tasks:
                if t._obs_done:
                    continue
                st = t.last_status
                terminal = st is not None and st.get("state") in (
                    "FINISHED", "FAILED", "CANCELED", "CANCELED_SPECULATIVE",
                )
                if ok and not terminal:
                    # one best-effort poll only on the success path — a
                    # failed query may have unreachable workers
                    try:
                        st = t.status()
                    except Exception:  # noqa: BLE001
                        pass
                self._finish_attempt(query_id, fid, t, st, obs)
        reg = get_registry()
        listeners = getattr(self.engine, "event_listeners", None)
        task_attempts = stats.get("task_attempts", {})
        now = time.monotonic()
        stages = []
        query_programs: dict[str, dict] = {}
        for fid in sorted(obs["stage_spans"]):
            start = obs["stage_start"].get(fid)
            elapsed_ms = (now - start) * 1000.0 if start is not None else 0.0
            n_tasks = task_counts.get(fid, 0)
            # retries recorded as {query_id}.{fid}.{i} -> total attempts
            extra = 0
            for base, a in task_attempts.items():
                rest = base[len(query_id) + 1:] if base.startswith(
                    query_id + "."
                ) else ""
                if rest.split(".", 1)[0] == str(fid):
                    extra += a - 1
            n_attempts = n_tasks + extra
            entry = {
                "stage": fid,
                "tasks": n_tasks,
                "attempts": n_attempts,
                "elapsedMs": elapsed_ms,
            }
            vals = obs["elapsed"].get(fid, [])
            if vals:
                entry["taskElapsedMs"] = {
                    "count": len(vals),
                    "p50": percentile(vals, 50),
                    "p99": percentile(vals, 99),
                    "max": max(vals),
                }
            self._merge_stage_task_stats(
                entry, remote_tasks.get(fid, []), query_programs
            )
            stages.append(entry)
            reg.histogram(
                # fragment ids restart at 0 per plan: a bounded domain
                "trino_tpu_stage_elapsed_ms", stage=str(fid)  # lint: ignore[OBS001]
            ).observe(elapsed_ms)
            obs["stage_spans"][fid].finish(
                status="OK" if ok else "ERROR",
                tasks=n_tasks,
                attempts=n_attempts,
            )
            if listeners is not None:
                listeners.fire_stage_completed(
                    StageCompletedEvent(
                        query_id=query_id,
                        stage_id=fid,
                        state="FINISHED" if ok else "FAILED",
                        tasks=n_tasks,
                        attempts=n_attempts,
                        elapsed_ms=elapsed_ms,
                        task_elapsed_p50_ms=percentile(vals, 50),
                        task_elapsed_p99_ms=percentile(vals, 99),
                    )
                )
        stats["stages"] = stages
        # query-level exchange rollup for /v1/query parity with local
        # mode: sum worker-shipped counters across stages, but take
        # dispatchRoundTrips from the coordinator's own accounting — one
        # per task POST attempt — since worker-side values also count
        # retried attempts whose work was discarded
        exchange_totals: dict = {}
        total_caps: dict = {}
        join_strategy: dict = {}
        total_operators: dict = {}
        for entry in stages:
            for k, v in (entry.get("exchange") or {}).items():
                if k == "capacities" and isinstance(v, dict):
                    total_caps.update(v)  # site names are per-stage unique
                elif k == "joinStrategy" and isinstance(v, dict):
                    join_strategy.update(v)  # ditto: densejoin@{fid}#{ord}
                elif k == "operators" and isinstance(v, dict):
                    total_operators.update(v)  # ditto: scan@{fid}#{ord}
                elif k != "padding_ratio" and isinstance(
                    v, (int, float)
                ) and not isinstance(v, bool):
                    exchange_totals[k] = exchange_totals.get(k, 0) + v
        if total_caps:
            exchange_totals["capacities"] = total_caps
        if join_strategy:
            exchange_totals["joinStrategy"] = join_strategy
        if total_operators:
            exchange_totals["operators"] = total_operators
        round_trips = sum(e.get("attempts", 0) for e in stages)
        if exchange_totals or round_trips:
            exchange_totals["dispatchRoundTrips"] = round_trips
            if exchange_totals.get("shuffle_rows"):
                exchange_totals["padding_ratio"] = round(
                    exchange_totals.get("padded_shuffle_rows", 0)
                    / max(1, exchange_totals["shuffle_rows"]),
                    4,
                )
            stats["exchangeStats"] = exchange_totals
        # ingest rollup: decode/H2D/table-cache counters summed per stage
        ingest_totals: dict = {}
        for entry in stages:
            for k, v in (entry.get("ingest") or {}).items():
                ingest_totals[k] = round(ingest_totals.get(k, 0) + v, 3)
        if ingest_totals:
            stats["ingestStats"] = ingest_totals
        if query_programs:
            from trino_tpu.obs.profiler import rollup_device_stats

            ds = rollup_device_stats(query_programs)
            ds["programs"] = query_programs
            stats["deviceStats"] = ds

    @staticmethod
    def _merge_stage_task_stats(
        entry: dict,
        tasks: list[HttpRemoteTask],
        query_programs: dict[str, dict],
    ) -> None:
        """Merge every FINISHED sibling task's shipped stats (rows, bytes,
        compile, exchange counters, device profiler snapshot —
        ``server/task.py::SqlTask.info``) into one stage entry, and fold
        the per-program device stats into the query-level accumulator.
        Non-FINISHED attempts (failed, speculative losers) are skipped so
        a retried partition counts once."""
        from trino_tpu.obs.profiler import merge_device_stats

        rows = in_rows = out_bytes = in_bytes = 0
        have_rows = have_in = have_bytes = False
        compile_ms = flops = 0.0
        have_flops = have_peak = False
        peak = 0
        exchange: dict = {}
        exchange_caps: dict = {}
        exchange_join: dict = {}
        exchange_ops: dict = {}
        ingest: dict = {}
        for t in tasks:
            st = t.last_status or {}
            if st.get("state") != "FINISHED":
                continue
            ts = st.get("stats") or {}
            if "output_rows" in ts:
                have_rows = True
                rows += int(ts["output_rows"])
            if "input_rows" in ts:
                have_in = True
                in_rows += int(ts["input_rows"])
            if "output_bytes" in ts:
                have_bytes = True
                out_bytes += int(ts["output_bytes"])
            if "input_bytes" in ts:
                in_bytes += int(ts["input_bytes"])
            compile_ms += float((ts.get("compile") or {}).get("compile_ms", 0.0))
            for k, v in (ts.get("exchange") or {}).items():
                # ratios/capacity maps don't sum — recomputed/unioned below
                if k != "padding_ratio" and isinstance(
                    v, (int, float)
                ) and not isinstance(v, bool):
                    exchange[k] = exchange.get(k, 0) + v
            # capacity sites union across sibling tasks (same program,
            # same sites): keep the largest observed value per site so
            # the stage view and the web-UI provenance column reflect
            # the worst-case (final) shape
            for name, ent in ((ts.get("exchange") or {}).get(
                "capacities"
            ) or {}).items():
                if not isinstance(ent, dict):
                    continue
                old = exchange_caps.get(name)
                if old is None or int(ent.get("value", 0) or 0) >= int(
                    old.get("value", 0) or 0
                ):
                    exchange_caps[name] = ent
            # join sites are per-stage unique, same strategy on every
            # sibling task — a plain union is exact
            js = (ts.get("exchange") or {}).get("joinStrategy")
            if isinstance(js, dict):
                exchange_join.update(js)
            # operator row counters sum across sibling tasks: each task
            # saw a disjoint partition of the stage's rows
            for site, ent in ((ts.get("exchange") or {}).get(
                "operators"
            ) or {}).items():
                if not isinstance(ent, dict):
                    continue
                acc = exchange_ops.get(site)
                if acc is None:
                    acc = exchange_ops[site] = {
                        "kind": ent.get("kind", ""),
                        "rows_in": 0,
                        "rows_out": 0,
                    }
                acc["rows_in"] += int(ent.get("rows_in", 0) or 0)
                acc["rows_out"] += int(ent.get("rows_out", 0) or 0)
            for k, v in (ts.get("ingest") or {}).items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    ingest[k] = ingest.get(k, 0) + v
            ds = ts.get("deviceStats") or {}
            merge_device_stats(query_programs, ds.get("programs"))
            if ds.get("total_flops") is not None:
                have_flops = True
                flops += float(ds["total_flops"])
            if ds.get("peak_hbm_bytes") is not None:
                have_peak = True
                peak = max(peak, int(ds["peak_hbm_bytes"]))
        if have_rows:
            entry["rows"] = rows
        if have_in:
            entry["inputRows"] = in_rows
        if have_bytes:
            entry["outputBytes"] = out_bytes
            entry["inputBytes"] = in_bytes
        if compile_ms:
            entry["compileMs"] = round(compile_ms, 3)
        if exchange_caps:
            exchange["capacities"] = exchange_caps
        if exchange_join:
            exchange["joinStrategy"] = exchange_join
        if exchange_ops:
            exchange["operators"] = exchange_ops
        if exchange:
            if exchange.get("shuffle_rows"):
                exchange["padding_ratio"] = round(
                    exchange.get("padded_shuffle_rows", 0)
                    / max(1, exchange["shuffle_rows"]),
                    4,
                )
            entry["exchange"] = exchange
        if ingest:
            entry["ingest"] = ingest
        if have_flops:
            entry["flops"] = flops
        if have_peak:
            entry["peakHbmBytes"] = peak

    # --- root fragment on the coordinator --------------------------------

    def _execute_root(
        self,
        frag: PlanFragment,
        session: Session,
        remote_tasks: dict[int, list[HttpRemoteTask]],
        task_counts: dict[int, int],
        policy: str = "NONE",
        rc: Optional[dict] = None,
    ):
        from trino_tpu.ft.retry import RetryPolicy, TaskFailure
        from trino_tpu.server.task import WorkerExecutor

        root = frag.root
        # A producer can die between the stage barrier and the root pull.
        # With spooling (rc set) heal the lost producers and re-pull; the
        # sources dict is rebuilt each attempt so SpoolHandle / lineage
        # re-execution URIs are picked up automatically.
        attempts = 3 if rc is not None else 1
        batch = names = None
        for attempt in range(attempts):
            sources = {
                fid: {"locations": [t.uri for t in tasks], "partition": 0}
                for fid, tasks in remote_tasks.items()
                if fid in frag.source_fragment_ids
            }
            local_session = Session(
                user=session.user, catalog=session.catalog, schema=session.schema
            )
            for k, v in session.properties.items():
                if k != "execution_mode":
                    local_session.properties[k] = v
            executor = WorkerExecutor(self.engine.catalogs, local_session, {}, sources)
            try:
                if isinstance(root, P.Output):
                    batch, names = executor.execute(root)
                else:
                    res = executor._exec(root)
                    batch = res.batch.compact()
                    names = [s.name for s in root.output_symbols]
                break
            except Exception as e:  # noqa: BLE001
                if rc is not None and attempt < attempts - 1:
                    if self._heal_sources(frag, rc, probe=True):
                        continue
                # the coordinator-side symptom (empty exchange, timeout) is
                # usually downstream of a worker task failure — surface the
                # root cause with the worker's retryable classification
                failed = self._first_failed_status(remote_tasks)
                if failed is not None:
                    t, st = failed
                    raise TaskFailure(
                        st.get("taskId") or t.task_id,
                        t.node.node_id,
                        st.get("error"),
                        retryable=bool(st.get("retryable", True)),
                    ) from e
                raise
        # surface any worker failure even if results looked complete; the
        # TASK stage barrier already verified every producer FINISHED
        if policy != RetryPolicy.TASK:
            failed = self._first_failed_status(remote_tasks)
            if failed is not None:
                t, st = failed
                raise TaskFailure(
                    st.get("taskId") or t.task_id,
                    t.node.node_id,
                    st.get("error"),
                    retryable=bool(st.get("retryable", True)),
                )
        return batch, names

    @staticmethod
    def _first_failed_status(
        remote_tasks: dict[int, list[HttpRemoteTask]],
    ) -> Optional[tuple[HttpRemoteTask, dict]]:
        for tasks in remote_tasks.values():
            for t in tasks:
                try:
                    st = t.status()
                except Exception:  # noqa: BLE001 - unreachable worker
                    continue
                if st.get("state") == "FAILED":
                    return t, st
        return None
