"""Coordinator server: HTTP client protocol, query management, dispatch.

Reference layers L7-L9 (SURVEY.md §1): ``core/trino-main/.../server/``,
``.../dispatcher/``, ``.../execution/`` (QueryManager / state machines).
"""
