"""Internal cluster authentication: shared-secret bearer token.

Reference: ``server/InternalAuthenticationManager.java`` +
``InternalCommunicationConfig.java:49`` — coordinator/worker RPC carries a
shared-secret credential so task, announce, discovery, and SPMD endpoints
reject outside callers. The secret rides the ``TRINO_TPU_INTERNAL_SECRET``
environment variable (every process of one cluster shares it); with no
secret configured, auth is disabled (single-process/dev mode).
"""

from __future__ import annotations

import os

ENV_VAR = "TRINO_TPU_INTERNAL_SECRET"

#: request paths that are cluster-internal (prefix match)
INTERNAL_PREFIXES = (
    "/v1/task", "/v1/announce", "/v1/spmd", "/v1/discovery", "/v1/write",
    "/v1/spool",
)


def secret() -> str | None:
    return os.environ.get(ENV_VAR) or None


def headers() -> dict[str, str]:
    s = secret()
    return {"Authorization": f"Bearer {s}"} if s else {}


def is_internal_path(path: str) -> bool:
    return any(path.startswith(p) for p in INTERNAL_PREFIXES)


def authorized(request_headers) -> bool:
    import hmac

    s = secret()
    if s is None:
        return True
    provided = request_headers.get("Authorization") or ""
    return hmac.compare_digest(provided, f"Bearer {s}")
